"""
Segmented (stateful-scan) LSTM fleet training
(models/training.py build_raw_segmented_fit_fn, opted in via
GORDO_TPU_LSTM_SEGMENTED):

- at segments_per_update == batch_size (segment length 1) every window
  starts cold, so the path must match the window-restart path exactly;
- at smaller segment counts the warm-state approximation must still
  train to comparable quality on the serving (cold-window) metric;
- masking: bucket padding windows must not affect training.
"""

import numpy as np
import pytest

from gordo_tpu.models.factories import lstm_model
from gordo_tpu.models.training import FitConfig
from gordo_tpu.ops.windows import window_targets
from gordo_tpu.parallel import FleetTrainer, WindowedFleetMember

#: segmented-scan LSTM fleet compiles are multi-minute on CPU hosts:
#: runs in the dedicated `parallel` CI job, outside the tier-1 budget.
pytestmark = pytest.mark.slow

LOOKBACK = 8
TAGS = 3


def _members(n_rows, n_members, lookahead=0, n_rows_other=None):
    spec = lstm_model(TAGS, lookback_window=LOOKBACK)
    members = []
    for i in range(n_members):
        rows = n_rows if n_rows_other is None or i % 2 == 0 else n_rows_other
        X = np.random.RandomState(i).rand(rows, TAGS).astype(np.float32)
        members.append(
            WindowedFleetMember(
                name=f"m{i}",
                spec=spec,
                series=X,
                targets=window_targets(X, LOOKBACK, lookahead),
                seed=i,
            )
        )
    return members


def _train(members, config, segments, monkeypatch):
    if segments:
        monkeypatch.setenv("GORDO_TPU_LSTM_SEGMENTED", str(segments))
    else:
        monkeypatch.delenv("GORDO_TPU_LSTM_SEGMENTED", raising=False)
    return FleetTrainer().train(members, config)


@pytest.mark.parametrize("lookahead", [0, 1])
def test_single_window_segments_match_windowed_exactly(lookahead, monkeypatch):
    """L=1 segments are cold windows in the same batch order — identical."""
    config = FitConfig(epochs=3, batch_size=16, shuffle=False)
    windowed = _train(_members(70, 2, lookahead), config, None, monkeypatch)
    segmented = _train(_members(70, 2, lookahead), config, 16, monkeypatch)
    for w, s in zip(windowed, segmented):
        np.testing.assert_allclose(
            s.history.history["loss"], w.history.history["loss"], rtol=1e-5
        )
        np.testing.assert_allclose(
            np.concatenate(
                [p.ravel() for p in jax_leaves(s.params)]
            ),
            np.concatenate([p.ravel() for p in jax_leaves(w.params)]),
            rtol=1e-4,
            atol=1e-6,
        )


def jax_leaves(tree):
    import jax

    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(tree)]


def test_segmented_trains_to_comparable_quality(monkeypatch):
    """Warm-state training must still fit the cold-window serving task:
    compare final reconstruction error over cold windows."""
    from gordo_tpu.ops.windows import sliding_windows
    from gordo_tpu.parallel.fleet import (
        fleet_windowed_predict_program,
        stack_member_params,
    )

    config = FitConfig(epochs=20, batch_size=16, shuffle=False)
    windowed = _train(_members(140, 1), config, None, monkeypatch)
    segmented = _train(_members(140, 1), config, 4, monkeypatch)

    def cold_mse(result):
        member = _members(140, 1)[0]
        spec = member.spec
        nv = member.n_windows - member.n_windows % config.batch_size
        order = np.arange(nv, dtype=np.int32)
        params = stack_member_params([result])
        outs = np.asarray(
            fleet_windowed_predict_program(spec, config.batch_size)(
                params, member.series[None], order[None]
            )
        )[0]
        return float(np.mean((outs - member.targets[:nv]) ** 2))

    mse_windowed, mse_segmented = cold_mse(windowed[0]), cold_mse(segmented[0])
    # warm-state training may be slightly better or worse on the cold
    # metric; it must be in the same regime, not diverged
    assert mse_segmented < max(2.5 * mse_windowed, 0.02), (
        mse_segmented,
        mse_windowed,
    )


def test_segmented_ignores_bucket_padding(monkeypatch):
    """A short member padded inside a longer bucket must train the same
    as it does alone (padding windows carry zero weight)."""
    # 46 and 60 rows both round up to a 64-row bucket with the same
    # offset, so the short member trains padded inside the shared bucket
    config = FitConfig(epochs=2, batch_size=8, shuffle=False)
    alone = _train(_members(46, 1), config, 4, monkeypatch)
    mixed = _train(
        _members(46, 2, n_rows_other=60), config, 4, monkeypatch
    )
    np.testing.assert_allclose(
        mixed[0].history.history["loss"],
        alone[0].history.history["loss"],
        rtol=1e-4,
    )


def test_segmented_falls_back_when_shuffled(monkeypatch):
    """shuffle=True cannot use consecutive segments; the trainer must
    quietly run the window-restart path instead of failing."""
    config = FitConfig(epochs=1, batch_size=16, shuffle=True)
    results = _train(_members(70, 1), config, 4, monkeypatch)
    assert np.isfinite(results[0].history.history["loss"][-1])
