"""On-device windowing parity: WindowedFleetMember (raw series resident,
windows gathered per batch) must train exactly like the dense path on
pre-materialized windows."""

import jax
import numpy as np
import pytest

from gordo_tpu.models.factories import lstm_model
from gordo_tpu.models.training import FitConfig
from gordo_tpu.ops.windows import sliding_windows, window_targets
from gordo_tpu.parallel import FleetMember, FleetTrainer, WindowedFleetMember
from gordo_tpu.parallel.fleet import (
    fleet_windowed_predict_program,
    stack_member_params,
)

#: LSTM fleet compiles are multi-minute on CPU hosts: this suite runs
#: in the dedicated `parallel` CI job (scripts/tests.sh), outside the
#: sub-15-minute tier-1 `-m 'not slow'` budget.
pytestmark = pytest.mark.slow

LOOKBACK = 8


def _series(n, f, seed):
    return np.random.RandomState(seed).rand(n, f).astype(np.float32)


def _members(n_rows, n_members, lookahead=0, order=None):
    spec = lstm_model(3, lookback_window=LOOKBACK)
    dense, windowed = [], []
    for i in range(n_members):
        X = _series(n_rows, 3, seed=i)
        wins = sliding_windows(X, LOOKBACK, lookahead)
        tgts = window_targets(X, LOOKBACK, lookahead)
        virt = wins if order is None else wins[order]
        virt_t = tgts if order is None else tgts[order]
        dense.append(
            FleetMember(name=f"m{i}", spec=spec, X=np.ascontiguousarray(virt),
                        y=np.ascontiguousarray(virt_t), seed=i)
        )
        windowed.append(
            WindowedFleetMember(
                name=f"m{i}", spec=spec, series=X, targets=tgts,
                order=order, seed=i,
            )
        )
    return spec, dense, windowed


@pytest.mark.parametrize("lookahead", [0, 1])
def test_windowed_matches_dense_no_shuffle(lookahead):
    spec, dense, windowed = _members(70, 2, lookahead=lookahead)
    config = FitConfig(epochs=3, batch_size=16, validation_split=0.25, shuffle=False)
    trainer = FleetTrainer()
    dense_res = trainer.train(dense, config)
    win_res = trainer.train(windowed, config)
    for d, w in zip(dense_res, win_res):
        np.testing.assert_allclose(
            w.history.history["loss"], d.history.history["loss"], rtol=1e-5
        )
        assert ("val_loss" in d.history.history) == ("val_loss" in w.history.history)
        if "val_loss" in d.history.history:
            np.testing.assert_allclose(
                w.history.history["val_loss"], d.history.history["val_loss"], rtol=1e-4
            )
        for a, b in zip(
            jax.tree_util.tree_leaves(d.params), jax.tree_util.tree_leaves(w.params)
        ):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-6)


def test_windowed_with_order_permutation():
    rng = np.random.RandomState(0)
    # lookahead=0 -> n_windows = 70 - 8 + 1 = 63
    order = rng.permutation(63).astype(np.int32)
    spec, dense, windowed = _members(70, 1, order=order)
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)
    trainer = FleetTrainer()
    dense_res = trainer.train(dense, config)
    win_res = trainer.train(windowed, config)
    np.testing.assert_allclose(
        win_res[0].history.history["loss"],
        dense_res[0].history.history["loss"],
        rtol=1e-5,
    )


def test_windowed_shuffle_trains_finite():
    spec, _, windowed = _members(70, 2)
    config = FitConfig(epochs=3, batch_size=16, shuffle=True)
    results = FleetTrainer().train(windowed, config)
    for r in results:
        assert np.all(np.isfinite(r.history.history["loss"]))
        assert len(r.history.history["loss"]) == 3


def test_windowed_mixed_with_dense_members():
    spec, dense, windowed = _members(70, 2)
    # same names would collide; rename the dense ones
    for i, m in enumerate(dense):
        m.name = f"d{i}"
    config = FitConfig(epochs=1, batch_size=16, shuffle=False)
    results = FleetTrainer().train(dense + windowed, config)
    assert [r.name for r in results] == ["d0", "d1", "m0", "m1"]


def test_windowed_predict_program_matches_dense():
    spec, dense, windowed = _members(70, 2)
    config = FitConfig(epochs=1, batch_size=16, shuffle=False)
    trainer = FleetTrainer()
    results = trainer.train(windowed, config)
    stacked = stack_member_params(results)

    batch = 16
    nv = windowed[0].n_windows
    nv_pad = -(-nv // batch) * batch
    order = np.zeros((2, nv_pad), np.int32)
    order[:, :nv] = np.arange(nv)
    series = np.stack([m.series for m in windowed])
    out = np.asarray(
        fleet_windowed_predict_program(spec, batch)(stacked, series, order)
    )[:, :nv]

    expected = trainer.predict_bucket(
        spec, stacked, np.stack([sliding_windows(m.series, LOOKBACK) for m in windowed])
    )
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_windowed_too_short_series_raises():
    spec = lstm_model(3, lookback_window=LOOKBACK)
    with pytest.raises(ValueError, match="too short"):
        WindowedFleetMember(
            name="x", spec=spec, series=_series(5, 3, 0),
            targets=np.zeros((0, 3), np.float32),
        )
