"""
The driver contract of bench.py: stage subprocesses write JSON results,
and a full run prints exactly ONE JSON line and exits 0 — regardless of
backend health. Runs tiny and CPU-forced.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")

pytestmark = pytest.mark.slow

TINY_ENV = {
    "BENCH_MODELS": "6",
    "BENCH_E2E_MODELS": "2",
    "BENCH_EPOCHS": "2",
    "BENCH_SAMPLES": "128",
    "BENCH_TAGS": "4",
    "BENCH_LSTM_MODELS": "2",
    "BENCH_LSTM_TAGS": "4",
    "BENCH_LSTM_LOOKBACK": "8",
    "BENCH_LSTM_EPOCHS": "1",
    "BENCH_FORCE_CPU": "1",
    "BENCH_STAGE_TIMEOUT": "300",
    # the TF-vs-JAX parity stage has its own dedicated test
    # (tests/models/test_parity_tf.py); at harness-test sizes it would
    # just burn minutes of TF training
    "BENCH_SKIP_PARITY": "1",
}


def test_stage_subprocess_writes_json(tmp_path):
    out = tmp_path / "probe.json"
    env = {**os.environ, **TINY_ENV}
    proc = subprocess.run(
        [sys.executable, BENCH, "--stage", "backend_probe", str(out)],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(out.read_text())
    assert "cpu" in payload["device"]
    assert payload["checksum"] == 28.0  # arange(8).sum() — transfer-only probe


def test_full_run_emits_one_json_line_rc0(tmp_path):
    env = {
        **os.environ,
        **TINY_ENV,
        "BENCH_SKIP_E2E": "1",
        "BENCH_PACKING": "0",
        "BENCH_PARTIAL_PATH": str(tmp_path / "partial.json"),
    }
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=580,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # stdout carries exactly one line, and it is the JSON record
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(lines) == 1, lines
    record = json.loads(lines[0])
    assert record["metric"] == "autoencoders_trained_per_hour"
    assert record["unit"] == "models/hour"
    assert record["value"] and record["value"] > 0
    # the partial artifact survived with the per-stage results
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert "fleet_train" in partial and "result" in partial


def test_failing_stage_yields_partial_artifact(tmp_path):
    """An impossible stage timeout must not zero the run silently: the
    partial artifact records the failure and rc is non-zero only because
    NOTHING produced a usable number."""
    env = {
        **os.environ,
        **TINY_ENV,
        "BENCH_SKIP_E2E": "1",
        # the 1s stage timeout kills every stage subprocess (including
        # the TF baseline — its repo-root cache fallback contributes no
        # headline, so the run still ends with a null value)
        "BENCH_STAGE_TIMEOUT": "1",
        "BENCH_PARTIAL_PATH": str(tmp_path / "partial.json"),
    }
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
        cwd=str(tmp_path),  # keep any stray baseline cache out of the repo
    )
    partial = json.loads((tmp_path / "partial.json").read_text())
    errors = [k for k in partial if k.endswith("_error")]
    assert errors, partial
    # the final JSON line still printed (value null) — the driver sees a
    # parseable record either way
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert json.loads(lines[-1])["metric"] == "autoencoders_trained_per_hour"
    # rc is non-zero: nothing produced a usable number
    assert proc.returncode != 0
