import json

import pytest

from gordo_tpu.models.spec import FeedForwardSpec, LSTMSpec
from gordo_tpu.planner import costmodel
from gordo_tpu.planner.costmodel import (
    CostModel,
    CostTable,
    calibrate,
    spec_flops_per_sample,
    spec_param_count,
)

pytestmark = pytest.mark.planner

FF = FeedForwardSpec(
    n_features=3, n_features_out=3, dims=(6, 3), activations=("tanh", "tanh")
)
LSTM = LSTMSpec(
    n_features=2,
    n_features_out=2,
    lookback_window=4,
    dims=(4,),
    activations=("tanh",),
)


def test_spec_param_count_feedforward():
    # 3->6->3->3 dense chain: (3*6+6) + (6*3+3) + (3*3+3)
    assert spec_param_count(FF) == 24 + 21 + 12


def test_spec_param_count_lstm():
    # one LSTM layer (4 gates of [2+4, 4] + bias) + dense head 4->2
    assert spec_param_count(LSTM) == 4 * (2 * 4 + 4 * 4 + 4) + (4 * 2 + 2)


def test_spec_flops_scale_with_lookback():
    longer = LSTMSpec(
        n_features=2,
        n_features_out=2,
        lookback_window=8,
        dims=(4,),
        activations=("tanh",),
    )
    assert spec_flops_per_sample(longer) > 1.9 * spec_flops_per_sample(LSTM)


def test_cost_table_round_trip(tmp_path):
    table = CostTable(
        run_factors={"fleet_fit": 1.5}, compile_factors={"fleet_fit": 0.8},
        samples={"fleet_fit": 12},
    )
    path = str(tmp_path / "cost_table.json")
    table.save(path)
    loaded = CostTable.load(path)
    assert loaded.to_dict() == table.to_dict()
    assert loaded.calibrated


def test_cost_table_rejects_wrong_version(tmp_path):
    path = tmp_path / "cost_table.json"
    path.write_text(json.dumps({"version": 99}))
    with pytest.raises(ValueError, match="version"):
        CostTable.load(str(path))


def test_stacked_shape_mesh_rounding():
    model = CostModel(mesh_shape=(4, 2))
    m_total, n_total = model.stacked_shape(m=5, n_padded=100, batch_size=16)
    assert m_total == 8  # multiple of the model axis
    assert n_total % 16 == 0 and n_total % 2 == 0 and n_total >= 100


def test_predict_hbm_monotonic():
    model = CostModel()
    small = model.predict_hbm_bytes(FF, 4, 128, 16)
    bigger_fleet = model.predict_hbm_bytes(FF, 8, 128, 16)
    more_samples = model.predict_hbm_bytes(FF, 4, 512, 16)
    assert bigger_fleet > small
    assert more_samples > small


def test_predict_run_scales_with_work():
    model = CostModel()
    base = model.predict_run_s("fleet_fit", FF, 4, 128, epochs=2)
    doubled = model.predict_run_s("fleet_fit", FF, 8, 128, epochs=2)
    assert doubled > base


def _span(program, seconds, m, n, compile=False, **extra):
    attrs = {
        "program": program,
        "flops_per_sample": spec_flops_per_sample(FF),
        "stacked_members": m,
        "stacked_samples": n,
        "epochs": 2,
    }
    if compile:
        attrs["compile"] = True
    attrs.update(extra)
    return {
        "name": "device_program",
        "duration_ms": seconds * 1000.0,
        "attributes": attrs,
    }


def test_calibrate_fits_median_run_factors(tmp_path):
    """The factor is the MEDIAN actual/analytic ratio, robust to one
    neighbor-stall outlier."""
    base = CostTable()
    m, n = 4, 128
    flops = costmodel._TRAIN_FLOP_FACTOR * spec_flops_per_sample(FF) * m * n * 2
    analytic = flops / base.throughput + base.dispatch_s
    spans = [
        _span("fleet_fit", 2.0 * analytic, m, n),
        _span("fleet_fit", 2.0 * analytic, m, n),
        _span("fleet_fit", 50.0 * analytic, m, n),  # host-noise outlier
    ]
    trace = tmp_path / "build_trace.jsonl"
    trace.write_text("\n".join(json.dumps(s) for s in spans) + "\n")
    table = calibrate(str(trace))
    assert table.run_factors["fleet_fit"] == pytest.approx(2.0, rel=1e-3)
    assert table.samples["fleet_fit"] == 3
    assert table.calibrated


def test_calibrate_separates_compile_spans(tmp_path):
    spans = [
        _span("fleet_fit", 5.0, 4, 128, compile=True),
        _span("fleet_fit", 0.1, 4, 128),
    ]
    trace = tmp_path / "build_trace.jsonl"
    trace.write_text("\n".join(json.dumps(s) for s in spans) + "\n")
    table = calibrate(str(trace))
    assert "fleet_fit" in table.compile_factors
    assert "fleet_fit" in table.run_factors
    assert table.compile_factors["fleet_fit"] > 0


def test_calibrate_skips_unusable_lines(tmp_path):
    """Old traces (no static features), foreign spans and torn tails
    must not break calibration."""
    trace = tmp_path / "build_trace.jsonl"
    lines = [
        json.dumps({"name": "build_phase", "duration_ms": 5.0}),
        json.dumps(
            {
                "name": "device_program",
                "duration_ms": 100.0,
                "attributes": {"program": "fleet_fit"},  # pre-planner span
            }
        ),
        json.dumps(_span("fleet_fit", 0.5, 4, 128)),
        '{"torn": tail',  # killed build's partial line
    ]
    trace.write_text("\n".join(lines) + "\n")
    table = calibrate(str(trace))
    assert table.samples == {"fleet_fit": 1}


# -- the precision axis (PR 14) ----------------------------------------------


@pytest.mark.precision
def test_precision_factor_scales_predicted_run():
    model = CostModel()
    f32 = model.predict_run_s("fleet_fit", FF, 4, 1024, 10, precision="f32")
    bf16 = model.predict_run_s("fleet_fit", FF, 4, 1024, 10, precision="bf16")
    # the per-precision factor multiplies the FLOP share, not dispatch
    dispatch = model.table.dispatch_s
    assert bf16 < f32
    assert (bf16 - dispatch) == pytest.approx(0.6 * (f32 - dispatch))
    # precision defaults to the spec's compute_dtype
    bf16_spec = FeedForwardSpec(
        n_features=3,
        n_features_out=3,
        dims=(6, 3),
        activations=("tanh", "tanh"),
        compute_dtype="bfloat16",
    )
    assert model.predict_run_s("fleet_fit", bf16_spec, 4, 1024, 10) == bf16


@pytest.mark.precision
def test_serve_weight_bytes_halve_and_quarter():
    model = CostModel()
    f32 = model.serve_weight_bytes(FF, 8, "f32")
    bf16 = model.serve_weight_bytes(FF, 8, "bf16")
    int8 = model.serve_weight_bytes(FF, 8, "int8")
    assert f32 == 4 * spec_param_count(FF) * 8
    assert bf16 == f32 // 2
    # int8 quarters the matrices but pays f32 per-channel scales
    scales = 4 * 8 * sum(FF.dims + (FF.n_features_out,))
    assert int8 == spec_param_count(FF) * 8 + scales
    assert int8 < bf16


@pytest.mark.precision
def test_serve_hbm_and_step_predictions_carry_precision():
    model = CostModel()
    hbm_f32 = model.predict_serve_hbm_bytes(FF, 8, 128, "f32")
    hbm_bf16 = model.predict_serve_hbm_bytes(FF, 8, 128, "bf16")
    assert hbm_bf16 < hbm_f32
    step_f32 = model.predict_serve_step_s(FF, 8, 128, "f32")
    step_bf16 = model.predict_serve_step_s(FF, 8, 128, "bf16")
    assert 0 < step_bf16 < step_f32


@pytest.mark.precision
def test_hbm_precision_changes_bin_packing_caps():
    """bf16 compute halves the activation bytes, so a cap that forces an
    f32 bucket to split can hold the bf16-compute twin whole — the
    packer's HBM item weights genuinely move with the precision axis."""
    model = CostModel()
    wide = FeedForwardSpec(
        n_features=64,
        n_features_out=64,
        dims=(512, 512),
        activations=("tanh", "tanh"),
    )
    wide_bf16 = FeedForwardSpec(
        n_features=64,
        n_features_out=64,
        dims=(512, 512),
        activations=("tanh", "tanh"),
        compute_dtype="bfloat16",
    )
    f32_bytes = model.predict_hbm_bytes(wide, 4, 4096, 4096)
    bf16_bytes = model.predict_hbm_bytes(wide_bf16, 4, 4096, 4096)
    assert bf16_bytes < f32_bytes
    # a cap between the two: the f32 bucket overflows, the bf16 fits
    cap = (f32_bytes + bf16_bytes) // 2
    assert f32_bytes > cap >= bf16_bytes


@pytest.mark.precision
def test_cost_table_round_trips_precision_factors(tmp_path):
    table = CostTable(precision_factors={"bf16": 0.5, "int8": 0.4})
    path = str(tmp_path / "cost_table.json")
    table.save(path)
    loaded = CostTable.load(path)
    assert loaded.precision_factors == {"bf16": 0.5, "int8": 0.4}
    assert loaded.precision_factor("bf16") == 0.5
    assert loaded.precision_factor("f32") == 1.0
    assert loaded.precision_factor("bfloat16") == 0.5  # alias-normalized
    # a pre-precision table (no key) loads with the analytic defaults
    doc = table.to_dict()
    del doc["precision_factors"]
    legacy = CostTable.from_dict(doc)
    assert legacy.precision_factor("bf16") == 0.6
