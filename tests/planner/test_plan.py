from types import SimpleNamespace

import pytest

from gordo_tpu.models.spec import FeedForwardSpec
from gordo_tpu.planner.costmodel import CostModel, CostTable
from gordo_tpu.planner.packing import PACKED, plan_train_buckets
from gordo_tpu.planner.plan import (
    FleetPlan,
    PlanError,
    build_plan_doc,
    config_fingerprint,
)
from gordo_tpu.planner.report import render_plan

pytestmark = pytest.mark.planner

SPEC = FeedForwardSpec(
    n_features=3, n_features_out=3, dims=(6, 3), activations=("tanh", "tanh")
)
CONFIG = SimpleNamespace(
    epochs=2,
    batch_size=16,
    validation_split=0.1,
    shuffle=False,
    early_stopping=None,
)


def dense(name, n):
    return SimpleNamespace(name=name, spec=SPEC, n=n)


def make_plan(members=None, table=None):
    members = members or [dense("a", 50), dense("b", 120), dense("c", 700)]
    cost_model = CostModel(table)
    buckets = plan_train_buckets(
        members, CONFIG, strategy=PACKED, cost_model=cost_model
    )
    return build_plan_doc(
        [(CONFIG, buckets)],
        PACKED,
        cost_model.mesh_shape,
        cost_model.table,
        config_fingerprint(["k1", "k2", "k3"]),
    )


def test_plan_is_byte_deterministic():
    """Same configs + cost table => byte-identical JSON and equal hash —
    the identity the journal records and --resume trusts."""
    assert make_plan().to_json() == make_plan().to_json()
    assert make_plan().plan_hash == make_plan().plan_hash


def test_plan_hash_tracks_cost_table():
    calibrated = CostTable(run_factors={"fleet_fit": 3.0})
    assert make_plan().to_json() != make_plan(table=calibrated).to_json()


def test_plan_save_load_round_trip(tmp_path):
    plan = make_plan()
    path = str(tmp_path / "fleet_plan.json")
    plan.save(path)
    loaded = FleetPlan.load(path)
    assert loaded.to_json() == plan.to_json()
    assert loaded.plan_hash == plan.plan_hash
    assert loaded.strategy == PACKED


def test_plan_rejects_bad_documents(tmp_path):
    bad_version = tmp_path / "v.json"
    bad_version.write_text('{"version": 99, "buckets": []}')
    with pytest.raises(PlanError, match="version"):
        FleetPlan.load(str(bad_version))
    torn = tmp_path / "torn.json"
    torn.write_text('{"version": 1')
    with pytest.raises(PlanError, match="unreadable"):
        FleetPlan.load(str(torn))


def test_materialize_keeps_pad_targets_for_subsets():
    """After --resume removed neighbors, a member keeps its planned
    bucket and pad target — its padded shape (and numerics) never depend
    on which other members still build."""
    plan = make_plan()
    full, uncovered = plan.materialize_buckets(
        [dense("a", 50), dense("b", 120), dense("c", 700)]
    )
    assert uncovered == []
    subset, uncovered = plan.materialize_buckets([dense("b", 120)])
    assert uncovered == []
    assert len(subset) == 1
    original = next(
        b for b in full if "b" in b.member_names
    )
    assert subset[0].n_padded == original.n_padded
    assert subset[0].bucket_id == original.bucket_id


def test_materialize_routes_unknown_and_outgrown_members_live():
    plan = make_plan()
    unknown = dense("new-machine", 64)
    outgrown = dense("a", 10_000)  # data grew past the planned pad target
    buckets, uncovered = plan.materialize_buckets([unknown, outgrown])
    assert buckets == []
    assert {m.name for m in uncovered} == {"new-machine", "a"}


def test_materialize_routes_spec_drifted_members_live():
    """A machine whose architecture was edited since planning keeps its
    name but must NOT land in its old bucket — it would train under the
    wrong program (or drag its unchanged neighbors onto the new one)."""
    plan = make_plan()
    drifted_spec = FeedForwardSpec(
        n_features=3, n_features_out=3, dims=(9, 4), activations=("tanh", "tanh")
    )
    drifted = SimpleNamespace(name="a", spec=drifted_spec, n=50)
    buckets, uncovered = plan.materialize_buckets(
        [drifted, dense("b", 120), dense("c", 700)]
    )
    assert [m.name for m in uncovered] == ["a"]
    assert all("a" not in b.member_names for b in buckets)
    assert {n for b in buckets for n in b.member_names} == {"b", "c"}


def test_config_fingerprint_is_order_insensitive():
    assert config_fingerprint(["x", "y"]) == config_fingerprint(["y", "x"])
    assert config_fingerprint(["x"]) != config_fingerprint(["y"])


def test_render_plan_mentions_every_bucket():
    plan = make_plan()
    text = render_plan(plan)
    for bucket in plan.buckets:
        assert bucket["id"] in text
    assert plan.plan_hash in text
    assert "padding_waste" in text
