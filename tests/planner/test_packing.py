from types import SimpleNamespace

import pytest

from gordo_tpu.models.spec import FeedForwardSpec, LSTMSpec
from gordo_tpu.planner.costmodel import CostModel
from gordo_tpu.planner.packing import (
    NAIVE,
    PACKED,
    _round_up_pow2,
    annotate_predictions,
    naive_pad_target,
    plan_train_buckets,
)

pytestmark = pytest.mark.planner

SPEC = FeedForwardSpec(
    n_features=3, n_features_out=3, dims=(6, 3), activations=("tanh", "tanh")
)
OTHER_SPEC = FeedForwardSpec(
    n_features=5, n_features_out=5, dims=(8, 4), activations=("tanh", "tanh")
)
LSTM = LSTMSpec(
    n_features=2,
    n_features_out=2,
    lookback_window=4,
    dims=(4,),
    activations=("tanh",),
)

CONFIG = SimpleNamespace(epochs=2, batch_size=16)


def dense(name, n, spec=SPEC):
    return SimpleNamespace(name=name, spec=spec, n=n)


def windowed(name, length, spec=LSTM):
    return SimpleNamespace(
        name=name,
        spec=spec,
        series=[0.0] * length,
        n_windows=length - spec.lookback_window + 1,
    )


def test_naive_matches_historical_grouping():
    """The naive strategy is the trainer's exact-key grouping: one
    bucket per (spec, pow2 pad), members in input order."""
    members = [
        dense("a", 70),
        dense("b", 100),  # 70 and 100 both pad to 128
        dense("c", 100, OTHER_SPEC),
        dense("d", 300),  # pads to 512
    ]
    buckets = plan_train_buckets(members, CONFIG, strategy=NAIVE)
    rosters = {tuple(b.member_names): b for b in buckets}
    assert set(rosters) == {("a", "b"), ("c",), ("d",)}
    assert rosters[("a", "b")].n_padded == _round_up_pow2(100, 16)
    assert rosters[("d",)].n_padded == _round_up_pow2(300, 16)


def test_naive_windowed_uses_geometric_series_ladder():
    """The pow2 time-axis fix (satellite): naive windowed members pad up
    the shared geometric ladder, not to the next power of two."""
    from gordo_tpu.planner.ladder import round_up_ladder, series_pad_ratio

    member = windowed("w", 1100)
    assert naive_pad_target(member, CONFIG.batch_size) == round_up_ladder(
        1100, series_pad_ratio()
    )
    assert naive_pad_target(member, CONFIG.batch_size) < 2048  # the old pow2


def test_packed_merges_rungs_under_break_even():
    """Small same-spec members with scattered sample counts are one
    bucket under packed (padding a few rows is cheaper than a compile),
    where naive mints one bucket per pow2 key."""
    members = [dense(f"m{i}", 40 + 17 * i) for i in range(6)]  # 40..125
    naive = plan_train_buckets(members, CONFIG, strategy=NAIVE)
    packed = plan_train_buckets(members, CONFIG, strategy=PACKED)
    assert len(packed) < len(naive) or len(naive) == 1
    assert sorted(n for b in packed for n in b.member_names) == sorted(
        m.name for m in members
    )


def test_packed_never_mixes_specs():
    members = [dense("a", 64), dense("b", 64, OTHER_SPEC)]
    packed = plan_train_buckets(members, CONFIG, strategy=PACKED)
    assert len(packed) == 2
    for bucket in packed:
        specs = {m.spec for m in bucket.members}
        assert len(specs) == 1


def test_packed_compile_budget_forces_merges():
    """An explicit budget keeps merging past break-even until the
    program count fits."""
    members = [dense(f"m{i}", 100 * (i + 1)) for i in range(8)]  # 100..800
    unbudgeted = plan_train_buckets(
        members, CONFIG, strategy=PACKED, budget=0, hbm_cap=1 << 40
    )
    capped = plan_train_buckets(
        members, CONFIG, strategy=PACKED, budget=1, hbm_cap=1 << 40
    )
    assert len(capped) == 1
    assert len(capped) <= len(unbudgeted)


def test_packed_hbm_cap_splits_before_oom():
    """A tiny cap splits a rung group into several bins, each under the
    cap, padded to one shared member rung so they share a compile."""
    cost_model = CostModel()
    members = [dense(f"m{i}", 128) for i in range(9)]
    per_member = cost_model.predict_hbm_bytes(SPEC, 1, 128, CONFIG.batch_size)
    cap = int(3.5 * per_member)  # 3 members per bin
    buckets = plan_train_buckets(
        members, CONFIG, strategy=PACKED, cost_model=cost_model, hbm_cap=cap
    )
    assert len(buckets) == 3
    for bucket in buckets:
        assert bucket.predicted["hbm_bytes"] <= cap * 2  # padded members
        assert bucket.m_padded == 4  # shared pow2 rung over max bin size
    # sibling bins share ONE compile: identical padded signature
    assert sum(b.predicted["compiles"] for b in buckets) == 1


def test_packed_deterministic_and_order_stable():
    members = [dense(f"m{i}", 40 + 13 * i) for i in range(10)]
    first = plan_train_buckets(members, CONFIG, strategy=PACKED)
    second = plan_train_buckets(members, CONFIG, strategy=PACKED)
    assert [(b.bucket_id, b.member_names) for b in first] == [
        (b.bucket_id, b.member_names) for b in second
    ]
    # members inside a bucket stay in fleet input order
    order = {f"m{i}": i for i in range(10)}
    for bucket in first:
        positions = [order[n] for n in bucket.member_names]
        assert positions == sorted(positions)


def test_annotate_predictions_attributes_compiles_once():
    """Two buckets with the same padded signature cost one compile —
    mirroring the telemetry's first-call-per-signature attribution."""
    buckets = plan_train_buckets(
        [dense("a", 100), dense("b", 700)], CONFIG, strategy=NAIVE
    )
    for b in buckets:
        b.n_padded = 1024  # force an identical signature
    annotate_predictions(buckets, CONFIG, CostModel())
    assert sorted(b.predicted["compiles"] for b in buckets) == [0, 1]


def test_predictions_account_padding_waste():
    buckets = plan_train_buckets([dense("a", 65)], CONFIG, strategy=NAIVE)
    predicted = buckets[0].predicted
    assert predicted["flops_padded"] > predicted["flops_true"]
    assert 0.0 < predicted["padding_waste"] < 1.0
    assert predicted["stacked_shape"][1] == 128


def test_profitable_merge_not_masked_by_cheap_unprofitable_one():
    """The greedy must pick the largest NET win across all families: a
    family whose cheapest-padding merge is unprofitable (tiny compile
    save) must not stop a big-save merge in another family."""
    from gordo_tpu.planner.costmodel import CostTable

    # dense merges save almost nothing; windowed compiles are precious
    table = CostTable(
        compile_factors={"fleet_fit": 1e-6, "fleet_windowed_fit": 100.0}
    )
    members = [
        dense("a1", 100),
        dense("a2", 200),
        windowed("w1", 100),
        windowed("w2", 200),
    ]
    buckets = plan_train_buckets(
        members,
        CONFIG,
        strategy=PACKED,
        cost_model=CostModel(table),
        hbm_cap=1 << 40,
    )
    rosters = {tuple(b.member_names) for b in buckets}
    # the windowed family merged (its compile save dwarfs the padding),
    # the dense family did not (its compile save is ~free to re-pay)
    assert ("w1", "w2") in rosters
    assert ("a1",) in rosters and ("a2",) in rosters


def test_bucket_ids_distinct_across_fit_configs():
    """Two fit-config groups sharing a spec and rung must NOT collide on
    bucket id — materialize_buckets keys rosters by id, and a collision
    would train the pooled members twice."""
    from gordo_tpu.planner.plan import build_plan_doc, config_fingerprint

    other_config = SimpleNamespace(
        epochs=9,
        batch_size=16,
        validation_split=None,
        shuffle=False,
        early_stopping=None,
    )
    base_config = SimpleNamespace(
        epochs=2,
        batch_size=16,
        validation_split=None,
        shuffle=False,
        early_stopping=None,
    )
    member_a, member_b = dense("a", 128), dense("b", 128)
    plan = build_plan_doc(
        [
            (base_config, plan_train_buckets([member_a], base_config, strategy=NAIVE)),
            (other_config, plan_train_buckets([member_b], other_config, strategy=NAIVE)),
        ],
        NAIVE,
        (1, 1),
        None,
        config_fingerprint(["a", "b"]),
    )
    ids = [b["id"] for b in plan.buckets]
    assert len(ids) == len(set(ids)) == 2
    buckets, uncovered = plan.materialize_buckets([member_a, member_b])
    assert uncovered == []
    rosters = sorted(tuple(b.member_names) for b in buckets)
    assert rosters == [("a",), ("b",)]  # each member exactly once


def test_mixed_windowed_and_dense_partition():
    members = [dense("d1", 64), windowed("w1", 40), windowed("w2", 40)]
    buckets = plan_train_buckets(members, CONFIG, strategy=PACKED)
    by_kind = {b.windowed: b for b in buckets}
    assert by_kind[False].member_names == ["d1"]
    assert by_kind[True].member_names == ["w1", "w2"]
    assert by_kind[True].program == "fleet_windowed_fit"
    assert by_kind[True].offset == LSTM.lookback_window - 1
