import pytest

from gordo_tpu.planner import ladder

pytestmark = pytest.mark.planner


def test_round_up_ladder_pow2_parity():
    """ratio 2.0 reproduces the trainer's historical pow2 rounding."""
    from gordo_tpu.planner.packing import _round_up_pow2

    for n in (1, 5, 16, 100, 128, 129, 1000, 4096):
        for batch in (1, 16, 32):
            assert ladder.round_up_ladder(
                max(n, batch), 2.0, multiple=batch
            ) == _round_up_pow2(n, batch)


def test_round_up_ladder_examples():
    assert ladder.round_up_ladder(100, 2.0, 16) == 128
    assert ladder.round_up_ladder(1100, 2.0) == 2048
    assert ladder.round_up_ladder(1100, 1.25) == 1263
    # already on a rung stays put
    assert ladder.round_up_ladder(128, 2.0, 16) == 128


def test_round_up_ladder_respects_multiple():
    for n in (7, 33, 100, 999):
        rung = ladder.round_up_ladder(n, 1.25, multiple=16)
        assert rung >= n
        assert rung % 16 == 0


def test_round_up_ladder_strictly_increasing_rungs():
    """Small ratios never stall: successive rungs strictly increase even
    when ceil(ratio**k) rounds to the same multiple."""
    rungs = ladder.geometric_rungs(1, 200, 1.01, multiple=8)
    assert rungs == sorted(set(rungs))
    assert rungs[-1] >= 200


def test_geometric_rungs_cover_range():
    rungs = ladder.geometric_rungs(50, 1000, 1.25)
    assert rungs[0] >= 50
    assert rungs[-1] >= 1000
    for lo, hi in zip(rungs[:-1], rungs[1:]):
        assert hi > lo


def test_pad_ratio_env_overrides(monkeypatch):
    monkeypatch.setenv(ladder.SERIES_PAD_RATIO_ENV, "1.5")
    monkeypatch.setenv(ladder.SAMPLE_PAD_RATIO_ENV, "2.0")
    assert ladder.series_pad_ratio() == 1.5
    assert ladder.sample_pad_ratio() == 2.0


def test_pad_ratio_rejects_degenerate_values(monkeypatch):
    """Ratios <= 1 would loop forever in round_up_ladder — fall back."""
    for bad in ("0.5", "1.0", "-3", "nonsense"):
        monkeypatch.setenv(ladder.SERIES_PAD_RATIO_ENV, bad)
        monkeypatch.setenv(ladder.SAMPLE_PAD_RATIO_ENV, bad)
        assert ladder.series_pad_ratio() == ladder.DEFAULT_SERIES_PAD_RATIO
        assert ladder.sample_pad_ratio() == ladder.DEFAULT_SAMPLE_PAD_RATIO


def test_serve_ladder_reexports_planner_implementation():
    """Build and serve must quantize with the SAME code: the serve module
    is a facade over the planner's (the PR that moved it)."""
    from gordo_tpu.serve import ladder as serve_ladder

    assert serve_ladder.pad_to is ladder.pad_to
    assert serve_ladder.member_ladder is ladder.member_ladder
    assert serve_ladder.row_ladder is ladder.row_ladder
    assert serve_ladder.DEFAULT_ROW_LADDER == ladder.DEFAULT_ROW_LADDER
