import numpy as np
import pandas as pd
import pytest

from gordo_tpu.models.transformers.imputer import InfImputer


@pytest.fixture
def data():
    X = np.array(
        [[1.0, 10.0], [2.0, np.inf], [-np.inf, 30.0], [4.0, 40.0]], dtype=np.float64
    )
    return X


def test_minmax_strategy(data):
    imputer = InfImputer(strategy="minmax", delta=2.0)
    out = imputer.fit_transform(data)
    assert np.isfinite(out).all()
    assert out[1, 1] == 40.0 + 2.0
    assert out[2, 0] == 1.0 - 2.0


def test_extremes_strategy(data):
    imputer = InfImputer(strategy="extremes")
    out = imputer.fit_transform(data)
    assert np.isfinite(out).all()
    assert out[1, 1] == np.finfo(data.dtype).max


def test_explicit_fill_values(data):
    imputer = InfImputer(inf_fill_value=99.0, neg_inf_fill_value=-99.0)
    out = imputer.fit_transform(data)
    assert out[1, 1] == 99.0
    assert out[2, 0] == -99.0


def test_dataframe_round_trip(data):
    df = pd.DataFrame(data, columns=["a", "b"])
    out = InfImputer().fit_transform(df)
    assert isinstance(out, pd.DataFrame)
    assert list(out.columns) == ["a", "b"]
    # original untouched
    assert np.isinf(df.values).any()


def test_unknown_strategy():
    with pytest.raises(ValueError):
        InfImputer(strategy="bogus")
