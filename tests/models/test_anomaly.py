"""
Anomaly-detector tests against fast sklearn base estimators (the reference's
strategy — tests/gordo/machine/model/anomaly/test_anomaly_detectors.py runs
these against sklearn models, no deep nets needed).
"""

from datetime import timedelta

import numpy as np
import pandas as pd
import pytest
from sklearn.linear_model import LinearRegression
from sklearn.preprocessing import MinMaxScaler, RobustScaler

from gordo_tpu.models.anomaly import (
    DiffBasedAnomalyDetector,
    DiffBasedKFCVAnomalyDetector,
)

EXPECTED_COLS = {
    "start",
    "end",
    "model-input",
    "model-output",
    "tag-anomaly-scaled",
    "tag-anomaly-unscaled",
    "total-anomaly-scaled",
    "total-anomaly-unscaled",
    "anomaly-confidence",
    "total-anomaly-confidence",
}


@pytest.fixture
def frame():
    rng = np.random.RandomState(1)
    index = pd.date_range("2020-01-01", periods=300, freq="10min", tz="UTC")
    data = rng.rand(300, 3) * 10
    return pd.DataFrame(data, columns=["t1", "t2", "t3"], index=index)


@pytest.mark.parametrize("scaler", [MinMaxScaler(), RobustScaler()])
@pytest.mark.parametrize("shuffle", [False, True])
def test_tss_detector_full_flow(frame, scaler, shuffle):
    det = DiffBasedAnomalyDetector(
        base_estimator=LinearRegression(), scaler=scaler, shuffle=shuffle
    )
    det.cross_validate(X=frame, y=frame)
    det.fit(frame, frame)

    assert det.feature_thresholds_ is not None
    assert len(det.feature_thresholds_) == 3
    assert np.isfinite(det.aggregate_threshold_)
    assert set(det.aggregate_thresholds_per_fold_) == {"fold-0", "fold-1", "fold-2"}
    assert det.feature_thresholds_per_fold_.shape == (3, 3)

    out = det.anomaly(frame, frame, frequency=timedelta(minutes=10))
    assert set(out.columns.get_level_values(0)) == EXPECTED_COLS
    assert len(out) == len(frame)
    # LinearRegression reconstructs X≈X, so errors are ~0
    assert (out["total-anomaly-unscaled"] < 1e-10).all()


def test_smoothed_variants(frame):
    det = DiffBasedAnomalyDetector(
        base_estimator=LinearRegression(), window=12, smoothing_method="sma"
    )
    det.cross_validate(X=frame, y=frame)
    det.fit(frame, frame)
    out = det.anomaly(frame, frame)
    got = set(out.columns.get_level_values(0))
    assert {
        "smooth-tag-anomaly-scaled",
        "smooth-tag-anomaly-unscaled",
        "smooth-total-anomaly-scaled",
        "smooth-total-anomaly-unscaled",
    } <= got
    assert det.smooth_aggregate_threshold_ is not None
    meta = det.get_metadata()
    assert meta["smoothing-method"] == "sma"
    assert "smooth-feature-thresholds" in meta


@pytest.mark.parametrize("smoothing_method", ["smm", "sma", "ewma"])
def test_kfcv_detector(frame, smoothing_method):
    det = DiffBasedKFCVAnomalyDetector(
        base_estimator=LinearRegression(),
        window=24,
        smoothing_method=smoothing_method,
        threshold_percentile=0.95,
    )
    det.cross_validate(X=frame, y=frame)
    det.fit(frame, frame)
    assert np.isfinite(det.aggregate_threshold_)
    assert len(det.feature_thresholds_) == 3
    out = det.anomaly(frame, frame, frequency=timedelta(minutes=10))
    assert len(out) == len(frame)


def test_require_thresholds_enforced(frame):
    det = DiffBasedAnomalyDetector(base_estimator=LinearRegression())
    det.fit(frame, frame)
    with pytest.raises(AttributeError):
        det.anomaly(frame, frame)

    relaxed = DiffBasedAnomalyDetector(
        base_estimator=LinearRegression(), require_thresholds=False
    )
    relaxed.fit(frame, frame)
    out = relaxed.anomaly(frame, frame)
    assert "anomaly-confidence" not in set(out.columns.get_level_values(0))


def test_attribute_delegation(frame):
    det = DiffBasedAnomalyDetector(base_estimator=LinearRegression())
    det.fit(frame, frame)
    # coef_ lives on the base estimator
    assert det.coef_.shape == (3, 3)
    with pytest.raises(AttributeError):
        det.into_definition  # serializer hooks must not delegate


def test_get_metadata_structure(frame):
    det = DiffBasedAnomalyDetector(base_estimator=LinearRegression())
    det.cross_validate(X=frame, y=frame)
    det.fit(frame, frame)
    meta = det.get_metadata()
    assert "feature-thresholds" in meta
    assert "aggregate-threshold" in meta
    assert "feature-thresholds-per-fold" in meta
