"""
Host-loop callbacks: the built-ins beyond compiled EarlyStopping
(ReduceLROnPlateau, TerminateOnNaN) and the reference's config-defined
custom-callback contract (gordo/serializer/from_definition.py:352-373) —
a dotted-path callback in YAML must ride the per-epoch host loop all the
way through local_build.
"""

import sys
import textwrap

import numpy as np
import pytest

from gordo_tpu.models.callbacks import (
    Callback,
    ReduceLROnPlateau,
    TerminateOnNaN,
)
from gordo_tpu.models.estimators import JaxAutoEncoder
from gordo_tpu.serializer.from_definition import build_callbacks


def _logs(loss, val=None, lr=0.1):
    logs = {"loss": loss, "lr": lr}
    if val is not None:
        logs["val_loss"] = val
    return logs


class TestReduceLROnPlateau:
    def test_requests_reduction_after_patience(self):
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2)
        cb.on_train_begin()
        assert not cb.on_epoch_end(0, _logs(1.0))
        assert cb.consume_lr_request() is None
        cb.on_epoch_end(1, _logs(1.0))  # wait 1
        cb.on_epoch_end(2, _logs(1.0))  # wait 2 -> reduce
        assert cb.consume_lr_request() == pytest.approx(0.05)
        assert cb.consume_lr_request() is None  # one-shot

    def test_improvement_resets_wait(self):
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2)
        cb.on_train_begin()
        cb.on_epoch_end(0, _logs(1.0))
        cb.on_epoch_end(1, _logs(1.0))
        cb.on_epoch_end(2, _logs(0.5))  # improved
        cb.on_epoch_end(3, _logs(0.5))
        assert cb.consume_lr_request() is None

    def test_min_lr_floor(self):
        cb = ReduceLROnPlateau(monitor="loss", factor=0.1, patience=1, min_lr=0.09)
        cb.on_train_begin()
        cb.on_epoch_end(0, _logs(1.0))
        cb.on_epoch_end(1, _logs(1.0))
        assert cb.consume_lr_request() == pytest.approx(0.09)

    def test_rejects_factor_ge_one(self):
        with pytest.raises(ValueError):
            ReduceLROnPlateau(factor=1.5)


class TestTerminateOnNaN:
    def test_stops_on_nan_loss(self):
        cb = TerminateOnNaN()
        assert not cb.on_epoch_end(0, {"loss": 1.0})
        assert cb.on_epoch_end(1, {"loss": float("nan")})
        assert cb.on_epoch_end(2, {"loss": float("inf")})


def test_host_loop_applies_lr_reduction():
    """An aggressive ReduceLROnPlateau measurably changes training: with
    factor ~0 the LR collapses to ~0 after the first plateau, freezing
    the loss where the callback-free run keeps improving."""
    rng = np.random.RandomState(0)
    X = rng.rand(96, 4).astype(np.float32)

    def fit(callbacks):
        model = JaxAutoEncoder(
            kind="feedforward_hourglass",
            epochs=8,
            batch_size=32,
            callbacks=callbacks,
            seed=1,
        )
        model.fit(X, X)
        return model._history.history["loss"]

    free = fit([])
    clamped = fit(
        [ReduceLROnPlateau(monitor="loss", factor=1e-6, patience=1, min_delta=10.0)]
    )
    # min_delta=10 makes every epoch a "plateau": LR collapses after
    # epoch 2, so later epochs barely move while the free run improves
    assert free[-1] < free[2] * 0.98
    assert abs(clamped[-1] - clamped[3]) < abs(free[-1] - free[3]) * 0.2


def test_custom_dotted_path_callback_through_local_build(tmp_path, monkeypatch):
    """A YAML config naming a user-module callback by dotted path runs it
    through the whole build (the reference serializer's generic callback
    construction, proven end-to-end)."""
    from gordo_tpu.builder import local_build

    module_dir = tmp_path / "userlib"
    module_dir.mkdir()
    (module_dir / "custom_callbacks.py").write_text(
        textwrap.dedent(
            """
            from gordo_tpu.models.callbacks import Callback

            class EpochRecorder(Callback):
                seen = []

                def __init__(self, tag="x", **kwargs):
                    self.tag = tag

                def on_epoch_end(self, epoch, logs=None):
                    EpochRecorder.seen.append((self.tag, epoch, dict(logs or {})))
                    return False
            """
        )
    )
    monkeypatch.syspath_prepend(str(module_dir))

    config = """
machines:
  - name: cb-machine
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [a, b, c]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        epochs: 3
        callbacks:
          - custom_callbacks.EpochRecorder:
              tag: from-yaml
"""
    model, machine = next(local_build(config, project_name="p"))
    recorder = sys.modules["custom_callbacks"].EpochRecorder
    tags = {t for t, _, _ in recorder.seen}
    epochs = [e for t, e, _ in recorder.seen if t == "from-yaml"]
    assert "from-yaml" in tags
    # builder runs CV folds + final fit; the final fit contributes one
    # full 3-epoch pass and every call carried loss + lr logs
    assert {0, 1, 2} <= set(epochs)
    assert all("loss" in logs and "lr" in logs for _, _, logs in recorder.seen)


def test_keras_paths_resolve_to_builtins():
    callbacks = build_callbacks(
        [
            {"tensorflow.keras.callbacks.ReduceLROnPlateau": {"patience": 3}},
            {"keras.callbacks.TerminateOnNaN": {}},
        ]
    )
    assert isinstance(callbacks[0], ReduceLROnPlateau)
    assert callbacks[0].patience == 3
    assert isinstance(callbacks[1], TerminateOnNaN)
    assert all(isinstance(cb, Callback) for cb in callbacks)
