"""
The north-star correctness gate: anomaly-score MAE parity vs TF2/Keras
(BASELINE.md: "anomaly-score MAE parity vs the TF2 CPU baseline").

Trains the same hourglass AE on the same data with the reference's Keras
engine and with the JAX engine, runs the same CV + threshold math through
:class:`DiffBasedAnomalyDetector`, and gates the anomaly surfaces against
the tolerances stated in gordo_tpu/compat/tf_parity.py (calibrated
against the reference engine's own seed-to-seed envelope).
"""

import numpy as np
import pytest

pytest.importorskip("tensorflow")

from gordo_tpu.compat import tf_parity  # noqa: E402


@pytest.fixture(scope="module")
def parity_record() -> dict:
    # The calibrated configuration from the module header: small enough
    # for CI, converged enough that residuals are noise-dominated.
    return tf_parity.run_parity(
        n_train=720, n_eval=240, n_tags=8, epochs=150, batch_size=64
    )


@pytest.mark.slow
def test_anomaly_score_mae_parity(parity_record):
    assert parity_record["score_rel_mae"] <= tf_parity.DEFAULT_REL_MAE_TOL, (
        "anomaly-score MAE vs TF2 out of tolerance: "
        f"{parity_record['score_rel_mae']:.3f} > {tf_parity.DEFAULT_REL_MAE_TOL}"
    )
    assert parity_record["score_corr"] >= tf_parity.DEFAULT_CORR_MIN


@pytest.mark.slow
def test_threshold_parity(parity_record):
    assert (
        parity_record["agg_threshold_rel_delta"]
        <= tf_parity.DEFAULT_AGG_THRESHOLD_REL_TOL
    )
    assert (
        parity_record["tag_threshold_mean_rel_delta"]
        <= tf_parity.DEFAULT_TAG_THRESHOLD_REL_TOL
    )


@pytest.mark.slow
def test_parity_gate(parity_record):
    assert parity_record["passes"] is True
    # Both engines must actually have converged — a parity of two underfit
    # models would be vacuous.
    assert parity_record["explained_variance_tf"] > 0.95
    assert parity_record["explained_variance_jax"] > 0.95


def test_make_parity_data_shapes():
    train, evaluation = tf_parity.make_parity_data(
        n_train=100, n_eval=40, n_tags=5, anomaly_tags=2, anomaly_offset=2.0
    )
    assert train.shape == (100, 5)
    assert evaluation.shape == (40, 5)
    # the injected anomaly lives in the last quarter of the eval window
    clean, anomalous = evaluation.iloc[:-10], evaluation.iloc[-10:]
    assert (
        anomalous.iloc[:, 0].mean() - clean.iloc[:, 0].mean() > 1.0
    ), "anomaly offset missing from eval tail"
    assert train.index.tz is not None


def test_parity_passes_gate_logic():
    good = {
        "score_rel_mae": 0.1,
        "score_corr": 0.999,
        "agg_threshold_rel_delta": 0.1,
        "tag_threshold_mean_rel_delta": 0.1,
    }
    assert tf_parity.parity_passes(good)
    assert not tf_parity.parity_passes({**good, "score_rel_mae": 0.9})
    assert not tf_parity.parity_passes({**good, "score_corr": 0.5})
    assert not tf_parity.parity_passes({**good, "agg_threshold_rel_delta": 0.9})
