import pickle

import numpy as np
import pytest

from gordo_tpu.models import (
    EarlyStopping,
    JaxAutoEncoder,
    JaxLSTMAutoEncoder,
    JaxLSTMForecast,
    JaxRawModelRegressor,
    register_model_builder,
)

# Every (estimator type, kind) pair in the registry — the reference's
# MODEL_COMBINATIONS parity surface (tests/gordo/machine/model/test_model.py:35-47)
ESTIMATORS = {
    "JaxAutoEncoder": JaxAutoEncoder,
    "JaxLSTMAutoEncoder": JaxLSTMAutoEncoder,
    "JaxLSTMForecast": JaxLSTMForecast,
}
MODEL_COMBINATIONS = [
    (ESTIMATORS[type_name], kind)
    for type_name, kinds in register_model_builder.factories.items()
    if type_name in ESTIMATORS
    for kind in kinds
]

SMALL = dict(
    encoding_dim=(8, 4), encoding_func=("tanh", "tanh"),
    decoding_dim=(4, 8), decoding_func=("tanh", "tanh"),
)
SMALL_BY_KIND = {
    "feedforward_model": SMALL,
    "lstm_model": SMALL,
    "feedforward_symmetric": dict(dims=(8, 4), funcs=("tanh", "tanh")),
    "lstm_symmetric": dict(dims=(8, 4), funcs=("tanh", "tanh")),
    "feedforward_hourglass": dict(encoding_layers=2),
    "lstm_hourglass": dict(encoding_layers=2),
}

X = np.random.RandomState(0).rand(60, 3).astype(np.float32)


@pytest.mark.parametrize("Model,kind", MODEL_COMBINATIONS)
def test_fit_predict_all_combinations(Model, kind):
    kwargs = dict(SMALL_BY_KIND[kind])
    if "LSTM" in Model.__name__:
        kwargs["lookback_window"] = 3
    model = Model(kind=kind, epochs=1, batch_size=16, **kwargs)
    model.fit(X, X.copy())
    out = model.predict(X)
    assert out.shape[1] == 3
    offset = len(X) - len(out)
    if Model is JaxAutoEncoder:
        assert offset == 0
    elif Model is JaxLSTMAutoEncoder:
        assert offset == 3 - 1
    else:  # forecast
        assert offset == 3
    score = model.score(X, X.copy())
    assert np.isfinite(score)


def test_unknown_kind_raises():
    with pytest.raises(ValueError):
        JaxAutoEncoder(kind="no_such_kind")
    with pytest.raises(ValueError):
        JaxAutoEncoder(kind="no.such.module.fn")


def test_callable_kind_registers():
    from gordo_tpu.models.factories.feedforward_autoencoder import feedforward_model

    def my_kind(n_features: int, **kwargs):
        return feedforward_model(n_features, encoding_dim=(4,),
                                 encoding_func=("tanh",), decoding_dim=(4,),
                                 decoding_func=("tanh",))

    model = JaxAutoEncoder(kind=my_kind, epochs=1)
    model.fit(X, X)
    assert model.predict(X).shape == X.shape


def test_dotted_path_kind():
    model = JaxAutoEncoder(
        kind="gordo_tpu.models.factories.feedforward_autoencoder.feedforward_hourglass",
        epochs=1,
        encoding_layers=1,
    )
    model.fit(X, X)
    assert model.predict(X).shape == X.shape


def test_fit_history_metadata():
    model = JaxAutoEncoder(
        kind="feedforward_hourglass", epochs=3, validation_split=0.2,
        encoding_layers=1,
    )
    model.fit(X, X)
    history = model.get_metadata()["history"]
    assert len(history["loss"]) == 3
    assert len(history["val_loss"]) == 3
    assert history["params"]["epochs"] == 3
    # training should reduce loss on this easy identity task
    assert history["loss"][-1] <= history["loss"][0]


def test_early_stopping_compiled_into_program():
    model = JaxAutoEncoder(
        kind="feedforward_hourglass",
        epochs=50,
        encoding_layers=1,
        validation_split=0.2,
        callbacks=[
            {
                "gordo_tpu.models.callbacks.EarlyStopping": {
                    "monitor": "val_loss",
                    "patience": 1,
                    "min_delta": 10.0,  # impossible improvement -> stop fast
                }
            }
        ],
    )
    model.fit(X, X)
    assert len(model.get_metadata()["history"]["loss"]) < 50


def test_pickle_round_trip_preserves_predictions():
    model = JaxAutoEncoder(kind="feedforward_hourglass", epochs=1, encoding_layers=1)
    model.fit(X, X)
    expected = model.predict(X)
    restored = pickle.loads(pickle.dumps(model))
    np.testing.assert_allclose(restored.predict(X), expected, rtol=1e-6)
    # params are host numpy after round trip
    leaf = next(iter(restored.params_.values()))["W"]
    assert isinstance(leaf, np.ndarray)


def test_from_definition_into_definition_round_trip():
    model = JaxAutoEncoder(kind="feedforward_symmetric", dims=(4, 2), epochs=2)
    definition = model.into_definition()
    rebuilt = JaxAutoEncoder.from_definition(dict(definition))
    assert rebuilt.kind == "feedforward_symmetric"
    assert rebuilt.kwargs["epochs"] == 2


def test_deterministic_given_seed():
    a = JaxAutoEncoder(kind="feedforward_hourglass", epochs=1, encoding_layers=1)
    b = JaxAutoEncoder(kind="feedforward_hourglass", epochs=1, encoding_layers=1)
    a.fit(X, X)
    b.fit(X, X)
    np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=1e-6)


def test_lstm_lookback_too_large_raises():
    model = JaxLSTMAutoEncoder(kind="lstm_hourglass", lookback_window=100)
    with pytest.raises(ValueError):
        model.fit(X, X)


def test_raw_model_regressor():
    config = {
        "compile": {"loss": "mse", "optimizer": "adam"},
        "spec": {
            "tensorflow.keras.models.Sequential": {
                "layers": [
                    {"tensorflow.keras.layers.Dense": {"units": 4, "input_shape": [3]}},
                    {"tensorflow.keras.layers.Dense": {"units": 3}},
                ]
            }
        },
    }
    model = JaxRawModelRegressor(kind=config, epochs=1)
    model.fit(X, X)
    assert model.predict(X).shape == X.shape
