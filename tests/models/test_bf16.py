"""
bfloat16 compute support: specs carry ``compute_dtype``; params and
activations run in bf16 while outputs, losses and thresholds stay
float32 (the dtype contract in models/nn.py). In the measured HBM-bound
tiny-model regime bf16 halves the bytes each training step re-reads —
the bench's fleet stage reports the realized speedup.

Correctness here is PARITY, not convergence: a bf16 model must answer
(tolerably) what the same-seed f32 model answers. The old assert —
"bf16 converges past 0.8 EV" — tracked the init seed, not the dtype
(CHANGES.md: it flipped between seeds with f32 scoring identically),
so it could fail on a healthy bf16 path and pass on a broken one. The
tolerance-based check (``gordo_tpu.serve.precision.recon_agreement``)
is the same math the serving precision-parity gate runs.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models.estimators import JaxAutoEncoder, JaxLSTMAutoEncoder
from gordo_tpu.models.factories import feedforward_hourglass, lstm_model
from gordo_tpu.models.training import FitConfig
from gordo_tpu.parallel import FleetMember, FleetTrainer
from gordo_tpu.serve.precision import recon_agreement

pytestmark = pytest.mark.precision


@pytest.fixture(scope="module")
def sine_data():
    rng = np.random.RandomState(0)
    t = np.linspace(0, 8 * np.pi, 400, dtype=np.float32)
    X = np.stack(
        [np.sin(t + phase) for phase in (0.0, 0.7, 1.4, 2.1)], axis=1
    ) + 0.05 * rng.standard_normal((400, 4)).astype(np.float32)
    return X


def test_factory_plumbs_compute_dtype():
    spec = feedforward_hourglass(8, compute_dtype="bfloat16")
    assert spec.compute_dtype == "bfloat16"
    lstm = lstm_model(8, lookback_window=4, compute_dtype="bfloat16")
    assert lstm.compute_dtype == "bfloat16"
    # default unchanged
    assert feedforward_hourglass(8).compute_dtype == "float32"


def test_bf16_estimator_trains_and_predicts_float32(sine_data):
    model = JaxAutoEncoder(
        kind="feedforward_hourglass",
        compute_dtype="bfloat16",
        epochs=30,
        batch_size=64,
        seed=1,
    )
    model.fit(sine_data, sine_data)
    assert model.spec_.compute_dtype == "bfloat16"
    # mixed precision: master params stay f32 (bf16 params drop most Adam
    # updates below the 8-bit-mantissa ULP — see models/nn.py)
    leaf = model.params_["dense_0"]["W"]
    assert jnp.asarray(leaf).dtype == jnp.float32
    out = model.predict(sine_data)
    # sklearn-facing output is full-precision numpy
    assert np.asarray(out).dtype == np.float32
    assert np.all(np.isfinite(out))


def test_bf16_tracks_f32_training_within_tolerance(sine_data):
    """The parity contract: same seed, same budget — the bf16 model's
    reconstructions agree with the f32 model's row for row within the
    precision-parity gate's tolerance (the shared ``recon_agreement``
    helper, NOT an absolute convergence bar that tracks seed luck)."""
    kwargs = dict(kind="feedforward_hourglass", epochs=30, batch_size=64, seed=1)
    f32 = JaxAutoEncoder(**kwargs).fit(sine_data, sine_data)
    bf16 = JaxAutoEncoder(compute_dtype="bfloat16", **kwargs).fit(
        sine_data, sine_data
    )
    report = recon_agreement(
        f32.predict(sine_data), bf16.predict(sine_data), rtol=0.1, atol=0.05
    )
    # training amplifies rounding differences over 30 epochs of updates,
    # so the tolerance is looser than the serving gate's (which compares
    # the SAME weights across dtypes); the overwhelming majority of rows
    # must still agree
    assert report["agreement"] >= 0.95, report
    # and the two models' answers stay in the same EV neighborhood —
    # relative parity, never an absolute convergence assert
    ev_f32 = f32.score(sine_data, sine_data)
    ev_bf16 = bf16.score(sine_data, sine_data)
    assert ev_bf16 > ev_f32 - 0.1, (ev_f32, ev_bf16)


def test_bf16_fleet_bucket(sine_data):
    spec = feedforward_hourglass(4, compute_dtype="bfloat16")
    members = [
        FleetMember(name=f"m{i}", spec=spec, X=sine_data, y=sine_data, seed=i)
        for i in range(3)
    ]
    results = FleetTrainer().train(members, FitConfig(epochs=5, batch_size=64))
    for result in results:
        assert np.isfinite(result.history.history["loss"][-1])


def test_bf16_packed_fleet(sine_data):
    spec = feedforward_hourglass(4, compute_dtype="bfloat16")
    members = [
        FleetMember(name=f"m{i}", spec=spec, X=sine_data, y=sine_data, seed=i)
        for i in range(4)
    ]
    results = FleetTrainer(packing=2).train(
        members, FitConfig(epochs=5, batch_size=64)
    )
    for result in results:
        assert np.isfinite(result.history.history["loss"][-1])


def test_bf16_lstm_trains(sine_data):
    model = JaxLSTMAutoEncoder(
        kind="lstm_model",
        lookback_window=6,
        compute_dtype="bfloat16",
        encoding_dim=(8,),
        encoding_func=("tanh",),
        decoding_dim=(8,),
        decoding_func=("tanh",),
        epochs=2,
    )
    model.fit(sine_data[:120], sine_data[:120])
    out = model.predict(sine_data[:60])
    assert np.asarray(out).dtype == np.float32
    assert np.all(np.isfinite(out))
