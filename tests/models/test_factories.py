import pytest

from gordo_tpu.models.factories import (
    feedforward_hourglass,
    feedforward_model,
    feedforward_symmetric,
    lstm_hourglass,
    lstm_model,
    lstm_symmetric,
)
from gordo_tpu.models.register import register_model_builder
from gordo_tpu.models.spec import FeedForwardSpec, LSTMSpec


def test_registry_contents():
    factories = register_model_builder.factories
    assert set(factories["JaxAutoEncoder"]) == {
        "feedforward_model",
        "feedforward_symmetric",
        "feedforward_hourglass",
    }
    for lstm_type in ("JaxLSTMAutoEncoder", "JaxLSTMForecast"):
        assert set(factories[lstm_type]) == {
            "lstm_model",
            "lstm_symmetric",
            "lstm_hourglass",
        }


def test_feedforward_model_geometry():
    spec = feedforward_model(
        5,
        encoding_dim=(8, 4),
        encoding_func=("tanh", "relu"),
        decoding_dim=(4, 8),
        decoding_func=("relu", "tanh"),
    )
    assert isinstance(spec, FeedForwardSpec)
    assert spec.dims == (8, 4, 4, 8)
    assert spec.activations == ("tanh", "relu", "relu", "tanh")
    assert spec.n_features_out == 5
    # l1 activity on non-first encoder layers only
    assert spec.l1_activity == (0.0, 1e-4, 0.0, 0.0)


def test_feedforward_symmetric_mirrors():
    spec = feedforward_symmetric(6, dims=(10, 4), funcs=("tanh", "tanh"))
    assert spec.dims == (10, 4, 4, 10)


@pytest.mark.parametrize(
    "n_features,kwargs,expected_dims",
    [
        (10, {}, (8, 7, 5, 5, 7, 8)),
        (5, {}, (4, 4, 3, 3, 4, 4)),
        (10, {"compression_factor": 0.2}, (7, 5, 2, 2, 5, 7)),
        (10, {"encoding_layers": 1}, (5, 5)),
    ],
)
def test_hourglass_geometry_parity(n_features, kwargs, expected_dims):
    """Geometry matches the reference's doctest examples
    (factories/feedforward_autoencoder.py:224-236)."""
    spec = feedforward_hourglass(n_features, **kwargs)
    assert spec.dims == expected_dims
    assert spec.n_features_out == n_features


def test_hourglass_validation():
    with pytest.raises(ValueError):
        feedforward_hourglass(10, compression_factor=2.0)
    with pytest.raises(ValueError):
        feedforward_hourglass(10, encoding_layers=0)


def test_dim_func_mismatch_raises():
    with pytest.raises(ValueError):
        feedforward_model(4, encoding_dim=(8, 4), encoding_func=("tanh",))


def test_lstm_factories():
    spec = lstm_model(4, lookback_window=7, encoding_dim=(8,), encoding_func=("tanh",),
                      decoding_dim=(8,), decoding_func=("tanh",))
    assert isinstance(spec, LSTMSpec)
    assert spec.lookback_window == 7
    assert spec.dims == (8, 8)
    sym = lstm_symmetric(4, dims=(6, 3), funcs=("tanh", "tanh"))
    assert sym.dims == (6, 3, 3, 6)
    hg = lstm_hourglass(10)
    assert hg.dims == (8, 7, 5, 5, 7, 8)


def test_optimizer_spec_defaults_match_keras():
    spec = feedforward_hourglass(4)
    assert spec.optimizer.name == "Adam"
    assert spec.optimizer.learning_rate == pytest.approx(0.001)


def test_specs_are_hashable_bucket_keys():
    a = feedforward_hourglass(10)
    b = feedforward_hourglass(10)
    c = feedforward_hourglass(12)
    assert hash(a) == hash(b) and a == b
    assert a != c
    assert len({a, b, c}) == 2


def test_register_validates_n_features_first():
    with pytest.raises(ValueError):

        @register_model_builder(type="Whatever")
        def bad_factory(features):
            ...
