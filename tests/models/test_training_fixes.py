import numpy as np

from gordo_tpu.models import EarlyStopping, JaxAutoEncoder
from gordo_tpu.models.callbacks import Callback

X = np.random.RandomState(3).rand(50, 3).astype(np.float32)


class RecordingCallback(Callback):
    def __init__(self):
        self.epochs = []

    def on_epoch_end(self, epoch, logs=None):
        self.epochs.append(dict(logs or {}))
        return False


def test_early_stopping_honored_alongside_host_callbacks():
    recorder = RecordingCallback()
    model = JaxAutoEncoder(
        kind="feedforward_hourglass",
        encoding_layers=1,
        epochs=50,
        validation_split=0.2,
        callbacks=[
            EarlyStopping(monitor="val_loss", patience=1, min_delta=10.0),
            recorder,
        ],
    )
    model.fit(X, X)
    assert 0 < len(recorder.epochs) < 50
    assert "val_loss" in recorder.epochs[0]


def test_multi_aggregation_dataset():
    from gordo_tpu.dataset import RandomDataset

    ds = RandomDataset(
        "2020-01-01T00:00:00+00:00",
        "2020-01-05T00:00:00+00:00",
        tag_list=["a", "b"],
        aggregation_methods=["mean", "max"],
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["a_mean", "a_max", "b_mean", "b_max"]
    assert len(X) > 0
