"""
Estimator-level segmented (stateful-scan) LSTM training — the single-
model twin of the fleet opt-in (GORDO_TPU_LSTM_SEGMENTED): the raw
series trains without host-side window materialization, matching the
window-restart path exactly at segment length 1 and staying in the same
quality regime at real segment counts.
"""

import numpy as np
import pytest

from gordo_tpu.models.estimators import JaxLSTMAutoEncoder
from gordo_tpu.models.factories import lstm_model
from gordo_tpu.models.training import FitConfig, fit_single_segmented
from gordo_tpu.ops.windows import window_targets

LOOKBACK = 8
TAGS = 3


def _series(n=90, seed=0):
    return np.random.RandomState(seed).rand(n, TAGS).astype(np.float32)


def _fit_estimator(monkeypatch, segments, **kwargs):
    if segments:
        monkeypatch.setenv("GORDO_TPU_LSTM_SEGMENTED", str(segments))
    else:
        monkeypatch.delenv("GORDO_TPU_LSTM_SEGMENTED", raising=False)
    model = JaxLSTMAutoEncoder(
        kind="lstm_model",
        lookback_window=LOOKBACK,
        encoding_dim=[8],
        encoding_func=["tanh"],
        decoding_dim=[8],
        decoding_func=["tanh"],
        epochs=3,
        batch_size=16,
        seed=1,
        **kwargs,
    )
    X = _series()
    model.fit(X, X)
    return model, X


def test_estimator_segmented_single_window_matches_dense(monkeypatch):
    """L=1 segments (G=batch) are cold windows in batch order: losses and
    predictions must match the materialized-window path."""
    dense, X = _fit_estimator(monkeypatch, None)
    segmented, _ = _fit_estimator(monkeypatch, 16)
    np.testing.assert_allclose(
        segmented._history.history["loss"],
        dense._history.history["loss"],
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        segmented.predict(X), dense.predict(X), rtol=1e-4, atol=1e-6
    )
    assert segmented._history.params.get("segmented") == 16


def test_estimator_segmented_real_segments_trains(monkeypatch):
    model, X = _fit_estimator(monkeypatch, 4)
    losses = model._history.history["loss"]
    assert len(losses) == 3 and all(np.isfinite(losses))
    out = model.predict(X)
    # model-offset contract unchanged: lookback-1 rows shorter
    assert out.shape == (len(X) - LOOKBACK + 1, TAGS)


def test_estimator_falls_back_with_host_callbacks(monkeypatch):
    """Custom callbacks need the per-epoch host loop — segmented must
    quietly defer to the dense path rather than dropping them."""
    from gordo_tpu.models.callbacks import Callback

    class Recorder(Callback):
        epochs = []

        def on_epoch_end(self, epoch, logs=None):
            Recorder.epochs.append(epoch)
            return False

    model, _ = _fit_estimator(monkeypatch, 4, callbacks=[Recorder()])
    assert Recorder.epochs  # the callback actually ran
    assert "segmented" not in model._history.params


def test_fit_single_segmented_validation_split():
    spec = lstm_model(
        TAGS, lookback_window=LOOKBACK,
        encoding_dim=(8,), encoding_func=("tanh",),
        decoding_dim=(8,), decoding_func=("tanh",),
    )
    X = _series(120)
    targets = window_targets(X, LOOKBACK, 0)
    config = FitConfig(
        epochs=2, batch_size=16, shuffle=False, validation_split=0.25
    )
    _, history = fit_single_segmented(spec, X, targets, config, segments=4)
    assert "val_loss" in history.history
    assert all(np.isfinite(history.history["val_loss"]))


def test_fit_single_segmented_rejects_shuffle():
    spec = lstm_model(
        TAGS, lookback_window=LOOKBACK,
        encoding_dim=(8,), encoding_func=("tanh",),
        decoding_dim=(8,), decoding_func=("tanh",),
    )
    X = _series()
    with pytest.raises(ValueError, match="shuffle"):
        fit_single_segmented(
            spec, X, window_targets(X, LOOKBACK, 0),
            FitConfig(epochs=1, batch_size=16, shuffle=True),
        )
