"""
Static gates as tests — the stand-in for the reference's mypy/pyflakes
pytest plugins and black-format test (reference pytest.ini and
tests/test_formatting.py). The heavy tools aren't installed in this
environment (and cannot be: no package installs), so the always-on
gates are stdlib checks: syntax, unused imports, scope-aware
undefined-name detection via ``symtable`` (the other high-signal
pyflakes check), an annotation-coverage ratchet, and tab/trailing-
whitespace hygiene. The real linters are pinned as the ``dev`` extra in
pyproject.toml and their gates run whenever they are importable, so a
normally-provisioned CI runs them for real.
"""

import ast
import builtins
import io
import os
import symtable
import tokenize

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "gordo_tpu")


def _python_files():
    for root, _, files in os.walk(PACKAGE):
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)
    for extra in ("bench.py", "__graft_entry__.py"):
        yield os.path.join(REPO_ROOT, extra)


FILES = sorted(_python_files())
IDS = [os.path.relpath(f, REPO_ROOT) for f in FILES]


@pytest.mark.parametrize("path", FILES, ids=IDS)
def test_syntax_and_compile(path):
    with open(path, "rb") as f:
        source = f.read()
    compile(source, path, "exec")


class _ImportUsage(ast.NodeVisitor):
    """Collect imported names (name -> lineno) and every name usage."""

    def __init__(self, noqa_lines=frozenset()):
        self.imports = {}  # name -> lineno
        self.used = set()
        self._noqa_lines = noqa_lines

    def visit_Import(self, node):
        if node.lineno not in self._noqa_lines:
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                self.imports[name] = node.lineno
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.lineno not in self._noqa_lines:
            for alias in node.names:
                if alias.name == "*":
                    continue
                self.imports[alias.asname or alias.name] = node.lineno
        self.generic_visit(node)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)


@pytest.mark.parametrize("path", FILES, ids=IDS)
def test_no_unused_imports(path):
    """pyflakes' highest-signal check, via the stdlib AST."""
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source, path)
    # `# noqa` on an import line is the escape hatch for deliberate
    # re-exports outside __init__.py files.
    noqa_lines = frozenset(
        i for i, line in enumerate(source.splitlines(), 1) if "# noqa" in line
    )
    visitor = _ImportUsage(noqa_lines)
    visitor.visit(tree)

    # __init__.py re-exports and __all__ mentions count as usage.
    exported = set()
    if os.path.basename(path) == "__init__.py":
        pytest.skip("export surfaces re-import by design")
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if getattr(target, "id", None) == "__all__" and isinstance(
                    node.value, (ast.List, ast.Tuple)
                ):
                    exported |= {
                        c.value
                        for c in node.value.elts
                        if isinstance(c, ast.Constant)
                    }
    # String usages inside docstrings/comments don't count, but names used
    # only in annotations do appear as Name loads via ast in py3.12.
    unused = {
        name: lineno
        for name, lineno in visitor.imports.items()
        if name not in visitor.used and name not in exported and name != "_"
    }
    assert not unused, f"unused imports in {path}: {unused}"


#: names the interpreter injects at module scope
_MODULE_DUNDERS = {
    "__file__",
    "__name__",
    "__doc__",
    "__package__",
    "__spec__",
    "__loader__",
    "__path__",
    "__builtins__",
    "__debug__",
    "__annotations__",
    "__dict__",
    "__class__",
    "__module__",
    "__qualname__",
}
_BUILTIN_NAMES = set(dir(builtins)) | _MODULE_DUNDERS


def _undefined_names(path):
    """Scope-aware undefined-name detection via the stdlib ``symtable``:
    a referenced symbol that is neither assigned/imported/parameter in
    its scope, nor a closure variable, nor defined at module scope, nor
    a builtin, is a typo waiting for a rare code path."""
    with open(path) as f:
        source = f.read()
    top = symtable.symtable(source, path, "exec")
    module_defined = {
        s.get_name()
        for s in top.get_symbols()
        if s.is_assigned() or s.is_imported() or s.is_namespace()
    }
    problems = []

    def walk(table):
        for sym in table.get_symbols():
            name = sym.get_name()
            if not sym.is_referenced():
                continue
            if (
                sym.is_assigned()
                or sym.is_imported()
                or sym.is_parameter()
                or sym.is_namespace()
            ):
                continue
            if sym.is_free():
                continue  # closure variable: defined in an enclosing scope
            if name in module_defined or name in _BUILTIN_NAMES:
                continue
            problems.append((table.get_name(), table.get_lineno(), name))
        for child in table.get_children():
            walk(child)

    walk(top)
    return problems


@pytest.mark.parametrize("path", FILES, ids=IDS)
def test_no_undefined_names(path):
    problems = _undefined_names(path)
    assert not problems, f"undefined names in {path}: {problems}"


def _public_function_annotation_coverage():
    total, annotated = 0, 0
    for path in FILES:
        with open(path) as f:
            tree = ast.parse(f.read(), path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("_"):
                continue
            total += 1
            args = node.args
            params = args.posonlyargs + args.args + args.kwonlyargs
            params = [a for a in params if a.arg not in ("self", "cls")]
            # zero-parameter functions count only via a return annotation
            # (all([]) is vacuously true and would let them ratchet-dodge)
            if node.returns is not None or (
                params and all(a.annotation is not None for a in params)
            ):
                annotated += 1
    return annotated, total


def test_annotation_coverage_ratchet():
    """Typing gate without mypy in the image: public functions must keep
    at least the current level of annotation coverage (a return
    annotation, or fully annotated parameters). Raise the floor as
    coverage improves; never lower it."""
    annotated, total = _public_function_annotation_coverage()
    coverage = annotated / max(total, 1)
    floor = 0.75
    assert coverage >= floor, (
        f"public-function annotation coverage fell to {coverage:.1%} "
        f"({annotated}/{total}); the ratchet floor is {floor:.0%}"
    )


@pytest.mark.parametrize("path", FILES, ids=IDS)
def test_formatting_hygiene(path):
    """Black's non-negotiables that don't need black: no tabs in
    indentation, no trailing whitespace, newline at EOF."""
    with open(path) as f:
        lines = f.readlines()
    if not lines:
        return
    offenders = []
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            offenders.append(f"{i}: trailing whitespace")
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            offenders.append(f"{i}: tab indentation")
    if not lines[-1].endswith("\n"):
        offenders.append("missing newline at EOF")
    assert not offenders, f"{path}: {offenders}"


@pytest.mark.parametrize("path", FILES, ids=IDS)
def test_tokenizes_cleanly(path):
    with open(path, "rb") as f:
        list(tokenize.tokenize(io.BytesIO(f.read()).readline))


def test_black_formatting_if_available():
    black = pytest.importorskip("black")
    import subprocess
    import sys

    result = subprocess.run(
        [sys.executable, "-m", "black", "--check", "--quiet", str(PACKAGE)],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr


def test_pyflakes_if_available():
    pytest.importorskip("pyflakes")
    import subprocess
    import sys

    result = subprocess.run(
        [sys.executable, "-m", "pyflakes", str(PACKAGE)],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout


def test_mypy_if_available():
    pytest.importorskip("mypy")
    import subprocess
    import sys

    result = subprocess.run(
        [sys.executable, "-m", "mypy", "--ignore-missing-imports", str(PACKAGE)],
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout
