"""
TPU slice geometry: accelerator type → (hosts per slice, chips per host).

Used to size the k8s Job that trains a machine shard: the Job runs one pod
per TPU host (`parallelism == completions == hosts`), each pod claiming
`google.com/tpu: chips_per_host`, with `jax.distributed` coordinating the
hosts into one slice-wide mesh.

Geometry follows the published GKE TPU topology tables (v5e/v5p/v4); an
unknown type falls back to a single-host 4-chip slice and logs a warning.
"""

import logging
from typing import NamedTuple

logger = logging.getLogger(__name__)


class SliceGeometry(NamedTuple):
    hosts: int
    chips_per_host: int
    topology: str


_GEOMETRIES = {
    # v5e (v5litepod): 8 chips/host up to one host; 4 chips/host multi-host
    "v5litepod-1": SliceGeometry(1, 1, "1x1"),
    "v5litepod-4": SliceGeometry(1, 4, "2x2"),
    "v5litepod-8": SliceGeometry(1, 8, "2x4"),
    "v5litepod-16": SliceGeometry(4, 4, "4x4"),
    "v5litepod-32": SliceGeometry(8, 4, "4x8"),
    "v5litepod-64": SliceGeometry(16, 4, "8x8"),
    "v5litepod-128": SliceGeometry(32, 4, "8x16"),
    "v5litepod-256": SliceGeometry(64, 4, "16x16"),
    # v4: 4 chips/host
    "v4-8": SliceGeometry(1, 4, "2x2x1"),
    "v4-16": SliceGeometry(2, 4, "2x2x2"),
    "v4-32": SliceGeometry(4, 4, "2x2x4"),
    "v4-64": SliceGeometry(8, 4, "2x4x4"),
    "v4-128": SliceGeometry(16, 4, "4x4x4"),
    # v5p: 4 chips/host
    "v5p-8": SliceGeometry(1, 4, "2x2x1"),
    "v5p-16": SliceGeometry(2, 4, "2x2x2"),
    "v5p-32": SliceGeometry(4, 4, "2x2x4"),
}

DEFAULT_GEOMETRY = SliceGeometry(1, 4, "2x2")

# GKE nodeSelector label value per accelerator family.
_GKE_ACCELERATOR_LABELS = {
    "v5litepod": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v4": "tpu-v4-podslice",
}


def gke_accelerator_label(accelerator_type: str) -> str:
    """The ``cloud.google.com/gke-tpu-accelerator`` value for a type."""
    family = accelerator_type.rsplit("-", 1)[0]
    return _GKE_ACCELERATOR_LABELS.get(family, family)


def slice_geometry(accelerator_type: str) -> SliceGeometry:
    """Geometry for a TPU accelerator type string (e.g. ``v5litepod-16``)."""
    geometry = _GEOMETRIES.get(accelerator_type)
    if geometry is None:
        logger.warning(
            "Unknown accelerator type %r; defaulting to %s",
            accelerator_type,
            DEFAULT_GEOMETRY,
        )
        return DEFAULT_GEOMETRY
    return geometry
