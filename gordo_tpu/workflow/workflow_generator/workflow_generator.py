"""
Template machinery for the workflow generator.

Reference parity: gordo/workflow/workflow_generator/workflow_generator.py —
YAML loading that forces tz-aware timestamps (and unwraps CRD
``spec.config`` documents), a Jinja2 environment with a ``yaml`` filter and
StrictUndefined, owner-reference validation, and the imagePullPolicy
policy derived from the docker-tag version grammar.

Engine difference: the rendered artifact is a **TPU fleet workflow** — a
k8s Job per TPU slice training a shard of machines, plus the serving plane
— instead of one Argo pod per machine (SURVEY.md §2.9 row 1).
"""

import io
import logging
import os
from typing import Any, Union, cast

import dateutil.parser
import jinja2
import yaml

from ...utils.version import GordoPR, GordoRelease, GordoSpecial, Version

logger = logging.getLogger(__name__)


def _docker_friendly_version(version: str) -> str:
    """'+' is not valid in a docker tag."""
    return version.replace("+", "_")


def _valid_owner_ref(owner_reference_str: str):
    """
    Validate a yaml/json list of k8s owner-references: each must carry at
    least 'uid', 'name', 'kind' and 'apiVersion'.
    """
    owner_ref = yaml.safe_load(owner_reference_str)
    if not isinstance(owner_ref, list) or len(owner_ref) < 1:
        raise TypeError("Owner-references must be a list with at least one element")
    for oref in owner_ref:
        if not {"uid", "name", "kind", "apiVersion"} <= set(oref):
            raise TypeError(
                "All elements in owner-references must contain a uid, name, "
                "kind, and apiVersion key "
            )
    return owner_ref


def _timestamp_constructor(_loader, node):
    parsed_date = dateutil.parser.isoparse(node.value)
    if parsed_date.tzinfo is None:
        raise ValueError(
            "Provide timezone to timestamp {}."
            " Example: for UTC timezone use {} or {} ".format(
                node.value, node.value + "Z", node.value + "+00:00"
            )
        )
    return parsed_date


def get_dict_from_yaml(config_file: Union[str, io.StringIO]) -> dict:
    """
    Read a config file (or file-like) of YAML into a dict. Timestamps must
    be tz-aware (plain YAML would silently convert to naive UTC); a CRD
    document is unwrapped to its ``spec.config``.
    """
    yaml.FullLoader.add_constructor(
        tag="tag:yaml.org,2002:timestamp", constructor=_timestamp_constructor
    )
    if hasattr(config_file, "read"):
        yaml_content = yaml.load(config_file, Loader=yaml.FullLoader)
    else:
        try:
            path_to_config_file = os.path.abspath(config_file)
            with open(path_to_config_file, "r") as yamlfile:
                yaml_content = yaml.load(yamlfile, Loader=yaml.FullLoader)
        except FileNotFoundError:
            raise FileNotFoundError(
                f"Unable to find config file <{path_to_config_file}>"
            )
    if "spec" in yaml_content:
        yaml_content = yaml_content["spec"]["config"]
    return yaml_content


def yaml_filter(data: Any) -> str:
    return yaml.safe_dump(data)


def load_workflow_template(workflow_template: str) -> jinja2.Template:
    """Load a Jinja2 template with the ``yaml`` filter and StrictUndefined."""
    path_to_workflow_template = os.path.abspath(workflow_template)
    template_dir = os.path.dirname(path_to_workflow_template)
    template_env = jinja2.Environment(
        loader=jinja2.FileSystemLoader(template_dir), undefined=jinja2.StrictUndefined
    )
    template_env.filters["yaml"] = yaml_filter
    return template_env.get_template(os.path.basename(workflow_template))


def default_workflow_template() -> str:
    """Path of the packaged TPU fleet workflow template."""
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "resources",
        "tpu-workflow.yml.template",
    )


def default_image_pull_policy(gordo_version: Version) -> str:
    """
    Mutable tags (bare major / major.minor, PRs, latest/stable) must always
    re-pull; fully pinned releases and SHAs may be cached.
    """
    version_type = type(gordo_version)
    if version_type is GordoRelease:
        version = cast(GordoRelease, gordo_version)
        if version.only_major() or version.only_major_minor():
            return "Always"
    elif version_type is GordoPR or version_type is GordoSpecial:
        return "Always"
    return "IfNotPresent"
