"""
Pydantic schemas validating the k8s fragments a config may carry.

Reference parity: gordo/workflow/config_elements/schemas.py — EnvVar,
Volume/VolumeMount, pod runtime and security contexts. Extended with the
TPU runtime block the fleet plane needs (accelerator topology, machines per
slice).
"""

from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field


class GordoModel(BaseModel):
    model_config = ConfigDict(extra="allow", populate_by_name=True)


class EnvVar(GordoModel):
    name: str
    value: Optional[str] = None
    valueFrom: Optional[Dict[str, Any]] = None


class KeyToPath(GordoModel):
    key: str
    path: str
    mode: Optional[int] = None


class ConfigMapVolumeSource(GordoModel):
    name: Optional[str] = None
    items: Optional[List[KeyToPath]] = None
    defaultMode: Optional[int] = None
    optional: Optional[bool] = None


class SecretVolumeSource(GordoModel):
    secretName: Optional[str] = None
    items: Optional[List[KeyToPath]] = None
    defaultMode: Optional[int] = None
    optional: Optional[bool] = None


class PersistentVolumeClaimVolumeSource(GordoModel):
    claimName: str
    readOnly: Optional[bool] = None


class Volume(GordoModel):
    name: str
    configMap: Optional[ConfigMapVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    persistentVolumeClaim: Optional[PersistentVolumeClaimVolumeSource] = None
    emptyDir: Optional[Dict[str, Any]] = None


class VolumeMount(GordoModel):
    name: str
    mountPath: str
    subPath: Optional[str] = None
    readOnly: Optional[bool] = None


class ResourceRequirements(GordoModel):
    requests: Optional[Dict[str, Any]] = None
    limits: Optional[Dict[str, Any]] = None


class SecurityContext(GordoModel):
    runAsUser: Optional[int] = None
    runAsGroup: Optional[int] = None
    runAsNonRoot: Optional[bool] = None
    readOnlyRootFilesystem: Optional[bool] = None
    allowPrivilegeEscalation: Optional[bool] = None


class PodSecurityContext(GordoModel):
    runAsUser: Optional[int] = None
    runAsGroup: Optional[int] = None
    runAsNonRoot: Optional[bool] = None
    fsGroup: Optional[int] = None
    supplementalGroups: Optional[List[int]] = None


class PodRuntime(GordoModel):
    image: Optional[str] = None
    resources: Optional[ResourceRequirements] = None
    env: Optional[List[EnvVar]] = None


class BuilderPodRuntime(PodRuntime):
    remote_logging: Optional[Dict[str, Any]] = None
    volumes: Optional[List[Volume]] = None
    volumeMounts: Optional[List[VolumeMount]] = None


class TpuFleetRuntime(GordoModel):
    """TPU fleet-training runtime: which slice trains how many machines."""

    accelerator_type: str = Field(default="v5litepod-16")
    topology: Optional[str] = None
    machines_per_slice: int = Field(default=1024, ge=1)
    num_slices: int = Field(default=1, ge=1)
    compute_dtype: str = "float32"
    resources: Optional[ResourceRequirements] = None
