"""
NormalizedConfig: a full project YAML → validated Machines + effective
runtime.

Reference parity: gordo/workflow/config_elements/normalized_config.py —
defaults in ``DEFAULT_CONFIG_GLOBALS`` (pod resources, cv_mode, scoring
scaler, four default metrics), globals patched by the user's ``globals``
block, per-machine Machine construction (every machine fully validated,
including the eager model test-build), and influx resources scaling with
machine count.

TPU-native addition: a ``fleet`` runtime block (accelerator type, machines
per slice, num slices) controlling how the training fleet is sharded over
TPU slices — this replaces the reference's one-builder-pod-per-machine
scale knobs while keeping them for the serving plane.
"""

from copy import deepcopy
from typing import Any, Dict, List, Optional

from ...machine import Machine, load_globals_config, load_machine_config
from ..helpers import patch_dict
from .schemas import BuilderPodRuntime, PodRuntime, TpuFleetRuntime


def _calculate_influx_resources(nr_of_machines: int) -> dict:
    """Influx sizing scales linearly with fleet size (reference lines 23-34)."""
    return {
        "requests": {
            "memory": min(3000 + (220 * nr_of_machines), 28000),
            "cpu": min(500 + (10 * nr_of_machines), 4000),
        },
        "limits": {
            "memory": min(3000 + (220 * nr_of_machines), 48000),
            "cpu": 10000 + (20 * nr_of_machines),
        },
    }


class NormalizedConfig:
    """Normalize a project config: globals defaulting + machine validation."""

    DEFAULT_CONFIG_GLOBALS: Dict[str, Any] = {
        "runtime": {
            "reporters": [],
            "server": {
                "resources": {
                    "requests": {"memory": 3000, "cpu": 1000},
                    "limits": {"memory": 6000, "cpu": 2000},
                }
            },
            "prometheus_metrics_server": {
                "resources": {
                    "requests": {"memory": 200, "cpu": 100},
                    "limits": {"memory": 1000, "cpu": 200},
                }
            },
            "builder": {
                "resources": {
                    "requests": {"memory": 3900, "cpu": 1001},
                    "limits": {"memory": 31200, "cpu": 1001},
                },
                "remote_logging": {"enable": False},
            },
            "client": {
                "resources": {
                    "requests": {"memory": 3500, "cpu": 100},
                    "limits": {"memory": 4000, "cpu": 2000},
                },
                "max_instances": 30,
            },
            "influx": {"enable": True},
            # TPU fleet-training plane (no reference analog: replaces
            # per-machine builder pods with sliced fleet jobs)
            "fleet": {
                "accelerator_type": "v5litepod-16",
                "machines_per_slice": 1024,
                "num_slices": 1,
                "compute_dtype": "float32",
            },
        },
        "evaluation": {
            "cv_mode": "full_build",
            "scoring_scaler": "sklearn.preprocessing.MinMaxScaler",
            "metrics": [
                "explained_variance_score",
                "r2_score",
                "mean_squared_error",
                "mean_absolute_error",
            ],
        },
    }

    def __init__(
        self,
        config: Dict[str, Any],
        project_name: str,
        model_builder_env: Optional[dict] = None,
    ):
        if not isinstance(config, dict):
            raise ValueError(f"Config must be a mapping, got {type(config)}")
        default_globals = deepcopy(self.DEFAULT_CONFIG_GLOBALS)
        user_globals = load_globals_config(config.get("globals", {}))
        patched_globals = patch_dict(default_globals, user_globals)
        patched_globals = self._validate_runtime(patched_globals)
        if model_builder_env is not None:
            patched_globals.setdefault("runtime", {}).setdefault("builder", {})[
                "env"
            ] = model_builder_env

        self.project_name = project_name
        machine_configs = config.get("machines") or []
        if not machine_configs:
            raise ValueError("Config has no machines")
        self.machines: List[Machine] = [
            Machine.from_config(
                load_machine_config(machine_config),
                project_name=project_name,
                config_globals=patched_globals,
            )
            for machine_config in machine_configs
        ]
        self.globals: Dict[str, Any] = patched_globals
        self.globals["runtime"]["influx"]["resources"] = _calculate_influx_resources(
            len(self.machines)
        )

    @staticmethod
    def _validate_runtime(config: Dict[str, Any]) -> Dict[str, Any]:
        """Pydantic-validate the known runtime pods (reference lines 171-190)."""
        runtime = config.get("runtime", {})
        if "builder" in runtime:
            BuilderPodRuntime(**runtime["builder"])
        for pod in ("server", "prometheus_metrics_server", "client"):
            if pod in runtime:
                PodRuntime(**runtime[pod])
        if "fleet" in runtime:
            runtime["fleet"] = TpuFleetRuntime(**runtime["fleet"]).model_dump(
                exclude_none=True
            )
        return config
