from .helpers import patch_dict

__all__ = ["NormalizedConfig", "patch_dict"]


def __getattr__(name):
    # Lazy: NormalizedConfig imports Machine which imports patch_dict from
    # this package — eager re-export here would close the circle.
    if name == "NormalizedConfig":
        from .config_elements.normalized_config import NormalizedConfig

        return NormalizedConfig
    raise AttributeError(name)
