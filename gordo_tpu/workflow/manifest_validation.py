"""
Offline schema validation of rendered deployment manifests.

The reference lints its rendered Argo workflow with the real ``argo``
binary inside dockertests (reference
gordo/workflow/workflow_generator/helpers.py:66-99,
tests/conftest.py:258-330). This framework renders plain Kubernetes
documents instead of an Argo Workflow, and this module is the analog
gate: every document a template render emits is checked against a
vendored structural schema for its kind plus cross-document invariants
(selector ↔ pod-template labels, volumeMounts ↔ volumes, scale targets,
duplicate names) — entirely offline, no cluster, no binaries, zero
egress. A typo anywhere in the 900-line template fails the render test
instead of shipping.

The schemas are hand-vendored condensations of the upstream Kubernetes
OpenAPI (and the Prometheus/KEDA/Istio CRD schemas): required fields,
field types, and the full container/pod-template shape are enforced;
unknown *optional* fields are allowed so the schemas don't have to track
every upstream addition. An UNKNOWN KIND is an error — a new kind in the
template must bring a schema with it.
"""

from typing import Any, Dict, Iterable, List, Optional

try:  # pragma: no cover - exercised via validate_manifests in tests
    import jsonschema
except ImportError:  # pragma: no cover - air-gapped minimal image
    jsonschema = None

# DNS-1123 subdomain (object names) and label restrictions.
_NAME_PATTERN = r"^[a-z0-9]([-a-z0-9.]{0,251}[a-z0-9])?$"
_LABEL_VALUE_PATTERN = r"^(|[A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?)$"

_DEFS: Dict[str, Any] = {
    "metadata": {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": {"type": "string", "pattern": _NAME_PATTERN},
            "namespace": {"type": "string", "pattern": _NAME_PATTERN},
            "labels": {
                "type": "object",
                "additionalProperties": {
                    "type": "string",
                    "pattern": _LABEL_VALUE_PATTERN,
                },
            },
            "annotations": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "ownerReferences": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["apiVersion", "kind", "name", "uid"],
                },
            },
        },
    },
    "quantity": {"type": ["string", "integer", "number"]},
    "resources": {
        "type": "object",
        "properties": {
            "limits": {
                "type": "object",
                "additionalProperties": {"$ref": "#/$defs/quantity"},
            },
            "requests": {
                "type": "object",
                "additionalProperties": {"$ref": "#/$defs/quantity"},
            },
        },
    },
    "envVar": {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "value": {"type": "string"},
            "valueFrom": {"type": "object"},
        },
        # exactly one source: a bare name is legal (empty value), but
        # value AND valueFrom together is a typo k8s rejects
        "not": {"required": ["value", "valueFrom"]},
    },
    "container": {
        "type": "object",
        "required": ["name", "image"],
        "properties": {
            "name": {"type": "string", "pattern": _NAME_PATTERN},
            "image": {"type": "string", "minLength": 1},
            "command": {"type": "array", "items": {"type": "string"}},
            "args": {"type": "array", "items": {"type": "string"}},
            "workingDir": {"type": "string"},
            "env": {"type": "array", "items": {"$ref": "#/$defs/envVar"}},
            "envFrom": {"type": "array", "items": {"type": "object"}},
            "ports": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["containerPort"],
                    "properties": {
                        "containerPort": {"$ref": "#/$defs/port"},
                        "name": {"type": "string"},
                    },
                },
            },
            "resources": {"$ref": "#/$defs/resources"},
            "volumeMounts": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name", "mountPath"],
                    "properties": {
                        "name": {"type": "string"},
                        "mountPath": {"type": "string", "minLength": 1},
                        "subPath": {"type": "string"},
                        "readOnly": {"type": "boolean"},
                    },
                },
            },
            "livenessProbe": {"type": "object"},
            "readinessProbe": {"type": "object"},
            "securityContext": {"type": "object"},
            "lifecycle": {"type": "object"},
            "terminationMessagePath": {"type": "string"},
            "terminationMessagePolicy": {
                "enum": ["File", "FallbackToLogsOnError"]
            },
            "imagePullPolicy": {"enum": ["Always", "IfNotPresent", "Never"]},
        },
    },
    "port": {"type": "integer", "minimum": 1, "maximum": 65535},
    "podSpec": {
        "type": "object",
        "required": ["containers"],
        "properties": {
            "containers": {
                "type": "array",
                "minItems": 1,
                "items": {"$ref": "#/$defs/container"},
            },
            "initContainers": {
                "type": "array",
                "items": {"$ref": "#/$defs/container"},
            },
            "volumes": {
                "type": "array",
                "items": {"type": "object", "required": ["name"]},
            },
            "restartPolicy": {"enum": ["Always", "OnFailure", "Never"]},
            "serviceAccountName": {"type": "string"},
            "securityContext": {"type": "object"},
            "nodeSelector": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "tolerations": {"type": "array"},
            "affinity": {"type": "object"},
            "terminationGracePeriodSeconds": {"type": "integer"},
            "imagePullSecrets": {"type": "array"},
        },
    },
    "podTemplate": {
        "type": "object",
        "required": ["spec"],
        "properties": {
            "metadata": {"type": "object"},
            "spec": {"$ref": "#/$defs/podSpec"},
        },
    },
    "labelSelector": {
        "type": "object",
        "properties": {
            "matchLabels": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "matchExpressions": {"type": "array"},
        },
    },
}


def _kind_schema(
    api_versions: Iterable[str], spec: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    schema: Dict[str, Any] = {
        "type": "object",
        "required": ["apiVersion", "kind", "metadata"],
        "properties": {
            "apiVersion": {"enum": list(api_versions)},
            "kind": {"type": "string"},
            "metadata": {"$ref": "#/$defs/metadata"},
        },
        "$defs": _DEFS,
    }
    if spec is not None:
        schema["required"] = schema["required"] + ["spec"]
        schema["properties"]["spec"] = spec
    return schema


#: kind → vendored structural schema. Every kind the workflow template
#: may emit MUST appear here; validate_manifests errors on strangers.
SCHEMAS: Dict[str, Dict[str, Any]] = {
    "ConfigMap": {
        "type": "object",
        "required": ["apiVersion", "kind", "metadata"],
        "properties": {
            "apiVersion": {"const": "v1"},
            "metadata": {"$ref": "#/$defs/metadata"},
            "data": {
                "type": "object",
                "additionalProperties": {"type": "string"},
            },
            "binaryData": {"type": "object"},
            "immutable": {"type": "boolean"},
        },
        "$defs": _DEFS,
    },
    "PersistentVolumeClaim": _kind_schema(
        ["v1"],
        {
            "type": "object",
            "required": ["accessModes", "resources"],
            "properties": {
                "accessModes": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "enum": [
                            "ReadWriteOnce",
                            "ReadOnlyMany",
                            "ReadWriteMany",
                            "ReadWriteOncePod",
                        ]
                    },
                },
                "resources": {
                    "type": "object",
                    "required": ["requests"],
                    "properties": {
                        "requests": {
                            "type": "object",
                            "required": ["storage"],
                            "properties": {
                                "storage": {"$ref": "#/$defs/quantity"}
                            },
                        }
                    },
                },
                "storageClassName": {"type": "string"},
                "volumeMode": {"enum": ["Filesystem", "Block"]},
            },
        },
    ),
    "Service": _kind_schema(
        ["v1"],
        {
            "type": "object",
            "required": ["ports"],
            "properties": {
                "ports": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "type": "object",
                        "required": ["port"],
                        "properties": {
                            "port": {"$ref": "#/$defs/port"},
                            "targetPort": {"type": ["integer", "string"]},
                            "name": {"type": "string"},
                            "protocol": {"enum": ["TCP", "UDP", "SCTP"]},
                        },
                    },
                },
                "selector": {
                    "type": "object",
                    "additionalProperties": {"type": "string"},
                },
                "type": {
                    "enum": [
                        "ClusterIP",
                        "NodePort",
                        "LoadBalancer",
                        "ExternalName",
                    ]
                },
                "clusterIP": {"type": "string"},
            },
        },
    ),
    "Job": _kind_schema(
        ["batch/v1"],
        {
            "type": "object",
            "required": ["template"],
            "properties": {
                "template": {"$ref": "#/$defs/podTemplate"},
                "backoffLimit": {"type": "integer", "minimum": 0},
                "activeDeadlineSeconds": {"type": "integer"},
                "ttlSecondsAfterFinished": {"type": "integer"},
                "completions": {"type": "integer"},
                "parallelism": {"type": "integer"},
            },
        },
    ),
    "Deployment": _kind_schema(
        ["apps/v1"],
        {
            "type": "object",
            "required": ["selector", "template"],
            "properties": {
                "replicas": {"type": "integer", "minimum": 0},
                "selector": {"$ref": "#/$defs/labelSelector"},
                "template": {"$ref": "#/$defs/podTemplate"},
                "strategy": {"type": "object"},
                "revisionHistoryLimit": {"type": "integer"},
            },
        },
    ),
    "StatefulSet": _kind_schema(
        ["apps/v1"],
        {
            "type": "object",
            "required": ["selector", "template", "serviceName"],
            "properties": {
                "serviceName": {"type": "string"},
                "replicas": {"type": "integer", "minimum": 0},
                "selector": {"$ref": "#/$defs/labelSelector"},
                "template": {"$ref": "#/$defs/podTemplate"},
                "volumeClaimTemplates": {"type": "array"},
            },
        },
    ),
    "HorizontalPodAutoscaler": _kind_schema(
        ["autoscaling/v2"],
        {
            "type": "object",
            "required": ["scaleTargetRef", "maxReplicas"],
            "properties": {
                "scaleTargetRef": {
                    "type": "object",
                    "required": ["apiVersion", "kind", "name"],
                },
                "minReplicas": {"type": "integer", "minimum": 1},
                "maxReplicas": {"type": "integer", "minimum": 1},
                "metrics": {"type": "array"},
                "behavior": {"type": "object"},
            },
        },
    ),
    "ServiceMonitor": _kind_schema(
        ["monitoring.coreos.com/v1"],
        {
            "type": "object",
            "required": ["selector", "endpoints"],
            "properties": {
                "selector": {"$ref": "#/$defs/labelSelector"},
                "endpoints": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "type": "object",
                        "properties": {
                            "port": {"type": "string"},
                            "path": {"type": "string"},
                            "interval": {"type": "string"},
                        },
                    },
                },
                "namespaceSelector": {"type": "object"},
            },
        },
    ),
    "ScaledObject": _kind_schema(
        ["keda.sh/v1alpha1"],
        {
            "type": "object",
            "required": ["scaleTargetRef", "triggers"],
            "properties": {
                "scaleTargetRef": {
                    "type": "object",
                    "required": ["name"],
                },
                "minReplicaCount": {"type": "integer"},
                "maxReplicaCount": {"type": "integer"},
                "triggers": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "type": "object",
                        "required": ["type", "metadata"],
                    },
                },
            },
        },
    ),
    "VirtualService": _kind_schema(
        [
            "networking.istio.io/v1",
            "networking.istio.io/v1beta1",
            "networking.istio.io/v1alpha3",
        ],
        {
            "type": "object",
            "required": ["http"],
            "properties": {
                "hosts": {"type": "array", "items": {"type": "string"}},
                "gateways": {"type": "array", "items": {"type": "string"}},
                "http": {
                    "type": "array",
                    "minItems": 1,
                    "items": {
                        "type": "object",
                        "required": ["route"],
                        "properties": {
                            "match": {"type": "array"},
                            "route": {
                                "type": "array",
                                "minItems": 1,
                                "items": {
                                    "type": "object",
                                    "required": ["destination"],
                                },
                            },
                            "rewrite": {"type": "object"},
                            "timeout": {"type": "string"},
                            "retries": {"type": "object"},
                        },
                    },
                },
            },
        },
    ),
    # The per-machine Model custom resource this project's controller
    # consumes (template :911); its spec is the machine config document.
    "Model": _kind_schema(
        ["equinor.com/v1", "gordo.equinor.com/v1"],
        {"type": "object", "required": ["config"]},
    ),
}


def _pod_template_errors(
    where: str,
    template: Dict[str, Any],
    extra_volumes: Iterable[str] = (),
) -> List[str]:
    """Invariants jsonschema can't express: mounts must name declared
    volumes (``extra_volumes`` carries a StatefulSet's
    volumeClaimTemplates, which mounts may also reference); env and
    container names must be unique."""
    errors: List[str] = []
    spec = template.get("spec") or {}
    volumes = {v.get("name") for v in spec.get("volumes") or []}
    volumes.update(extra_volumes)
    containers = list(spec.get("containers") or []) + list(
        spec.get("initContainers") or []
    )
    names = [c.get("name") for c in containers]
    if len(names) != len(set(names)):
        errors.append(f"{where}: duplicate container names {names}")
    for container in containers:
        cwhere = f"{where}/{container.get('name')}"
        for mount in container.get("volumeMounts") or []:
            if mount.get("name") not in volumes:
                errors.append(
                    f"{cwhere}: volumeMount {mount.get('name')!r} has no "
                    f"matching volume (declared: {sorted(filter(None, volumes))})"
                )
        env_names = [e.get("name") for e in container.get("env") or []]
        if len(env_names) != len(set(env_names)):
            duplicates = sorted(
                {n for n in env_names if env_names.count(n) > 1}
            )
            errors.append(f"{cwhere}: duplicate env names {duplicates}")
    return errors


def _selector_matches(selector: Dict[str, Any], labels: Dict[str, str]) -> bool:
    selector = selector or {}
    if "matchLabels" in selector or "matchExpressions" in selector:
        match = selector.get("matchLabels") or {}
        expressions = selector.get("matchExpressions") or []
    else:  # a plain label map (Service spec.selector)
        match, expressions = selector, []
    if not all(labels.get(k) == v for k, v in match.items()):
        return False
    for expr in expressions:
        key = expr.get("key")
        operator = expr.get("operator")
        values = expr.get("values") or []
        if operator == "In":
            if labels.get(key) not in values:
                return False
        elif operator == "NotIn":
            if key in labels and labels[key] in values:
                return False
        elif operator == "Exists":
            if key not in labels:
                return False
        elif operator == "DoesNotExist":
            if key in labels:
                return False
        # unknown operators are left to the API server's own validation
    return True


def validate_manifests(docs: Iterable[Optional[Dict[str, Any]]]) -> List[str]:
    """
    Validate rendered manifest documents; returns a list of error strings
    (empty = valid). Checks, in order:

    1. every non-empty document has a known ``kind`` and validates
       against its vendored schema;
    2. no two documents share (kind, namespace, name);
    3. workload selectors match their own pod-template labels;
    4. Service selectors, HPA/ScaledObject scale targets point at an
       emitted workload;
    5. pod-level invariants (mounts ↔ volumes, unique env/container
       names) for every pod template.

    Requires ``jsonschema`` (baked into the runtime image); returns a
    single explanatory error if it is unavailable rather than silently
    passing.
    """
    if jsonschema is None:  # pragma: no cover
        return ["jsonschema is not installed; manifest validation cannot run"]

    errors: List[str] = []
    seen: set = set()
    workloads: Dict[str, Dict[str, Any]] = {}  # name → pod labels, for refs
    documents = [d for d in docs if d]

    for position, doc in enumerate(documents):
        kind = doc.get("kind")
        name = (doc.get("metadata") or {}).get("name", f"<doc {position}>")
        where = f"{kind}/{name}"
        if kind not in SCHEMAS:
            errors.append(
                f"document {position} ({where}): unknown kind {kind!r} — "
                "add a vendored schema to manifest_validation.SCHEMAS"
            )
            continue
        validator = jsonschema.Draft202012Validator(SCHEMAS[kind])
        for error in validator.iter_errors(doc):
            path = ".".join(str(p) for p in error.absolute_path)
            errors.append(f"{where}: {path or '<root>'}: {error.message}")

        key = (kind, (doc.get("metadata") or {}).get("namespace"), name)
        if key in seen:
            errors.append(f"{where}: duplicate (kind, namespace, name)")
        seen.add(key)

        spec = doc.get("spec") or {}
        template = spec.get("template")
        if isinstance(template, dict):
            claim_names = [
                ((t.get("metadata") or {}).get("name"))
                for t in spec.get("volumeClaimTemplates") or []
            ]
            errors.extend(_pod_template_errors(where, template, claim_names))
            pod_labels = (template.get("metadata") or {}).get("labels") or {}
            if kind in ("Deployment", "StatefulSet"):
                workloads[name] = pod_labels
                if not _selector_matches(spec.get("selector") or {}, pod_labels):
                    errors.append(
                        f"{where}: selector does not match its own pod-"
                        f"template labels {sorted(pod_labels)}"
                    )

    for doc in documents:
        kind, spec = doc.get("kind"), doc.get("spec") or {}
        name = (doc.get("metadata") or {}).get("name")
        where = f"{kind}/{name}"
        if kind == "Service" and spec.get("selector"):
            if not any(
                _selector_matches({"matchLabels": spec["selector"]}, labels)
                for labels in workloads.values()
            ):
                errors.append(
                    f"{where}: selector {spec['selector']} matches no "
                    "emitted Deployment/StatefulSet pod template"
                )
        elif kind in ("HorizontalPodAutoscaler", "ScaledObject"):
            target = (spec.get("scaleTargetRef") or {}).get("name")
            if target not in workloads:
                errors.append(
                    f"{where}: scaleTargetRef {target!r} is not an emitted "
                    f"workload (have: {sorted(workloads)})"
                )
    return errors
