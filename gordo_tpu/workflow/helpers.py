"""
Config-overlay helper (reference:
gordo/workflow/workflow_generator/helpers.py:16-45, reimplemented without
dictdiffer): paths in the patch are added or replace existing values;
nothing is ever removed.
"""

import copy
from typing import Any, Dict


def patch_dict(original_dict: dict, patch_dictionary: dict) -> dict:
    """
    Overlay ``patch_dictionary`` on top of ``original_dict`` recursively.

    >>> patch_dict({"highKey": {"lowkey1": 1, "lowkey2": 2}}, {"highKey": {"lowkey1": 10}})
    {'highKey': {'lowkey1': 10, 'lowkey2': 2}}
    >>> patch_dict({"highKey": {"lowkey1": 1, "lowkey2": 2}}, {"highKey": {"lowkey3": 3}})
    {'highKey': {'lowkey1': 1, 'lowkey2': 2, 'lowkey3': 3}}
    >>> patch_dict({"highKey": {"lowkey1": 1, "lowkey2": 2}}, {"highKey2": 4})
    {'highKey': {'lowkey1': 1, 'lowkey2': 2}, 'highKey2': 4}
    """
    result: Dict[str, Any] = copy.deepcopy(original_dict)

    def overlay(base: dict, patch: dict):
        for key, value in patch.items():
            if (
                key in base
                and isinstance(base[key], dict)
                and isinstance(value, dict)
            ):
                overlay(base[key], value)
            else:
                base[key] = copy.deepcopy(value)

    overlay(result, patch_dictionary)
    return result
