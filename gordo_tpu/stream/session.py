"""
Stream sessions: the durable server-side half of one logical stream.

A session outlives any single HTTP exchange — that is the whole point.
Ingest POSTs land rows in the session's per-machine :class:`~.ring.RowRing`
buffers; scored windows and control frames append to its
:class:`~.ring.EventRing` outbox; any number of SSE subscriptions
(including a reconnect after a dropped socket) read the outbox from a
cursor. All mutable state is guarded by ONE lock per session
(``_wake`` — a Condition wrapping it — doubles as the subscriber
wakeup), so the plane's lock graph stays a star: plane registry lock →
session lock, never the reverse.

Robustness contract carried here:

- **resume**: ``subscribe(cursor=N)`` replays retained events with
  ``seq > N``; if the outbox already evicted past the cursor the first
  frames say exactly how many events were missed (``shed`` with scope
  ``outbox``) — a reconnect is never a silent gap.
- **backpressure**: both rings are bounded; ingest overflow sheds
  oldest-first with a ``shed`` (scope ``ring``) control frame, outbox
  overflow surfaces as the reader's ``shed`` (scope ``outbox``) frame.
- **drain/close**: :meth:`close` appends a terminal ``drain``/``end``
  frame and wakes every subscriber; a subscription always ends with a
  terminal frame on a graceful shutdown (EOF without one means the
  connection itself died → reconnect with the cursor).
"""

import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..utils.faults import FaultInjected, fault_point
from .events import StreamEvent, encode_sse, heartbeat_frame
from .ring import EventRing, RowRing

__all__ = ["MachineChannel", "StreamSession"]


class MachineChannel:
    """One machine's ingest state inside a session: its row ring plus
    the per-machine counters the status route and the soak bench audit
    (``ingested == scored + pending + shed`` is the zero-gap
    invariant)."""

    __slots__ = (
        "name",
        "ring",
        "rows_in",
        "rows_scored",
        "rows_failed",
        "windows_scored",
        "score_errors",
        "quarantine_notified",
        "last_score_lag_ms",
        "last_scored_ts",
    )

    def __init__(self, name: str, ring_rows: int):
        self.name = name
        self.ring = RowRing(ring_rows)
        self.rows_in = 0
        self.rows_scored = 0
        self.rows_failed = 0
        self.windows_scored = 0
        self.score_errors = 0
        #: True between the ``quarantined`` frame and the member's
        #: ``recovered`` frame — dedupes per-window quarantine noise and
        #: tells a fresh subscription to replay the notice immediately
        self.quarantine_notified = False
        #: ingest→scored wall-clock lag of this machine's most recent
        #: flush (None until the first window scores) and when it scored
        #: — the status route's per-machine freshness view
        self.last_score_lag_ms: Optional[float] = None
        self.last_scored_ts: Optional[float] = None

    def stats(self) -> Dict[str, Any]:
        oldest_ts = self.ring.oldest_ts
        return {
            "rows_in": self.rows_in,
            "rows_scored": self.rows_scored,
            "rows_failed": self.rows_failed,
            "rows_pending": self.ring.pending_rows,
            "rows_shed": self.ring.shed_rows,
            "windows_scored": self.windows_scored,
            "score_errors": self.score_errors,
            "quarantined": self.quarantine_notified,
            "last_score_lag_ms": self.last_score_lag_ms,
            "watermark_delay_ms": (
                None
                if oldest_ts is None
                else round(max(0.0, time.time() - oldest_ts) * 1000.0, 3)
            ),
        }


class StreamSession:
    """One stream id's rings, outbox, and subscriber bookkeeping."""

    def __init__(
        self,
        project: str,
        stream_id: str,
        collection_dir: str,
        ring_rows: int,
        outbox_events: int,
    ):
        self.project = project
        self.stream_id = stream_id
        #: the ANCHOR collection dir (the env var's value at session
        #: creation) — routing to the served revision happens per scoring
        #: flush, so a hot-swap mid-stream picks up the new revision at
        #: the next window, never mid-window
        self.collection_dir = collection_dir
        self.ring_rows = ring_rows
        self._wake = threading.Condition()
        self.channels: Dict[str, MachineChannel] = {}
        self.outbox = EventRing(outbox_events)
        self.closed = False
        self.last_used = time.monotonic()
        self._subscribers = 0
        self.emit_dropped = 0
        #: emit-site drops not yet surfaced as a ``shed`` frame
        self._emit_shed_pending = 0
        #: (trace_id, span_id) of recent ``stream_ingest`` spans not yet
        #: claimed by a flush — the scorer links its ``stream_score``
        #: span back to the ingests it drained (the batch-link pattern).
        #: Bounded: a stalled scorer must not grow this without limit.
        self._ingest_spans: List[Tuple[str, str]] = []
        #: rows_shed total already reported via :meth:`shed_delta` —
        #: keeps per-flush span/rollup shed attrs additive
        self._shed_reported = 0

    # -- ingest side ---------------------------------------------------------

    def touch(self) -> None:
        with self._wake:
            self.last_used = time.monotonic()

    def idle_for(self, now: float) -> float:
        with self._wake:
            return now - self.last_used

    def channel(self, name: str) -> MachineChannel:
        with self._wake:
            chan = self.channels.get(name)
            if chan is None:
                chan = self.channels[name] = MachineChannel(
                    name, self.ring_rows
                )
            return chan

    def append_rows(self, name: str, frame: Any) -> Tuple[int, int]:
        """Land decoded rows for ``name``; returns ``(first_seq, shed)``
        and emits the backpressure control frame when rows were shed."""
        with self._wake:
            chan = self.channels.get(name)
            if chan is None:
                chan = self.channels[name] = MachineChannel(
                    name, self.ring_rows
                )
            first_seq, shed = chan.ring.append(frame)
            chan.rows_in += int(len(frame))
            self.last_used = time.monotonic()
        if shed:
            self.emit(
                StreamEvent(
                    "shed",
                    {
                        "scope": "ring",
                        "machine": name,
                        "dropped": shed,
                        "rows_shed_total": chan.ring.shed_rows,
                    },
                )
            )
        return first_seq, shed

    def shed_delta(self) -> int:
        """Ring-shed rows since the last call — the per-flush ``shed``
        attribute on ``stream_score`` spans (deltas, not cumulative
        totals, so rollups can sum spans without double counting)."""
        with self._wake:
            total = sum(
                chan.ring.shed_rows for chan in self.channels.values()
            )
            delta = total - self._shed_reported
            self._shed_reported = total
            return max(0, delta)

    def note_ingest_span(self, trace_id: str, span_id: str) -> None:
        """Remember an ingest span's context for the next flush's OTel
        links (oldest dropped past a small bound)."""
        with self._wake:
            self._ingest_spans.append((trace_id, span_id))
            if len(self._ingest_spans) > 64:
                del self._ingest_spans[:-64]

    def drain_ingest_spans(self) -> List[Tuple[str, str]]:
        """Claim (and clear) the ingest-span contexts accumulated since
        the last flush."""
        with self._wake:
            spans, self._ingest_spans = self._ingest_spans, []
            return spans

    def latest_seq(self) -> int:
        """The consumer cursor that would catch everything emitted so
        far (the ingest ack's ``cursor`` field)."""
        with self._wake:
            return self.outbox.latest_seq

    def machine_names(self) -> List[str]:
        with self._wake:
            return sorted(self.channels)

    def pending_machines(self, window_rows: int) -> List[str]:
        """Machines with at least one full watermark window buffered —
        the flush's breaker-gate worklist (sorted for determinism)."""
        with self._wake:
            return sorted(
                name
                for name, chan in self.channels.items()
                if chan.ring.pending_rows >= window_rows
            )

    def cut_windows(
        self,
        window_rows: int,
        skip: Sequence[str] = (),
        snap: Optional[Any] = None,
    ) -> Dict[str, Tuple[List[Any], int, int, int, float]]:
        """Pop pending full watermark windows: ``{machine: (chunks,
        first_seq, last_seq, windows, oldest_ts)}``. Multiple pending
        windows for a machine come out as ONE contiguous span (scored in
        one fused call, counted as ``windows``); ``oldest_ts`` is the
        ingest wall-clock of the span's oldest row — the flush's
        ingest→scored lag anchor. Machines in ``skip`` (quarantined
        members) keep their rows buffered — their ring keeps absorbing
        (and, under pressure, shedding oldest-first) until the breaker's
        half-open probe lets scoring resume.

        ``snap`` (``pending_rows -> rows_to_cut``, a whole-window
        multiple — :func:`gordo_tpu.planner.ladder.snap_rows`) quantizes
        big multi-window spans onto the serve row ladder so backlog
        flushes reuse the request plane's compiled shapes; the un-taken
        remainder stays buffered (still counted pending — the zero-gap
        invariant is untouched) and rides the next watermark flush."""
        out: Dict[str, Tuple[List[Any], int, int, int, float]] = {}
        with self._wake:
            for name, chan in self.channels.items():
                if name in skip:
                    continue
                pending = chan.ring.pending_rows
                if snap is not None:
                    take_rows = int(snap(pending))
                    # defensive: a snap that is not a whole-window
                    # multiple would break the span accounting
                    take_rows -= take_rows % window_rows
                else:
                    take_rows = (pending // window_rows) * window_rows
                windows = take_rows // window_rows
                if windows <= 0:
                    continue
                taken = chan.ring.take(windows * window_rows)
                if taken is None:  # pragma: no cover - guarded by the //
                    continue
                chunks, first_seq, last_seq, oldest_ts = taken
                out[name] = (chunks, first_seq, last_seq, windows, oldest_ts)
        return out

    # -- emit side -----------------------------------------------------------

    def emit(
        self, event: StreamEvent, fault_key: Optional[str] = None
    ) -> Optional[int]:
        """Append one event to the outbox and wake subscribers; the
        ``stream_emit`` fault site can drop it (counted, surfaced as a
        deferred ``shed`` scope-``emit`` frame) — an emit failure never
        propagates into ingest or scoring."""
        try:
            fault_point(
                "stream_emit",
                fault_key
                if fault_key is not None
                else f"{self.stream_id}:{event.kind}",
            )
        except FaultInjected:
            with self._wake:
                self.emit_dropped += 1
                self._emit_shed_pending += 1
            return None
        return self._append(event)

    def _append(self, event: StreamEvent) -> int:
        """The unfaulted append: terminal frames and shed notices use it
        directly so a drill targeting ``stream_emit`` can never suppress
        its own loss report or a clean close."""
        with self._wake:
            if self._emit_shed_pending and event.kind != "shed":
                pending = self._emit_shed_pending
                self._emit_shed_pending = 0
                self.outbox.append(
                    StreamEvent(
                        "shed",
                        {"scope": "emit", "dropped": pending},
                    )
                )
            seq = self.outbox.append(event)
            self.last_used = time.monotonic()
            self._wake.notify_all()
            return seq

    def close(self, kind: str = "end", reason: str = "") -> None:
        """Terminal frame + closed flag + subscriber wakeup. Idempotent:
        the first close wins, later calls are no-ops (a drain racing a
        client DELETE must not emit two terminals)."""
        with self._wake:
            if self.closed:
                return
            self.closed = True
        self._append(StreamEvent(kind, {"reason": reason} if reason else {}))
        with self._wake:
            self._wake.notify_all()

    # -- subscribe side ------------------------------------------------------

    @property
    def subscribers(self) -> int:
        with self._wake:
            return self._subscribers

    def subscribe(
        self,
        cursor: int = 0,
        heartbeat_s: float = 15.0,
        max_events: Optional[int] = None,
        idle_timeout_s: Optional[float] = None,
        prelude: Sequence[StreamEvent] = (),
    ) -> Iterator[str]:
        """Yield SSE frames from ``cursor`` until a terminal frame (or
        the optional ``max_events``/``idle_timeout_s`` bounds, which
        exist so tests and the bench can run against a finite response).

        The first frame is always ``open`` (un-id'd), then the caller's
        ``prelude`` frames (e.g. the immediate quarantine notices for a
        reconnecting consumer), then the replay/live tail. Waits happen
        on the session condition with a ``heartbeat_s`` bound, so an
        idle stream stays alive through proxies and a ``close`` wakes
        every subscriber immediately.
        """
        with self._wake:
            self._subscribers += 1
            self.last_used = time.monotonic()
            latest = self.outbox.latest_seq
            closed = self.closed
        emitted = 0
        try:
            yield encode_sse(
                None,
                StreamEvent(
                    "open",
                    {
                        "stream": self.stream_id,
                        "cursor": cursor,
                        "latest_seq": latest,
                        "closed": closed,
                    },
                ),
            )
            for event in prelude:
                yield encode_sse(None, event)
            idle_since = time.monotonic()
            while True:
                with self._wake:
                    batch, missed = self.outbox.since(cursor)
                    if not batch and not self.closed:
                        self._wake.wait(timeout=heartbeat_s)
                        batch, missed = self.outbox.since(cursor)
                    session_closed = self.closed
                    pending_rows = sum(
                        chan.ring.pending_rows
                        for chan in self.channels.values()
                    )
                if missed:
                    # the consumer was slower than the outbox ring (or
                    # reconnected with an evicted cursor): say so, then
                    # continue from the oldest retained event
                    yield encode_sse(
                        None,
                        StreamEvent(
                            "shed",
                            {"scope": "outbox", "dropped": missed},
                        ),
                    )
                if not batch:
                    if session_closed:
                        # closed and fully drained (terminal already
                        # consumed by this subscriber via an earlier
                        # batch, or it was evicted): end cleanly
                        return
                    if (
                        idle_timeout_s is not None
                        and time.monotonic() - idle_since >= idle_timeout_s
                    ):
                        return
                    # heartbeats carry the consumer's cursor and the
                    # rings' pending-row depth: an idle consumer watches
                    # backpressure build without polling the status route
                    yield heartbeat_frame(
                        cursor=cursor, pending_rows=pending_rows
                    )
                    continue
                for seq, event in batch:
                    cursor = seq
                    yield encode_sse(seq, event)
                    emitted += 1
                    if event.terminal:
                        return
                    if max_events is not None and emitted >= max_events:
                        return
                idle_since = time.monotonic()
        finally:
            with self._wake:
                self._subscribers -= 1

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._wake:
            machines = {
                name: chan.stats() for name, chan in self.channels.items()
            }
            lags = sorted(
                stats["last_score_lag_ms"]
                for stats in machines.values()
                if stats["last_score_lag_ms"] is not None
            )
            delays = [
                stats["watermark_delay_ms"]
                for stats in machines.values()
                if stats["watermark_delay_ms"] is not None
            ]
            lag_summary = {
                "score_lag_p50_ms": (
                    lags[len(lags) // 2] if lags else None
                ),
                "score_lag_max_ms": lags[-1] if lags else None,
                "watermark_delay_max_ms": (
                    max(delays) if delays else None
                ),
            }
            accounting = {
                key: sum(stats[key] for stats in machines.values())
                for key in (
                    "rows_in",
                    "rows_scored",
                    "rows_failed",
                    "rows_pending",
                    "rows_shed",
                )
            }
            # the zero-gap invariant, checked live: every ingested row
            # is scored, failed, pending, or honestly shed — nonzero
            # here is a bug, not load
            accounting["gap"] = accounting["rows_in"] - (
                accounting["rows_scored"]
                + accounting["rows_failed"]
                + accounting["rows_pending"]
                + accounting["rows_shed"]
            )
            return {
                "lag": lag_summary,
                "accounting": accounting,
                "stream": self.stream_id,
                "project": self.project,
                "closed": self.closed,
                "subscribers": self._subscribers,
                "latest_seq": self.outbox.latest_seq,
                "events_dropped_outbox": self.outbox.dropped,
                "events_dropped_emit": self.emit_dropped,
                "machines": machines,
            }
