"""
Process-global streaming-plane telemetry accumulator.

The streaming plane's hot paths (ingest POSTs, watermark flushes) are
lock-striped per session; Prometheus scrapes and the status routes are
not on those paths. This module is the meeting point: ingest and the
scorer fold their observations into ONE process-global accumulator
under a dedicated lock (never held while scoring), and the scrape-time
``StreamPlaneCollector`` (``server/prometheus/metrics.py``) plus
``/stream/status`` read a consistent snapshot.

Cardinality is bounded by construction (the PR 8/9 exposition
contract): totals and two fixed-bucket histograms — flush duration and
ingest→scored lag — with NO per-machine or per-stream labels. The
per-machine detail lives on the status route and in the span trace,
where cardinality is the reader's choice, not the scrape's.

The histograms share ``telemetry.aggregate``'s fixed latency edges so
a scrape-side bucket and a rollup-side bucket always mean the same
thing.
"""

import threading
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "StreamTelemetry",
    "stream_telemetry",
    "reset_stream_telemetry",
    "lag_bucket_counts",
]


def _lag_edges() -> List[float]:
    from ..telemetry.aggregate import LATENCY_BUCKETS_MS

    return list(LATENCY_BUCKETS_MS)


def lag_bucket_counts(
    lags_ms: Sequence[float], weights: Optional[Sequence[int]] = None
) -> List[int]:
    """Bucket ``lags_ms`` observations (optionally row-weighted) into
    the shared fixed edges; the trailing slot is the overflow bucket.
    This is the compact per-flush shape ``stream_score`` spans carry so
    rollups keep a true rows-under-threshold distribution without
    hauling per-machine lists around."""
    edges = _lag_edges()
    counts = [0] * (len(edges) + 1)
    for i, value in enumerate(lags_ms):
        weight = int(weights[i]) if weights is not None else 1
        slot = len(edges)
        for j, edge in enumerate(edges):
            if value <= edge:
                slot = j
                break
        counts[slot] += weight
    return counts


class _Histogram:
    """Fixed-bucket histogram (count/sum + overflow slot), guarded by
    the owning accumulator's lock."""

    __slots__ = ("edges", "counts", "count", "sum_value")

    def __init__(self, edges: Sequence[float]):
        self.edges = list(edges)
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum_value = 0.0

    def add(self, value: float, weight: int = 1) -> None:
        slot = len(self.edges)
        for i, edge in enumerate(self.edges):
            if value <= edge:
                slot = i
                break
        self.counts[slot] += weight
        self.count += weight
        self.sum_value += value * weight

    def snapshot(self) -> Dict[str, Any]:
        return {
            "buckets_ms": list(self.edges),
            "counts": list(self.counts),
            "count": self.count,
            "sum_ms": round(self.sum_value, 3),
        }


class StreamTelemetry:
    """Counters + histograms for one process's streaming plane."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rows_in = 0
        self.rows_scored = 0
        self.rows_failed = 0
        self.rows_shed = 0
        self.flushes = 0
        self.ingest_batches = 0
        self._flush_ms = _Histogram(_lag_edges())
        self._lag_ms = _Histogram(_lag_edges())

    def observe_ingest(self, rows: int, batches: int = 1) -> None:
        with self._lock:
            self.rows_in += int(rows)
            self.ingest_batches += int(batches)

    def observe_flush(
        self,
        duration_s: float,
        rows_scored: int,
        rows_failed: int,
        rows_shed: int,
        lags_ms: Sequence[float] = (),
        lag_weights: Optional[Sequence[int]] = None,
    ) -> None:
        """One watermark flush: wall duration, the accounting deltas,
        and the per-machine ingest→scored lags (row-weighted when
        weights are given, so the lag histogram answers "what fraction
        of ROWS scored fresh", not "what fraction of machines")."""
        with self._lock:
            self.flushes += 1
            self.rows_scored += int(rows_scored)
            self.rows_failed += int(rows_failed)
            self.rows_shed += int(rows_shed)
            self._flush_ms.add(duration_s * 1000.0)
            for i, lag in enumerate(lags_ms):
                weight = (
                    int(lag_weights[i]) if lag_weights is not None else 1
                )
                self._lag_ms.add(float(lag), weight)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rows_in": self.rows_in,
                "rows_scored": self.rows_scored,
                "rows_failed": self.rows_failed,
                "rows_shed": self.rows_shed,
                "flushes": self.flushes,
                "ingest_batches": self.ingest_batches,
                "flush_ms": self._flush_ms.snapshot(),
                "lag_ms": self._lag_ms.snapshot(),
            }


_telemetry = StreamTelemetry()
_telemetry_lock = threading.Lock()


def stream_telemetry() -> StreamTelemetry:
    return _telemetry


def reset_stream_telemetry() -> StreamTelemetry:
    """Fresh accumulator (tests, post-fork, bench phases)."""
    global _telemetry
    with _telemetry_lock:
        _telemetry = StreamTelemetry()
        return _telemetry
