"""
The streaming plane's event vocabulary and SSE encoding.

Everything a stream consumer ever sees is a server-sent event with an
``id:`` (the session's outbox sequence number — reconnect cursors are
these ids), an ``event:`` kind, and a one-line JSON ``data:`` payload.
The kinds form the stream twin of the request/response error ladder in
``docs/serving.md`` (PR 15):

========== ============================================================
kind       meaning
========== ============================================================
open       first frame of every subscription: cursor position, replayed
           event count, and the session's live counters
anomaly    a scored watermark window: machine, ``first_seq``/
           ``last_seq`` row span, rows/windows, residual stats, and the
           revision that scored it (hot-swap visibility)
shed       backpressure: oldest-first drops happened — ``scope`` is
           ``ring`` (ingest rows), ``outbox`` (emitted events a slow or
           reconnecting consumer missed), or ``emit`` (events dropped at
           the emit fault site); carries the drop count
quarantined a member's circuit breaker is open: its windows are NOT
           scored; ``retry_after_s`` says when the next probe may run.
           Innocent machines on the same stream keep scoring.
recovered  a previously quarantined member scored cleanly again
           (half-open probe success closed its breaker)
error      a machine's window failed to score (contained: that window
           only, that machine only)
drain      terminal: the server is shutting down gracefully
           (``drain_and_stop``); the stream is complete, reconnect later
end        terminal: the session was closed explicitly (client DELETE)
========== ============================================================

``drain``/``end`` are **terminal**: they are the last frame a
subscription yields before the server closes the response cleanly — a
consumer that sees EOF *without* one knows the connection died and
should reconnect with its cursor.

Idle subscriptions additionally receive **heartbeat comment frames**
(``: keep-alive {"cursor": N, "pending": R}``) between events: not part
of the event vocabulary (SSE ``:`` comments are invisible to spec
parsers and never carry an ``id:``), but the payload lets an idle
consumer watch its cursor and the rings' pending-row depth — rising
``pending`` is backpressure building toward a ``shed`` — without
polling the status route.

>>> evt = StreamEvent("anomaly", {"machine": "m-1", "rows": 4})
>>> print(encode_sse(3, evt), end="")
id: 3
event: anomaly
data: {"machine": "m-1", "rows": 4}
<BLANKLINE>
"""

import json
from typing import Any, Dict, Optional

__all__ = [
    "StreamEvent",
    "TERMINAL_KINDS",
    "encode_sse",
    "heartbeat_frame",
    "SSE_CONTENT_TYPE",
]

SSE_CONTENT_TYPE = "text/event-stream"

#: kinds after which a subscription ends (clean close follows)
TERMINAL_KINDS = ("drain", "end")


class StreamEvent:
    """One emitted frame: a ``kind`` from the table above plus its JSON
    payload. Sequence numbers are assigned by the session outbox at
    append time, not here — the same event object is never reused."""

    __slots__ = ("kind", "data")

    def __init__(self, kind: str, data: Optional[Dict[str, Any]] = None):
        self.kind = kind
        self.data = data or {}

    @property
    def terminal(self) -> bool:
        return self.kind in TERMINAL_KINDS

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"StreamEvent({self.kind!r}, {self.data!r})"


def encode_sse(seq: Optional[int], event: StreamEvent) -> str:
    """One wire frame: ``id``/``event``/``data`` lines + blank-line
    terminator. ``data`` is a single line by construction (compact JSON
    with no embedded newlines), so no multi-line ``data:`` splitting is
    needed. ``seq=None`` omits the ``id:`` line — used for
    subscription-local frames (the ``open`` prelude, replayed
    quarantine notices) that must not advance the consumer's
    ``Last-Event-ID`` cursor."""
    payload = json.dumps(event.data, separators=(", ", ": "), default=str)
    head = f"id: {seq}\n" if seq is not None else ""
    return f"{head}event: {event.kind}\ndata: {payload}\n\n"


def heartbeat_frame(
    cursor: Optional[int] = None, pending_rows: Optional[int] = None
) -> str:
    """An SSE comment frame: keeps idle connections alive through
    proxies without advancing the consumer's cursor.

    When the session knows them, the comment carries the subscriber's
    ``cursor`` and the rings' total ``pending`` row depth — an idle
    consumer observes backpressure building (pending climbing toward
    the ring bound means shedding is next) without polling the status
    route. Still a comment frame: parsers that ignore ``:`` lines per
    the SSE spec are unaffected, and ``Last-Event-ID`` never advances.
    """
    if cursor is None and pending_rows is None:
        return ": keep-alive\n\n"
    payload = json.dumps(
        {"cursor": cursor, "pending": pending_rows},
        separators=(", ", ": "),
    )
    return f": keep-alive {payload}\n\n"
