"""
Bounded, sequence-numbered ring buffers for the streaming plane.

Two rings back every stream session (``session.py``):

- :class:`RowRing` — the per-machine *ingest* side: decoded sensor rows
  land here with monotonically increasing row sequence numbers and wait
  for the watermark to cut a scoring window. Overflow sheds
  **oldest-first** (the freshest telemetry is the valuable telemetry for
  anomaly detection) and counts every shed row — memory is bounded by
  construction, never by the client's politeness.
- :class:`EventRing` — the per-session *emit* side: every SSE event is
  appended under the next event sequence number and retained until the
  ring evicts it. A reconnecting consumer replays ``since(cursor)``; if
  the ring already evicted past its cursor the reader learns exactly how
  many events it missed (the ``shed`` control frame) instead of getting
  a silent gap.

Neither ring owns a lock: the owning :class:`~.session.StreamSession`
serializes access under its own lock (one lock per session keeps the
lock-ordering graph trivial).

>>> ring = EventRing(capacity=2)
>>> ring.append("a"), ring.append("b"), ring.append("c")
(1, 2, 3)
>>> events, missed = ring.since(0)   # "a" was evicted: 1 missed
>>> [seq for seq, _ in events], missed
([2, 3], 1)
>>> ring.since(3)
([], 0)
"""

import time
from collections import deque
from typing import Any, Deque, List, Optional, Tuple

__all__ = ["RowRing", "EventRing"]


class RowRing:
    """Bounded buffer of row chunks with per-row sequence numbers.

    Rows are appended as chunks (anything with ``len`` and positional
    slicing via ``.iloc`` or ``[...]`` — pandas frames in production,
    plain lists in tests) and taken oldest-first in exact arrival order.
    Row sequence numbers are 1-based and monotonic for the life of the
    ring; they never reset, so a scored window's ``(first_seq,
    last_seq)`` span is a durable, gap-checkable coordinate.

    Every chunk also carries the wall-clock instant it landed
    (``ingest_ts``), preserved across partial sheds and partial takes,
    so the scorer can compute ingest→scored lag per flush and the
    status surfaces can report watermark delay (``now - oldest_ts``)
    without a side table.
    """

    __slots__ = ("capacity", "_chunks", "_pending", "_next_seq", "shed_rows")

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        #: deque of (first_seq, ingest_ts, chunk) in arrival order
        self._chunks: Deque[Tuple[int, float, Any]] = deque()
        self._pending = 0
        self._next_seq = 1
        self.shed_rows = 0

    @property
    def pending_rows(self) -> int:
        return self._pending

    @property
    def next_seq(self) -> int:
        """Sequence number the next appended row will receive."""
        return self._next_seq

    @property
    def oldest_ts(self) -> Optional[float]:
        """Ingest wall-clock of the oldest buffered row (None when
        empty) — the watermark-delay anchor."""
        return self._chunks[0][1] if self._chunks else None

    @staticmethod
    def _slice(chunk: Any, start: int, stop: Optional[int] = None) -> Any:
        iloc = getattr(chunk, "iloc", None)
        if iloc is not None:
            return iloc[start:stop]
        return chunk[start:stop]

    def append(
        self, chunk: Any, ingest_ts: Optional[float] = None
    ) -> Tuple[int, int]:
        """Land ``chunk`` rows; returns ``(first_seq, rows_shed)``.

        Shedding is oldest-first: when the ring would exceed capacity the
        oldest buffered rows are dropped (counted in :attr:`shed_rows`)
        until the new chunk fits. A chunk taller than the whole ring
        keeps only its newest ``capacity`` rows — the bound is absolute.

        ``ingest_ts`` (default: now) is retained with the chunk; a
        partially-shed chunk keeps its original stamp — the surviving
        rows arrived when the chunk arrived.
        """
        rows = int(len(chunk))
        first_seq = self._next_seq
        if ingest_ts is None:
            ingest_ts = time.time()
        if rows == 0:
            return first_seq, 0
        shed = 0
        if rows >= self.capacity:
            # the chunk alone overflows the ring: every buffered row and
            # the chunk's own oldest overflow go
            shed += self._pending
            self._chunks.clear()
            self._pending = 0
            overflow = rows - self.capacity
            if overflow:
                shed += overflow
                chunk = self._slice(chunk, overflow)
            self._next_seq += rows
            self._chunks.append(
                (self._next_seq - self.capacity, ingest_ts, chunk)
            )
            self._pending = self.capacity
            self.shed_rows += shed
            return first_seq, shed
        self._next_seq += rows
        self._chunks.append((first_seq, ingest_ts, chunk))
        self._pending += rows
        while self._pending > self.capacity:
            over = self._pending - self.capacity
            oldest_seq, oldest_ts, oldest = self._chunks[0]
            if len(oldest) <= over:
                self._chunks.popleft()
                self._pending -= len(oldest)
                shed += len(oldest)
            else:
                self._chunks[0] = (
                    oldest_seq + over,
                    oldest_ts,
                    self._slice(oldest, over),
                )
                self._pending -= over
                shed += over
        self.shed_rows += shed
        return first_seq, shed

    def take(
        self, rows: int
    ) -> Optional[Tuple[List[Any], int, int, float]]:
        """Pop the oldest ``rows`` buffered rows, or None if fewer are
        pending. Returns ``(chunks, first_seq, last_seq, oldest_ts)`` —
        the chunk list concatenates (in order) to exactly ``rows`` rows
        and ``oldest_ts`` is the ingest wall-clock of the oldest row
        taken (``now - oldest_ts`` is this take's ingest→scored lag)."""
        rows = int(rows)
        if rows <= 0 or self._pending < rows:
            return None
        first_seq = self._chunks[0][0]
        oldest_ts = self._chunks[0][1]
        out: List[Any] = []
        needed = rows
        while needed > 0:
            chunk_seq, chunk_ts, chunk = self._chunks.popleft()
            if len(chunk) <= needed:
                out.append(chunk)
                needed -= len(chunk)
                self._pending -= len(chunk)
            else:
                out.append(self._slice(chunk, 0, needed))
                self._chunks.appendleft(
                    (chunk_seq + needed, chunk_ts, self._slice(chunk, needed))
                )
                self._pending -= needed
                needed = 0
        return out, first_seq, first_seq + rows - 1, oldest_ts


class EventRing:
    """Bounded event log with 1-based monotonic sequence numbers and
    cursor replay — the SSE outbox's memory."""

    __slots__ = ("capacity", "_events", "_latest", "dropped")

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        #: deque of (seq, event) — seq is contiguous within the deque
        self._events: Deque[Tuple[int, Any]] = deque()
        self._latest = 0
        self.dropped = 0

    @property
    def latest_seq(self) -> int:
        return self._latest

    @property
    def oldest_seq(self) -> int:
        """Sequence of the oldest retained event (0 when empty)."""
        return self._events[0][0] if self._events else 0

    def append(self, event: Any) -> int:
        self._latest += 1
        self._events.append((self._latest, event))
        while len(self._events) > self.capacity:
            self._events.popleft()
            self.dropped += 1
        return self._latest

    def since(self, cursor: int) -> Tuple[List[Tuple[int, Any]], int]:
        """Events with ``seq > cursor`` still retained, plus how many
        matching events were already evicted (the reader's gap)."""
        cursor = max(0, int(cursor))
        if cursor >= self._latest:
            return [], 0
        oldest = self.oldest_seq
        missed = max(0, oldest - cursor - 1) if self._events else self._latest - cursor
        return [entry for entry in self._events if entry[0] > cursor], missed
