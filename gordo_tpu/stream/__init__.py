"""
The always-on streaming scoring plane.

Request/response serving answers one frame per HTTP exchange; the
production reality for a sensor fleet is a continuous feed. This package
is the standing pipeline: Arrow-IPC record batches stream in over
long-lived connections (``server/views/stream.py``), rows land in
per-machine bounded ring buffers, the watermark cuts windows that score
through the SAME fused many-model gather programs the request path uses,
and anomalies flow out as server-sent events with replayable cursors.

The robustness contract is the point (see ``docs/serving.md`` —
"Streaming plane"):

- disconnects resume from a cursor (``ring.EventRing`` replay);
- backpressure sheds oldest-first with counters, never unbounded memory;
- a poisoned member is quarantined by PR 15's per-member circuit
  breakers while its stream-mates keep scoring (``scorer.WindowScorer``);
- hot-swaps never gap or double-score a window (per-flush pinned fleet);
- ``drain_and_stop`` closes every stream with a clean terminal frame.

Master switch: ``GORDO_TPU_STREAM_ENABLED`` (default on). The full knob
catalog lives in the Streaming section of ``docs/configuration.md``.
"""

from .events import (
    SSE_CONTENT_TYPE,
    TERMINAL_KINDS,
    StreamEvent,
    encode_sse,
    heartbeat_frame,
)
from .plane import (
    PlaneSaturated,
    StreamConfig,
    StreamPlane,
    ensure_plane,
    get_plane,
    install_plane,
    reset_plane,
    stream_enabled,
    stream_plane_section,
)
from .ring import EventRing, RowRing
from .scorer import WindowScorer
from .session import MachineChannel, StreamSession
from .telemetry import (
    StreamTelemetry,
    reset_stream_telemetry,
    stream_telemetry,
)

__all__ = [
    "EventRing",
    "MachineChannel",
    "PlaneSaturated",
    "RowRing",
    "SSE_CONTENT_TYPE",
    "StreamConfig",
    "StreamEvent",
    "StreamPlane",
    "StreamSession",
    "StreamTelemetry",
    "TERMINAL_KINDS",
    "WindowScorer",
    "encode_sse",
    "ensure_plane",
    "get_plane",
    "heartbeat_frame",
    "install_plane",
    "reset_plane",
    "reset_stream_telemetry",
    "stream_enabled",
    "stream_plane_section",
    "stream_telemetry",
]
