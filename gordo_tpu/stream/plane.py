"""
The streaming plane coordinator: sessions, ingest, subscribe, drain.

One process-global :class:`StreamPlane` (``ensure_plane`` — installed by
``build_app`` alongside the micro-batching engine, shared by every
worker thread like ``STORE``) owns the session registry and the
:class:`~.scorer.WindowScorer`. The HTTP layer (``server/views/stream.py``)
stays thin: it decodes bodies and hands frames here; everything
long-lived — rings, outboxes, breaker gates, TTL expiry, drain — is the
plane's.

Admission and lifetime are bounded like everything else on this plane:
at most ``GORDO_TPU_STREAM_MAX_SESSIONS`` live sessions (overflow is
refused with a retry hint — the session-level 429), and a session idle
past ``GORDO_TPU_STREAM_SESSION_TTL_S`` is expired on the next registry
access (no reaper thread: the plane creates NO threads at all, which
keeps the thread-lifecycle contract trivially true).

``drain()`` is the graceful-shutdown hook ``drain_and_stop`` calls
before the engine drains: every live session gets its terminal ``drain``
frame and every SSE subscriber wakes, finishes its outbox tail, and
closes cleanly — a standing stream socket never just dies mid-frame on
a planned shutdown.
"""

import logging
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utils.env import env_bool, env_float, env_int
from ..utils.faults import FaultInjected, fault_point
from .events import StreamEvent
from .scorer import WindowScorer
from .session import StreamSession
from .telemetry import stream_telemetry

logger = logging.getLogger(__name__)

__all__ = [
    "PlaneSaturated",
    "StreamConfig",
    "StreamPlane",
    "ensure_plane",
    "get_plane",
    "reset_plane",
    "stream_enabled",
    "stream_plane_section",
]

STREAM_ENV = "GORDO_TPU_STREAM_ENABLED"


def stream_enabled() -> bool:
    """Streaming-plane master switch (default on — the plane costs
    nothing until a stream route is hit)."""
    return env_bool(STREAM_ENV, True)


class PlaneSaturated(Exception):
    """Session admission refused (``GORDO_TPU_STREAM_MAX_SESSIONS``):
    the stream twin of the batcher's ``QueueFullError`` → 429 +
    Retry-After."""

    def __init__(self, limit: int, retry_after_s: float):
        super().__init__(f"stream session limit reached ({limit})")
        self.limit = limit
        self.retry_after_s = retry_after_s


class StreamConfig:
    """Plane knobs, resolved once from the environment at creation."""

    __slots__ = (
        "ring_rows",
        "window_rows",
        "outbox_events",
        "session_ttl_s",
        "heartbeat_s",
        "max_sessions",
        "shed_retry_s",
    )

    def __init__(
        self,
        ring_rows: int = 8192,
        window_rows: int = 64,
        outbox_events: int = 1024,
        session_ttl_s: float = 3600.0,
        heartbeat_s: float = 15.0,
        max_sessions: int = 64,
        shed_retry_s: float = 1.0,
    ):
        self.ring_rows = max(1, int(ring_rows))
        self.window_rows = max(1, int(window_rows))
        self.outbox_events = max(1, int(outbox_events))
        self.session_ttl_s = max(1.0, float(session_ttl_s))
        self.heartbeat_s = max(0.05, float(heartbeat_s))
        self.max_sessions = max(1, int(max_sessions))
        self.shed_retry_s = max(0.0, float(shed_retry_s))

    @classmethod
    def from_env(cls) -> "StreamConfig":
        return cls(
            ring_rows=env_int("GORDO_TPU_STREAM_RING_ROWS", 8192),
            window_rows=env_int("GORDO_TPU_STREAM_WINDOW_ROWS", 64),
            outbox_events=env_int("GORDO_TPU_STREAM_OUTBOX_EVENTS", 1024),
            session_ttl_s=env_float(
                "GORDO_TPU_STREAM_SESSION_TTL_S", 3600.0
            ),
            heartbeat_s=env_float("GORDO_TPU_STREAM_HEARTBEAT_S", 15.0),
            max_sessions=env_int("GORDO_TPU_STREAM_MAX_SESSIONS", 64),
            shed_retry_s=env_float("GORDO_TPU_STREAM_SHED_RETRY_S", 1.0),
        )


class StreamPlane:
    """Session registry + scorer + drain for one server process."""

    def __init__(self, config: Optional[StreamConfig] = None):
        self.config = config or StreamConfig.from_env()
        self._lock = threading.Lock()
        self._sessions: Dict[Tuple[str, str], StreamSession] = {}
        self.scorer = WindowScorer(self.config.window_rows)
        self._drained = False
        self.counters: Dict[str, int] = {
            "sessions_opened": 0,
            "sessions_expired": 0,
            "sessions_rejected": 0,
            "ingest_batches": 0,
            "ingest_errors": 0,
        }

    # -- wiring --------------------------------------------------------------

    @property
    def ledger_anchor(self) -> Optional[str]:
        return self.scorer.ledger_anchor

    @ledger_anchor.setter
    def ledger_anchor(self, anchor: Optional[str]) -> None:
        self.scorer.ledger_anchor = anchor

    def attach_drift(self, monitor: Any) -> None:
        """Wire a lifecycle ``DriftMonitor`` (duck-typed —
        ``observe_scores(frames, scores)``) into the scoring flush, so
        drift statistics accumulate from streaming traffic. Called by
        ``LifecycleSupervisor.attach_stream``; this package never
        imports ``gordo_tpu.lifecycle``."""
        self.scorer.drift_monitor = monitor

    # -- session registry ----------------------------------------------------

    def _prune_locked(self, now: float) -> None:
        # closed sessions linger as tombstones until the TTL: a late
        # ingest gets an honest 410 (not a silently re-opened stream
        # whose row seqs restart at 1) and a late reconnect still finds
        # the terminal frame in the outbox. They stop counting against
        # the admission cap the moment they close.
        ttl = self.config.session_ttl_s
        for key, session in list(self._sessions.items()):
            if now - session.last_used <= ttl:
                continue
            if not session.closed:
                session.close("end", reason="session expired (idle)")
                self.counters["sessions_expired"] += 1
            if session.subscribers == 0:
                del self._sessions[key]

    def session(
        self,
        project: str,
        stream_id: str,
        collection_dir: str,
        create: bool = True,
    ) -> Optional[StreamSession]:
        """Look up (or admit) one stream session. Raises
        :class:`PlaneSaturated` when admission would exceed the session
        cap; returns None for a miss with ``create=False``."""
        key = (project, stream_id)
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            session = self._sessions.get(key)
            if session is not None or not create:
                return session
            if self._drained:
                raise PlaneSaturated(0, self.config.shed_retry_s)
            live = sum(
                1 for s in self._sessions.values() if not s.closed
            )
            if live >= self.config.max_sessions:
                self.counters["sessions_rejected"] += 1
                raise PlaneSaturated(
                    self.config.max_sessions, self.config.shed_retry_s
                )
            session = StreamSession(
                project,
                stream_id,
                collection_dir,
                ring_rows=self.config.ring_rows,
                outbox_events=self.config.outbox_events,
            )
            self._sessions[key] = session
            self.counters["sessions_opened"] += 1
            return session

    def close_session(
        self, project: str, stream_id: str, reason: str = "closed by client"
    ) -> bool:
        with self._lock:
            session = self._sessions.get((project, stream_id))
        if session is None:
            return False
        session.close("end", reason=reason)
        return True

    # -- ingest --------------------------------------------------------------

    def ingest(
        self,
        session: StreamSession,
        frames: Dict[str, Any],
        errors: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> Dict[str, Any]:
        """Land decoded per-machine frames, run the watermark flush, and
        return the ingest ack: accepted/shed row counts, per-machine
        errors (decode errors passed in by the view + ``stream_ingest``
        fault-site hits), the flush summary, and the consumer cursor."""
        from ..telemetry import serving as serve_trace

        errors = dict(errors or {})
        accepted: Dict[str, int] = {}
        shed: Dict[str, int] = {}
        recorder = serve_trace.serve_recorder()
        with recorder.span(
            "stream_ingest",
            stream=session.stream_id,
            machines=len(frames),
        ) as ingest_span:
            for name, frame in frames.items():
                try:
                    fault_point(
                        "stream_ingest", f"{session.stream_id}:{name}"
                    )
                except FaultInjected as exc:
                    # one poisoned entry errors alone; the rest of the
                    # machines' rows still land (fleet-route isolation)
                    errors[name] = {"error": str(exc), "status": 500}
                    continue
                first_seq, shed_rows = session.append_rows(name, frame)
                accepted[name] = int(len(frame))
                if shed_rows:
                    shed[name] = shed_rows
            rows_accepted = sum(accepted.values())
            ingest_span.set(
                rows=rows_accepted,
                shed=sum(shed.values()),
                errors=len(errors),
            )
            # remember this span's identity so the flush that drains
            # these rows can link back to it (ingest → flush → emit)
            if ingest_span.span_id:
                session.note_ingest_span(
                    ingest_span.trace_id, ingest_span.span_id
                )
        stream_telemetry().observe_ingest(rows_accepted)
        flush = self.scorer.flush(session)
        with self._lock:
            self.counters["ingest_batches"] += 1
            self.counters["ingest_errors"] += len(errors)
        backpressure = bool(shed)
        ack: Dict[str, Any] = {
            "stream": session.stream_id,
            "accepted": accepted,
            "shed": shed,
            "errors": errors,
            "cursor": session.latest_seq(),
            "scored": flush["scored"],
            "score_errors": flush["errors"],
            "quarantined": flush["quarantined"],
            "backpressure": backpressure,
        }
        if backpressure:
            ack["retry_after_s"] = self.config.shed_retry_s
        return ack

    # -- subscribe -----------------------------------------------------------

    def _quarantine_prelude(
        self, session: StreamSession
    ) -> List[StreamEvent]:
        """The immediate quarantine notices a (re)connecting consumer
        gets ahead of the replay: one ``quarantined`` frame per member
        whose breaker is currently open/half-open — a reconnect must
        learn about an ongoing quarantine NOW, not from a silent gap.
        Read from the board's snapshot (no probe admission is consumed
        by subscribing)."""
        from .. import serve

        machines = session.machine_names()
        if not machines:
            return []
        try:
            board = serve.stream_breaker_board(
                self.scorer._on_breaker_transition
            )
            unhealthy = board.summary(top_k=len(machines))["members"]
        except Exception:  # noqa: BLE001 - the prelude is advisory
            logger.debug("quarantine prelude failed", exc_info=True)
            return []
        notices = []
        for member in unhealthy:
            name = member.get("member")
            if name in machines and member.get("state") != "closed":
                notices.append(
                    StreamEvent(
                        "quarantined",
                        {
                            "machine": name,
                            "retry_after_s": member.get("cooldown_s"),
                            "trips": member.get("trips"),
                        },
                    )
                )
        return notices

    def subscribe(
        self,
        session: StreamSession,
        cursor: int = 0,
        max_events: Optional[int] = None,
        idle_timeout_s: Optional[float] = None,
    ) -> Iterator[str]:
        """SSE frame iterator for one consumer: ``open`` + quarantine
        prelude + replay-from-cursor + live tail (see
        :meth:`.session.StreamSession.subscribe`)."""
        return session.subscribe(
            cursor=cursor,
            heartbeat_s=self.config.heartbeat_s,
            max_events=max_events,
            idle_timeout_s=idle_timeout_s,
            prelude=self._quarantine_prelude(session),
        )

    # -- shutdown ------------------------------------------------------------

    def drain(self) -> int:
        """Terminal ``drain`` frame into every live session and refuse
        new ones; returns how many sessions were closed. Idempotent —
        called from ``drain_and_stop`` BEFORE the engine drain so
        subscribers flush their tails while the batcher is still
        resolving in-flight futures."""
        with self._lock:
            self._drained = True
            sessions = list(self._sessions.values())
        closed = 0
        for session in sessions:
            if not session.closed:
                session.close("drain", reason="server draining")
                closed += 1
        if closed:
            logger.info("stream plane drained %d live session(s)", closed)
        return closed

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            sessions = dict(self._sessions)
            counters = dict(self.counters)
            drained = self._drained
        return {
            "enabled": stream_enabled(),
            "draining": drained,
            "sessions": {
                f"{project}/{stream_id}": session.stats()
                for (project, stream_id), session in sorted(sessions.items())
            },
            "counters": counters,
            "telemetry": stream_telemetry().snapshot(),
            "config": {
                "ring_rows": self.config.ring_rows,
                "window_rows": self.config.window_rows,
                "outbox_events": self.config.outbox_events,
                "max_sessions": self.config.max_sessions,
            },
        }


# -- process-global plane ----------------------------------------------------

_plane: Optional[StreamPlane] = None
_plane_lock = threading.Lock()


def get_plane() -> Optional[StreamPlane]:
    """The installed plane, or None (no stream route hit yet)."""
    return _plane


def ensure_plane() -> Optional[StreamPlane]:
    """Create-and-install the process plane when streaming is enabled
    (idempotent); None when ``GORDO_TPU_STREAM_ENABLED`` is off."""
    global _plane
    if not stream_enabled():
        return None
    with _plane_lock:
        if _plane is None:
            _plane = StreamPlane()
            logger.info(
                "stream plane on: ring_rows=%d window_rows=%d "
                "outbox_events=%d max_sessions=%d",
                _plane.config.ring_rows,
                _plane.config.window_rows,
                _plane.config.outbox_events,
                _plane.config.max_sessions,
            )
        return _plane


def install_plane(plane: Optional[StreamPlane]) -> None:
    """Install a specific plane (tests; pass None to uninstall)."""
    global _plane
    with _plane_lock:
        _plane = plane


def reset_plane() -> None:
    """Drain and uninstall the process plane (tests, reload)."""
    global _plane
    with _plane_lock:
        plane, _plane = _plane, None
    if plane is not None:
        plane.drain()


def stream_plane_section() -> Optional[Dict[str, Any]]:
    """The streaming-plane section of the fleet-status console: session
    counts, the summed zero-gap row accounting, freshness (score lag /
    watermark delay) and the process-global flush/lag percentiles —
    everything from this process's installed :class:`StreamPlane`.
    None when no stream route has been hit here (a CLI process reading
    somebody else's directory degrades exactly like the other injected
    sections). Lives HERE rather than in ``telemetry/fleet_health.py``
    because the layering arrows point down — callers inject it into
    ``fleet_status_document(stream=...)`` like device/programs/serving."""
    plane = get_plane()
    if plane is None:
        return None
    stats = plane.stats()
    sessions = stats.get("sessions") or {}
    active = [s for s in sessions.values() if not s.get("closed")]
    accounting = {
        key: 0
        for key in (
            "rows_in",
            "rows_scored",
            "rows_failed",
            "rows_pending",
            "rows_shed",
            "gap",
        )
    }
    quarantined = 0
    score_lags: List[float] = []
    delays: List[float] = []
    for session in sessions.values():
        for key in accounting:
            accounting[key] += int(
                (session.get("accounting") or {}).get(key, 0)
            )
        lag = session.get("lag") or {}
        if lag.get("score_lag_max_ms") is not None:
            score_lags.append(float(lag["score_lag_max_ms"]))
        if lag.get("watermark_delay_max_ms") is not None:
            delays.append(float(lag["watermark_delay_max_ms"]))
        quarantined += sum(
            1
            for machine in (session.get("machines") or {}).values()
            if machine.get("quarantined")
        )
    telemetry = stats.get("telemetry") or {}
    from ..telemetry.aggregate import histogram_percentile

    return {
        "enabled": stats.get("enabled"),
        "draining": stats.get("draining"),
        "sessions_active": len(active),
        "sessions_closed": len(sessions) - len(active),
        "subscribers": sum(
            int(s.get("subscribers", 0)) for s in sessions.values()
        ),
        "quarantined_machines": quarantined,
        "accounting": accounting,
        "lag": {
            "score_lag_max_ms": max(score_lags) if score_lags else None,
            "watermark_delay_max_ms": max(delays) if delays else None,
            "lag_p95_ms": histogram_percentile(
                telemetry.get("lag_ms") or {}, 0.95
            ),
            "flush_p95_ms": histogram_percentile(
                telemetry.get("flush_ms") or {}, 0.95
            ),
        },
        "flushes": int(telemetry.get("flushes", 0)),
        "counters": stats.get("counters"),
    }
