"""
Watermark-triggered window scoring for the streaming plane.

Every ingest that pushes a machine past the watermark
(``GORDO_TPU_STREAM_WINDOW_ROWS`` buffered rows) flushes through here:
the pending full windows are cut from the rings and scored as ONE fused
many-model call (``RevisionFleet.fleet_scores`` — the same per-spec
gather programs the fleet route and the micro-batching engine run), and
each machine's result becomes an ``anomaly`` event carrying its exact
``(first_seq, last_seq)`` row span and the revision that scored it.

Robustness properties, in the order they bite:

- **zero-gap hot-swap** — the serving revision is resolved ONCE per
  flush (``STORE.route`` + ``STORE.fleet``) and every window in the
  flush scores against that pinned :class:`RevisionFleet` object. A
  ``LifecycleSupervisor`` promotion lands between flushes, never inside
  one: row spans stay contiguous across the swap (the soak bench audits
  exactly this) and no window is dropped or double-scored.
- **poison containment** — the per-member circuit breakers are PR 15's
  (:func:`gordo_tpu.serve.stream_breaker_board`: the engine's own board
  when batching is on, a standalone one otherwise). A quarantined
  member's windows are not cut at all — its rows keep buffering (and
  shedding oldest-first under pressure) while the stream emits one
  ``quarantined`` frame with ``retry_after_s``; the *other* machines in
  the same flush keep scoring. When the cooldown lapses the next flush
  admits one window as the half-open probe; success closes the breaker
  and emits ``recovered``.
- **per-window error isolation** — a scoring failure (including the
  ``stream_score`` fault site) costs exactly that machine's cut span:
  an ``error`` frame, a breaker failure mark for server-side causes,
  and honest ``rows_failed`` accounting. Client-data failures
  (ValueError/TypeError) never count against the member's breaker.

Observability: one enriched ``stream_score`` span per flush on the
shared serving recorder — rows/windows/shed, per-machine ingest→scored
lag (p50/max plus a rows-weighted fixed-bucket histogram the rollups
merge), ``predicted_device_ms`` vs ``device_ms`` (the engine's
plan-accuracy axis, extended to flushes), and OTel links back to the
``stream_ingest`` spans the flush drained — followed by a
``stream_emit`` span timing the event fan-out, the process-global
stream telemetry accumulator (``telemetry.py`` → the Prometheus
``StreamPlaneCollector``), a batch-wise fleet-health ledger feed (rows
+ rolling residual mean + request marks — the stream twin of the fleet
route's feed), and an optional drift monitor fed ``observe_scores`` so
lifecycle drift detection runs off streaming traffic, not just sampled
HTTP requests.
"""

import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..planner import ladder
from ..utils.faults import fault_point
from .events import StreamEvent
from .session import StreamSession
from .telemetry import lag_bucket_counts, stream_telemetry

logger = logging.getLogger(__name__)

__all__ = ["WindowScorer"]

#: breaker spec key for members whose real spec bucket could not be
#: resolved (model failed to load, exotic provider): quarantine still
#: works, just without cross-plane key sharing for that member
FALLBACK_SPEC = "stream"


class WindowScorer:
    """Cut-and-score the watermark windows of one session flush."""

    def __init__(
        self,
        window_rows: int,
        ledger_anchor: Optional[str] = None,
        drift_monitor: Optional[Any] = None,
    ):
        self.window_rows = max(1, int(window_rows))
        #: the ANCHOR collection dir the ledger/breaker feeds key on
        #: (falls back to the session's own anchor per flush)
        self.ledger_anchor = ledger_anchor
        #: duck-typed ``DriftMonitor`` (``observe_scores(frames,
        #: scores)``) — injected by the lifecycle supervisor via
        #: ``StreamPlane.attach_drift`` so this package never imports
        #: ``gordo_tpu.lifecycle``
        self.drift_monitor = drift_monitor
        #: cost-model device-ms predictions cached per (spec, members,
        #: rows) — the engine's ``_predicted_step_ms`` pattern; flushes
        #: run at watermark rates, the estimator is pure arithmetic
        self._step_predictions: Dict[Any, float] = {}

    # -- plumbing ------------------------------------------------------------

    def _board(self):
        from .. import serve

        return serve.stream_breaker_board(self._on_breaker_transition)

    def _on_breaker_transition(
        self, member: str, old: str, new: str, info: dict
    ) -> None:
        """Standalone-board transitions mirror the engine's ledger feed:
        tripped stream members must reach ``fleet-status`` and the
        lifecycle supervisor's rebuild nomination the same way tripped
        request-plane members do."""
        try:
            from ..telemetry import ledger_for

            anchor = self.ledger_anchor or os.environ.get(
                "MODEL_COLLECTION_DIR"
            )
            if anchor:
                ledger_for(anchor).record_breaker(
                    member,
                    new,
                    trips=info.get("trips"),
                    cooldown_s=info.get("cooldown_s"),
                    reason=info.get("last_error") or None,
                )
        except Exception:  # noqa: BLE001 - the ledger is advisory
            logger.debug("stream breaker ledger feed failed", exc_info=True)

    @staticmethod
    def _spec_for(fleet: Any, name: str) -> Any:
        try:
            fleet.model(name)  # ensure loaded + bucketed
            spec = fleet.loaded_specs().get(name)
        except Exception:  # noqa: BLE001 - an unloadable member still
            # deserves a working breaker key
            spec = None
        return spec if spec is not None else FALLBACK_SPEC

    @staticmethod
    def _concat(chunks: List[Any]) -> Any:
        if len(chunks) == 1:
            return chunks[0]
        import pandas as pd

        return pd.concat(chunks)

    def _predicted_step_ms(self, spec: Any, members: int, rows: int) -> float:
        """Cost-model device milliseconds for one fused spec group at
        this flush's shape (f32 — the stream path's width), cached per
        shape like the serve engine's batch predictions. -1.0 when the
        estimator is unavailable (the sentinel the plan-accuracy
        consumers already skip)."""
        key = (spec, members, rows)
        cached = self._step_predictions.get(key)
        if cached is None:
            try:
                from ..planner.costmodel import CostModel, load_table_safe
                from ..utils.env import env_str

                # the perfmodel table (when GORDO_TPU_PERFMODEL_TABLE
                # names one) upgrades flush predictions to the learned
                # regressors; load_table_safe degrades any bad table to
                # the analytic defaults without raising
                cached = round(
                    CostModel(
                        load_table_safe(
                            env_str("GORDO_TPU_PERFMODEL_TABLE", None)
                        )
                    ).predict_serve_step_s(spec, members, rows, "f32")
                    * 1000.0,
                    4,
                )
            except Exception:  # noqa: BLE001 - prediction is telemetry,
                # never the flush's problem
                cached = -1.0
            if len(self._step_predictions) > 4096:
                self._step_predictions.clear()
            self._step_predictions[key] = cached
        return cached

    def _predicted_flush_ms(
        self, specs: Dict[str, Any], inputs: Dict[str, Any]
    ) -> float:
        """Predicted device-ms for the whole flush: the per-spec fused
        groups ``fleet_scores`` will actually run, summed. Members whose
        spec bucket could not be resolved (the breaker-fallback string)
        are unpredictable — a flush made only of those reports -1.0."""
        groups: Dict[Any, List[int]] = {}
        for name, frame in inputs.items():
            spec = specs.get(name)
            if spec is None or isinstance(spec, str):
                continue
            groups.setdefault(spec, []).append(int(len(frame)))
        total = 0.0
        for spec, row_counts in groups.items():
            predicted = self._predicted_step_ms(
                spec, len(row_counts), max(row_counts)
            )
            if predicted < 0.0:
                return -1.0
            total += predicted
        return round(total, 4) if groups else -1.0

    # -- the flush -----------------------------------------------------------

    def flush(self, session: StreamSession) -> Dict[str, Any]:
        """Score every full pending window in ``session``; returns the
        flush summary the ingest ack carries: scored/failed/quarantined
        machine maps plus total rows scored."""
        from ..server.fleet_store import STORE
        from ..telemetry import serving as serve_trace

        summary: Dict[str, Any] = {
            "scored": {},
            "errors": {},
            "quarantined": {},
            "rows": 0,
        }
        # pin ONCE per flush: every window below scores against this
        # revision object, however many promotions land meanwhile
        routed = STORE.route(session.collection_dir)
        fleet = STORE.fleet(routed)
        revision = os.path.basename(os.path.normpath(routed))
        board = self._board()

        # breaker gate BEFORE cutting: a quarantined member's rows stay
        # in its ring (bounded by oldest-first shed), they are not cut
        # into a window that could never score
        quarantined: Dict[str, float] = {}
        specs: Dict[str, Any] = {}
        for name in session.pending_machines(self.window_rows):
            spec = self._spec_for(fleet, name)
            specs[name] = spec
            retry_after = board.quarantined(fleet, spec, name)
            if retry_after is not None:
                quarantined[name] = retry_after
                chan = session.channel(name)
                if not chan.quarantine_notified:
                    chan.quarantine_notified = True
                    session.emit(
                        StreamEvent(
                            "quarantined",
                            {
                                "machine": name,
                                "retry_after_s": round(retry_after, 3),
                            },
                        )
                    )
        summary["quarantined"] = {
            name: round(retry, 3) for name, retry in quarantined.items()
        }

        flush_started = time.time()
        # multi-window spans snap onto the serve row ladder
        # (planner.ladder.snap_rows): a backlog flush runs the SAME
        # compiled shape the request plane batches into instead of
        # minting a worst-case-padded one; the remainder windows stay
        # buffered and ride the next watermark flush
        cut = session.cut_windows(
            self.window_rows,
            skip=tuple(quarantined),
            snap=lambda pending: ladder.snap_rows(pending, self.window_rows),
        )
        if not cut:
            return summary

        inputs: Dict[str, Any] = {}
        spans: Dict[str, Tuple[int, int, int]] = {}
        injected: Dict[str, BaseException] = {}
        lags_ms: Dict[str, float] = {}
        total_windows = 0
        for name, (chunks, first_seq, last_seq, windows, oldest_ts) in (
            cut.items()
        ):
            spans[name] = (first_seq, last_seq, windows)
            total_windows += windows
            # ingest→scored lag of this machine's span, anchored on its
            # OLDEST row: the freshness number a consumer experiences
            lags_ms[name] = round(
                max(0.0, flush_started - oldest_ts) * 1000.0, 3
            )
            try:
                fault_point(
                    "stream_score", f"{session.stream_id}:{name}"
                )
                inputs[name] = self._concat(chunks)
            except Exception as exc:  # noqa: BLE001 - injected poison or
                # a broken concat is THIS member's failure, nobody else's
                injected[name] = exc

        recorder = serve_trace.serve_recorder()
        total_rows = sum(int(len(x)) for x in inputs.values())
        shed_rows = session.shed_delta()
        lag_values = sorted(lags_ms.values())
        lag_p50 = (
            lag_values[len(lag_values) // 2] if lag_values else 0.0
        )
        lag_max = lag_values[-1] if lag_values else 0.0
        # rows-weighted lag distribution over every machine drained this
        # flush, binned into the shared fixed edges — the compact shape
        # rollups merge to answer "what fraction of rows scored fresh"
        cut_names = list(spans)
        cut_weights = [spans[n][1] - spans[n][0] + 1 for n in cut_names]
        lag_hist = lag_bucket_counts(
            [lags_ms.get(n, 0.0) for n in cut_names],
            weights=cut_weights,
        )
        lag_sum_ms = round(
            sum(
                lags_ms.get(n, 0.0) * weight
                for n, weight in zip(cut_names, cut_weights)
            ),
            3,
        )
        with recorder.span(
            "stream_score",
            stream=session.stream_id,
            machines=len(inputs),
            rows=total_rows,
            windows=total_windows,
            shed=shed_rows,
            revision=revision,
            lag_p50_ms=lag_p50,
            lag_max_ms=lag_max,
            lag_hist=lag_hist,
            lag_sum_ms=lag_sum_ms,
            predicted_device_ms=self._predicted_flush_ms(specs, inputs),
        ) as score_span:
            # the OTel links tie this flush back to the ingest exchanges
            # it drained (the serve engine's batch-link pattern): a
            # trace reader can walk ingest → flush → emit
            for trace_id, ingest_span_id in session.drain_ingest_spans():
                score_span.link(trace_id, ingest_span_id)
            device_started = time.monotonic()
            scores, errors = (
                fleet.fleet_scores(inputs) if inputs else ({}, {})
            )
            device_s = time.monotonic() - device_started
            # the scored/failed row split is stamped on the span itself
            # so rollups reconstruct the plane's zero-gap accounting
            # from traces alone (rows == rows_scored + rows_failed)
            score_span.set(
                device_ms=round(device_s * 1000.0, 3),
                rows_scored=sum(int(len(inputs[n])) for n in scores),
                rows_failed=sum(
                    spans[n][1] - spans[n][0] + 1
                    for n in set(errors) | set(injected)
                ),
            )
        errors.update(injected)

        emit_started = time.monotonic()
        events_emitted = 0
        scored_ts = time.time()
        for name, (reconstruction, mse) in scores.items():
            first_seq, last_seq, windows = spans[name]
            rows = int(len(inputs[name]))
            residuals = np.asarray(mse, dtype=float).ravel()
            finite = residuals[np.isfinite(residuals)]
            chan = session.channel(name)
            chan.rows_scored += rows
            chan.windows_scored += windows
            chan.last_score_lag_ms = lags_ms.get(name)
            chan.last_scored_ts = scored_ts
            board.record_success(fleet, specs.get(name, FALLBACK_SPEC), name)
            if chan.quarantine_notified:
                chan.quarantine_notified = False
                session.emit(StreamEvent("recovered", {"machine": name}))
                events_emitted += 1
            session.emit(
                StreamEvent(
                    "anomaly",
                    {
                        "machine": name,
                        "first_seq": first_seq,
                        "last_seq": last_seq,
                        "rows": rows,
                        "windows": windows,
                        "mse_mean": (
                            float(finite.mean()) if len(finite) else None
                        ),
                        "mse_max": (
                            float(finite.max()) if len(finite) else None
                        ),
                        "revision": revision,
                    },
                )
            )
            events_emitted += 1
            summary["scored"][name] = rows
            summary["rows"] += rows

        failed_rows = 0
        for name, exc in errors.items():
            first_seq, last_seq, _windows = spans[name]
            rows = last_seq - first_seq + 1
            chan = session.channel(name)
            chan.score_errors += 1
            chan.rows_failed += rows
            failed_rows += rows
            # client-data failures are not the member's health problem —
            # same classification as the fleet route's ledger feed
            server_side = not isinstance(
                exc, (ValueError, TypeError, FileNotFoundError)
            )
            if server_side:
                board.record_failure(
                    fleet, specs.get(name, FALLBACK_SPEC), name, exc
                )
            session.emit(
                StreamEvent(
                    "error",
                    {
                        "machine": name,
                        "first_seq": first_seq,
                        "last_seq": last_seq,
                        "error": type(exc).__name__,
                    },
                )
            )
            events_emitted += 1
            summary["errors"][name] = type(exc).__name__

        # the emit phase as an externally-timed span: with the ingest
        # links above, `gordo-tpu trace` can lay out the stream critical
        # path (ingest → flush/device → emit) per session
        recorder.record(
            "stream_emit",
            max(0.0, time.monotonic() - emit_started),
            stream=session.stream_id,
            events=events_emitted,
            machines=len(scores) + len(errors),
        )

        flush_s = max(0.0, time.time() - flush_started)
        scored_names = list(scores)
        stream_telemetry().observe_flush(
            flush_s,
            rows_scored=summary["rows"],
            rows_failed=failed_rows,
            rows_shed=shed_rows,
            lags_ms=[lags_ms.get(n, 0.0) for n in scored_names],
            lag_weights=[summary["scored"][n] for n in scored_names],
        )
        summary["lag_p50_ms"] = lag_p50
        summary["lag_max_ms"] = lag_max

        self._feed_ledger(session, inputs, scores, errors)
        self._feed_drift(inputs, scores)
        return summary

    # -- feeds ---------------------------------------------------------------

    def _feed_ledger(
        self,
        session: StreamSession,
        frames: Dict[str, Any],
        scores: Dict[str, Tuple[Any, Any]],
        errors: Dict[str, BaseException],
    ) -> None:
        """Batch-wise fleet-health feed: one throttled snapshot write per
        flush, so a stream-only deployment still populates per-machine
        health exactly like HTTP scoring traffic would."""
        try:
            from ..telemetry import ledger_for

            anchor = self.ledger_anchor or session.collection_dir
            if not anchor:
                return
            ledger = ledger_for(anchor)
            if not ledger.enabled:
                return
            for name, (reconstruction, mse) in scores.items():
                residuals = np.asarray(mse, dtype=float).ravel()
                residuals = residuals[np.isfinite(residuals)]
                frame = frames.get(name)
                ledger.record_scores(
                    name,
                    len(frame) if frame is not None else len(residuals),
                    float(residuals.mean()) if len(residuals) else None,
                    write=False,
                )
                ledger.record_request(name)
            for name, exc in errors.items():
                ledger.record_request(
                    name,
                    error=not isinstance(
                        exc, (ValueError, TypeError, FileNotFoundError)
                    ),
                )
            ledger.write()
        except Exception:  # noqa: BLE001 - health telemetry is advisory
            logger.debug("stream health not recorded", exc_info=True)

    def _feed_drift(
        self,
        frames: Dict[str, Any],
        scores: Dict[str, Tuple[Any, Any]],
    ) -> None:
        monitor = self.drift_monitor
        if monitor is None or not frames:
            return
        try:
            monitor.observe_scores(frames, scores)
        except Exception:  # noqa: BLE001 - drift statistics are advisory
            logger.debug("stream drift feed failed", exc_info=True)
