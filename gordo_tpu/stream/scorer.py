"""
Watermark-triggered window scoring for the streaming plane.

Every ingest that pushes a machine past the watermark
(``GORDO_TPU_STREAM_WINDOW_ROWS`` buffered rows) flushes through here:
the pending full windows are cut from the rings and scored as ONE fused
many-model call (``RevisionFleet.fleet_scores`` — the same per-spec
gather programs the fleet route and the micro-batching engine run), and
each machine's result becomes an ``anomaly`` event carrying its exact
``(first_seq, last_seq)`` row span and the revision that scored it.

Robustness properties, in the order they bite:

- **zero-gap hot-swap** — the serving revision is resolved ONCE per
  flush (``STORE.route`` + ``STORE.fleet``) and every window in the
  flush scores against that pinned :class:`RevisionFleet` object. A
  ``LifecycleSupervisor`` promotion lands between flushes, never inside
  one: row spans stay contiguous across the swap (the soak bench audits
  exactly this) and no window is dropped or double-scored.
- **poison containment** — the per-member circuit breakers are PR 15's
  (:func:`gordo_tpu.serve.stream_breaker_board`: the engine's own board
  when batching is on, a standalone one otherwise). A quarantined
  member's windows are not cut at all — its rows keep buffering (and
  shedding oldest-first under pressure) while the stream emits one
  ``quarantined`` frame with ``retry_after_s``; the *other* machines in
  the same flush keep scoring. When the cooldown lapses the next flush
  admits one window as the half-open probe; success closes the breaker
  and emits ``recovered``.
- **per-window error isolation** — a scoring failure (including the
  ``stream_score`` fault site) costs exactly that machine's cut span:
  an ``error`` frame, a breaker failure mark for server-side causes,
  and honest ``rows_failed`` accounting. Client-data failures
  (ValueError/TypeError) never count against the member's breaker.

Observability: one ``stream_score`` span per flush on the shared serving
recorder, a batch-wise fleet-health ledger feed (rows + rolling residual
mean + request marks — the stream twin of the fleet route's feed), and
an optional drift monitor fed ``observe_scores`` so lifecycle drift
detection runs off streaming traffic, not just sampled HTTP requests.
"""

import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.faults import fault_point
from .events import StreamEvent
from .session import StreamSession

logger = logging.getLogger(__name__)

__all__ = ["WindowScorer"]

#: breaker spec key for members whose real spec bucket could not be
#: resolved (model failed to load, exotic provider): quarantine still
#: works, just without cross-plane key sharing for that member
FALLBACK_SPEC = "stream"


class WindowScorer:
    """Cut-and-score the watermark windows of one session flush."""

    def __init__(
        self,
        window_rows: int,
        ledger_anchor: Optional[str] = None,
        drift_monitor: Optional[Any] = None,
    ):
        self.window_rows = max(1, int(window_rows))
        #: the ANCHOR collection dir the ledger/breaker feeds key on
        #: (falls back to the session's own anchor per flush)
        self.ledger_anchor = ledger_anchor
        #: duck-typed ``DriftMonitor`` (``observe_scores(frames,
        #: scores)``) — injected by the lifecycle supervisor via
        #: ``StreamPlane.attach_drift`` so this package never imports
        #: ``gordo_tpu.lifecycle``
        self.drift_monitor = drift_monitor

    # -- plumbing ------------------------------------------------------------

    def _board(self):
        from .. import serve

        return serve.stream_breaker_board(self._on_breaker_transition)

    def _on_breaker_transition(
        self, member: str, old: str, new: str, info: dict
    ) -> None:
        """Standalone-board transitions mirror the engine's ledger feed:
        tripped stream members must reach ``fleet-status`` and the
        lifecycle supervisor's rebuild nomination the same way tripped
        request-plane members do."""
        try:
            from ..telemetry import ledger_for

            anchor = self.ledger_anchor or os.environ.get(
                "MODEL_COLLECTION_DIR"
            )
            if anchor:
                ledger_for(anchor).record_breaker(
                    member,
                    new,
                    trips=info.get("trips"),
                    cooldown_s=info.get("cooldown_s"),
                    reason=info.get("last_error") or None,
                )
        except Exception:  # noqa: BLE001 - the ledger is advisory
            logger.debug("stream breaker ledger feed failed", exc_info=True)

    @staticmethod
    def _spec_for(fleet: Any, name: str) -> Any:
        try:
            fleet.model(name)  # ensure loaded + bucketed
            spec = fleet.loaded_specs().get(name)
        except Exception:  # noqa: BLE001 - an unloadable member still
            # deserves a working breaker key
            spec = None
        return spec if spec is not None else FALLBACK_SPEC

    @staticmethod
    def _concat(chunks: List[Any]) -> Any:
        if len(chunks) == 1:
            return chunks[0]
        import pandas as pd

        return pd.concat(chunks)

    # -- the flush -----------------------------------------------------------

    def flush(self, session: StreamSession) -> Dict[str, Any]:
        """Score every full pending window in ``session``; returns the
        flush summary the ingest ack carries: scored/failed/quarantined
        machine maps plus total rows scored."""
        from ..server.fleet_store import STORE
        from ..telemetry import serving as serve_trace

        summary: Dict[str, Any] = {
            "scored": {},
            "errors": {},
            "quarantined": {},
            "rows": 0,
        }
        # pin ONCE per flush: every window below scores against this
        # revision object, however many promotions land meanwhile
        routed = STORE.route(session.collection_dir)
        fleet = STORE.fleet(routed)
        revision = os.path.basename(os.path.normpath(routed))
        board = self._board()

        # breaker gate BEFORE cutting: a quarantined member's rows stay
        # in its ring (bounded by oldest-first shed), they are not cut
        # into a window that could never score
        quarantined: Dict[str, float] = {}
        specs: Dict[str, Any] = {}
        for name in session.pending_machines(self.window_rows):
            spec = self._spec_for(fleet, name)
            specs[name] = spec
            retry_after = board.quarantined(fleet, spec, name)
            if retry_after is not None:
                quarantined[name] = retry_after
                chan = session.channel(name)
                if not chan.quarantine_notified:
                    chan.quarantine_notified = True
                    session.emit(
                        StreamEvent(
                            "quarantined",
                            {
                                "machine": name,
                                "retry_after_s": round(retry_after, 3),
                            },
                        )
                    )
        summary["quarantined"] = {
            name: round(retry, 3) for name, retry in quarantined.items()
        }

        cut = session.cut_windows(self.window_rows, skip=tuple(quarantined))
        if not cut:
            return summary

        inputs: Dict[str, Any] = {}
        spans: Dict[str, Tuple[int, int, int]] = {}
        injected: Dict[str, BaseException] = {}
        for name, (chunks, first_seq, last_seq, windows) in cut.items():
            spans[name] = (first_seq, last_seq, windows)
            try:
                fault_point(
                    "stream_score", f"{session.stream_id}:{name}"
                )
                inputs[name] = self._concat(chunks)
            except Exception as exc:  # noqa: BLE001 - injected poison or
                # a broken concat is THIS member's failure, nobody else's
                injected[name] = exc

        recorder = serve_trace.serve_recorder()
        total_rows = sum(int(len(x)) for x in inputs.values())
        with recorder.span(
            "stream_score",
            stream=session.stream_id,
            machines=len(inputs),
            rows=total_rows,
            revision=revision,
        ):
            scores, errors = (
                fleet.fleet_scores(inputs) if inputs else ({}, {})
            )
        errors.update(injected)

        for name, (reconstruction, mse) in scores.items():
            first_seq, last_seq, windows = spans[name]
            rows = int(len(inputs[name]))
            residuals = np.asarray(mse, dtype=float).ravel()
            finite = residuals[np.isfinite(residuals)]
            chan = session.channel(name)
            chan.rows_scored += rows
            chan.windows_scored += windows
            board.record_success(fleet, specs.get(name, FALLBACK_SPEC), name)
            if chan.quarantine_notified:
                chan.quarantine_notified = False
                session.emit(StreamEvent("recovered", {"machine": name}))
            session.emit(
                StreamEvent(
                    "anomaly",
                    {
                        "machine": name,
                        "first_seq": first_seq,
                        "last_seq": last_seq,
                        "rows": rows,
                        "windows": windows,
                        "mse_mean": (
                            float(finite.mean()) if len(finite) else None
                        ),
                        "mse_max": (
                            float(finite.max()) if len(finite) else None
                        ),
                        "revision": revision,
                    },
                )
            )
            summary["scored"][name] = rows
            summary["rows"] += rows

        for name, exc in errors.items():
            first_seq, last_seq, _windows = spans[name]
            rows = last_seq - first_seq + 1
            chan = session.channel(name)
            chan.score_errors += 1
            chan.rows_failed += rows
            # client-data failures are not the member's health problem —
            # same classification as the fleet route's ledger feed
            server_side = not isinstance(
                exc, (ValueError, TypeError, FileNotFoundError)
            )
            if server_side:
                board.record_failure(
                    fleet, specs.get(name, FALLBACK_SPEC), name, exc
                )
            session.emit(
                StreamEvent(
                    "error",
                    {
                        "machine": name,
                        "first_seq": first_seq,
                        "last_seq": last_seq,
                        "error": type(exc).__name__,
                    },
                )
            )
            summary["errors"][name] = type(exc).__name__

        self._feed_ledger(session, inputs, scores, errors)
        self._feed_drift(inputs, scores)
        return summary

    # -- feeds ---------------------------------------------------------------

    def _feed_ledger(
        self,
        session: StreamSession,
        frames: Dict[str, Any],
        scores: Dict[str, Tuple[Any, Any]],
        errors: Dict[str, BaseException],
    ) -> None:
        """Batch-wise fleet-health feed: one throttled snapshot write per
        flush, so a stream-only deployment still populates per-machine
        health exactly like HTTP scoring traffic would."""
        try:
            from ..telemetry import ledger_for

            anchor = self.ledger_anchor or session.collection_dir
            if not anchor:
                return
            ledger = ledger_for(anchor)
            if not ledger.enabled:
                return
            for name, (reconstruction, mse) in scores.items():
                residuals = np.asarray(mse, dtype=float).ravel()
                residuals = residuals[np.isfinite(residuals)]
                frame = frames.get(name)
                ledger.record_scores(
                    name,
                    len(frame) if frame is not None else len(residuals),
                    float(residuals.mean()) if len(residuals) else None,
                    write=False,
                )
                ledger.record_request(name)
            for name, exc in errors.items():
                ledger.record_request(
                    name,
                    error=not isinstance(
                        exc, (ValueError, TypeError, FileNotFoundError)
                    ),
                )
            ledger.write()
        except Exception:  # noqa: BLE001 - health telemetry is advisory
            logger.debug("stream health not recorded", exc_info=True)

    def _feed_drift(
        self,
        frames: Dict[str, Any],
        scores: Dict[str, Tuple[Any, Any]],
    ) -> None:
        monitor = self.drift_monitor
        if monitor is None or not frames:
            return
        try:
            monitor.observe_scores(frames, scores)
        except Exception:  # noqa: BLE001 - drift statistics are advisory
            logger.debug("stream drift feed failed", exc_info=True)
