from .data_provider import (
    GordoBaseDataProvider,
    FileDataProvider,
    ListBackedDataProvider,
    RandomDataProvider,
)
from .datasets import GordoBaseDataset, RandomDataset, TimeSeriesDataset
from .exceptions import (
    ConfigException,
    InsufficientDataError,
    NoSuitableDataProviderError,
)
from .sensor_tag import (
    SensorTag,
    normalize_sensor_tag,
    normalize_sensor_tags,
    to_list_of_strings,
    unique_tag_names,
)

__all__ = [
    "GordoBaseDataset",
    "TimeSeriesDataset",
    "RandomDataset",
    "GordoBaseDataProvider",
    "RandomDataProvider",
    "ListBackedDataProvider",
    "FileDataProvider",
    "SensorTag",
    "normalize_sensor_tag",
    "normalize_sensor_tags",
    "to_list_of_strings",
    "unique_tag_names",
    "ConfigException",
    "InsufficientDataError",
    "NoSuitableDataProviderError",
]
