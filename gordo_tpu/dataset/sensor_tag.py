"""
Sensor-tag domain type and normalization.

Reference parity: gordo-core's ``SensorTag`` surface as consumed by gordo
(gordo/utils.py:16-50, machine/machine.py:151-168): a tag has a ``name`` and
an optional ``asset``; configs may give tags as bare strings, dicts, or
(name, asset) lists.
"""

from dataclasses import dataclass
from typing import List, Optional, Sequence, Union


@dataclass(frozen=True)
class SensorTag:
    name: str
    asset: Optional[str] = None

    def to_json(self) -> dict:
        out = {"name": self.name}
        if self.asset is not None:
            out["asset"] = self.asset
        return out

    @classmethod
    def from_json(cls, obj: Union[str, dict, Sequence]) -> "SensorTag":
        return normalize_sensor_tag(obj)


class SensorTagNormalizationError(ValueError):
    pass


def normalize_sensor_tag(
    tag: Union[str, dict, Sequence, SensorTag], asset: Optional[str] = None
) -> SensorTag:
    """
    Coerce any config-level tag representation into a ``SensorTag``.

    >>> normalize_sensor_tag("TAG-1")
    SensorTag(name='TAG-1', asset=None)
    >>> normalize_sensor_tag({"name": "TAG-1", "asset": "plant-a"})
    SensorTag(name='TAG-1', asset='plant-a')
    >>> normalize_sensor_tag(["TAG-1", "plant-a"])
    SensorTag(name='TAG-1', asset='plant-a')
    """
    if isinstance(tag, SensorTag):
        return tag
    if isinstance(tag, str):
        return SensorTag(name=tag, asset=asset)
    if isinstance(tag, dict):
        if "name" not in tag:
            raise SensorTagNormalizationError(f"Tag dict missing 'name': {tag!r}")
        return SensorTag(name=tag["name"], asset=tag.get("asset", asset))
    if isinstance(tag, (list, tuple)):
        if not 1 <= len(tag) <= 2:
            raise SensorTagNormalizationError(f"Tag sequence malformed: {tag!r}")
        return SensorTag(
            name=tag[0], asset=tag[1] if len(tag) > 1 else asset
        )
    raise SensorTagNormalizationError(f"Unrecognized tag form: {tag!r}")


def normalize_sensor_tags(
    tags: Sequence[Union[str, dict, Sequence, SensorTag]],
    asset: Optional[str] = None,
) -> List[SensorTag]:
    """Normalize a config tag list into ``SensorTag`` objects."""
    return [normalize_sensor_tag(tag, asset=asset) for tag in tags]


def to_list_of_strings(tags: Sequence[Union[str, SensorTag]]) -> List[str]:
    """Tag names as plain strings (column labels, metadata)."""
    return [tag.name if isinstance(tag, SensorTag) else str(tag) for tag in tags]


def unique_tag_names(tags: Sequence[Union[str, SensorTag]]) -> dict:
    """
    Map tag name → SensorTag (insertion-ordered union). Repeats of the same
    tag are fine; the same name bound to two different assets is an error
    (the join would produce ambiguous columns).
    """
    by_name = {}
    for tag in tags:
        normalized = normalize_sensor_tag(tag)
        existing = by_name.get(normalized.name)
        if existing is not None and existing != normalized:
            raise SensorTagNormalizationError(
                f"Tag name {normalized.name!r} bound to conflicting definitions: "
                f"{existing} vs {normalized}"
            )
        by_name[normalized.name] = normalized
    return by_name
