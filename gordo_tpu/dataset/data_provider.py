"""
Data providers: pluggable sources of raw per-tag time series.

Reference parity: gordo-core's ``GordoBaseDataProvider`` surface
(``load_series``, ``can_handle_tag``, ``to_dict``/``from_dict``) and
``RandomDataProvider``, the deterministic synthetic source used across the
reference's entire test suite (SURVEY.md §4).

Providers return host-side pandas Series; the dataset layer joins/resamples
them into aligned arrays which are then staged to TPU once per build — the
provider itself is deliberately device-unaware.
"""

import abc
import hashlib
import os
from typing import Dict, Iterable, List, Optional

import numpy as np
import pandas as pd

from ..serializer.import_utils import import_location
from ..utils import capture_args
from .sensor_tag import SensorTag, normalize_sensor_tags


class GordoBaseDataProvider(abc.ABC):
    @abc.abstractmethod
    def load_series(
        self,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
        tag_list: List[SensorTag],
        dry_run: bool = False,
        **kwargs,
    ) -> Iterable[pd.Series]:
        """Yield one raw ``pd.Series`` (DatetimeIndex) per requested tag."""

    @abc.abstractmethod
    def can_handle_tag(self, tag: SensorTag) -> bool:
        """Whether this provider can serve ``tag``."""

    def to_dict(self) -> dict:
        params = getattr(self, "_params", {})
        return {
            "type": f"{type(self).__module__}.{type(self).__name__}",
            **params,
        }

    @classmethod
    def from_dict(cls, config: dict) -> "GordoBaseDataProvider":
        config = dict(config)
        provider_type = config.pop("type", None)
        if provider_type is None:
            return cls(**config)
        if "." not in provider_type:
            # Bare names as the reference example configs use them
            # (examples/config.yaml: ``type: RandomDataProvider``); resolved
            # against this module, like gordo-core's provider registry.
            import sys

            candidate = getattr(sys.modules[__name__], provider_type, None)
            if candidate is None or not (
                isinstance(candidate, type) and issubclass(candidate, cls)
            ):
                raise ValueError(
                    f"Unknown data provider short name: {provider_type!r}"
                )
            ProviderClass: type = candidate
        else:
            ProviderClass = import_location(provider_type)
        return ProviderClass(**config)


class RandomDataProvider(GordoBaseDataProvider):
    """
    Deterministic synthetic sensor data for tests, examples and benchmarks.

    Each tag's series is a reproducible function of (tag name, date range,
    resolution): a smooth mixture of sinusoids plus noise, seeded by the tag
    name so the same config always yields the same data.
    """

    @capture_args
    def __init__(self, min_size: int = 100, max_size: int = 300, **kwargs):
        self.min_size = min_size
        self.max_size = max_size

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return True

    def _rng_for(self, tag: SensorTag) -> np.random.RandomState:
        digest = hashlib.sha256(tag.name.encode()).digest()
        return np.random.RandomState(int.from_bytes(digest[:4], "little"))

    def load_series(
        self,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
        tag_list: List[SensorTag],
        dry_run: bool = False,
        **kwargs,
    ) -> Iterable[pd.Series]:
        if train_start_date >= train_end_date:
            raise ValueError(
                f"train_start_date ({train_start_date}) must be before "
                f"train_end_date ({train_end_date})"
            )
        for tag in normalize_sensor_tags(tag_list):
            rng = self._rng_for(tag)
            n_points = rng.randint(self.min_size, self.max_size + 1)
            stamps = np.linspace(
                pd.Timestamp(train_start_date).value,
                pd.Timestamp(train_end_date).value,
                n_points,
            ).astype("int64")
            index = pd.DatetimeIndex(stamps.view("M8[ns]"))
            tz = getattr(train_start_date, "tz", None)
            if tz is not None:
                # .value above is UTC ns; localize back to the input tz
                index = index.tz_localize("UTC").tz_convert(tz)
            t = np.linspace(0.0, 2 * np.pi * rng.uniform(1.0, 6.0), n_points)
            base = rng.uniform(-50.0, 50.0)
            amplitude = rng.uniform(0.5, 10.0)
            values = (
                base
                + amplitude * np.sin(t + rng.uniform(0, 2 * np.pi))
                + 0.1 * amplitude * rng.standard_normal(n_points)
            )
            yield pd.Series(values, index=index, name=tag.name)


class FileDataProvider(GordoBaseDataProvider):
    """
    Tag series from parquet/CSV files on disk — the provider that makes
    ``local_build`` / ``build-fleet`` train on real exported data instead
    of synthetic series (reference surface: gordo-core's provider contract,
    SURVEY.md §2.9; resolvable from YAML as
    ``data_provider: {type: FileDataProvider, path: ...}``).

    Two on-disk layouts:

    - **wide file** — ``path`` is one file whose columns are tags and whose
      index (or ``timestamp_column``) holds timestamps::

          data_provider:
            type: FileDataProvider
            path: /data/plant-a.parquet
            timestamp_column: time       # optional; default: file index

    - **per-tag directory** — ``path`` is a directory of
      ``<tag-name>.parquet`` / ``<tag-name>.csv`` files, each holding one
      series (``timestamp_column`` + ``value_column``, defaulting to the
      first and second columns).

    ``tag_column_map`` renames: ``{config tag name: column or file name}``.
    Naive timestamps are localized to ``tz`` (default UTC) — gordo's train
    window bounds are always tz-aware.
    """

    _FORMATS = {
        ".parquet": "parquet",
        ".pq": "parquet",
        ".csv": "csv",
    }

    @capture_args
    def __init__(
        self,
        path: str,
        timestamp_column: Optional[str] = None,
        value_column: Optional[str] = None,
        tag_column_map: Optional[Dict[str, str]] = None,
        tz: str = "UTC",
        **kwargs,
    ):
        self.path = path
        self.timestamp_column = timestamp_column
        self.value_column = value_column
        self.tag_column_map = tag_column_map or {}
        self.tz = tz
        self._wide_frame: Optional[pd.DataFrame] = None

    # -- file plumbing -------------------------------------------------------

    def _format_of(self, path: str) -> str:
        ext = os.path.splitext(path)[1].lower()
        file_format = self._FORMATS.get(ext)
        if file_format is None:
            raise ValueError(
                f"Unsupported file format {ext!r} for {path!r} "
                f"(supported: {sorted(self._FORMATS)})"
            )
        return file_format

    def _read_frame(self, path: str) -> pd.DataFrame:
        if self._format_of(path) == "parquet":
            frame = pd.read_parquet(path)
        else:
            frame = pd.read_csv(path)
        ts_col = self.timestamp_column
        if ts_col is None and not isinstance(frame.index, pd.DatetimeIndex):
            ts_col = frame.columns[0]
        if ts_col is not None:
            if ts_col not in frame.columns:
                raise ValueError(
                    f"Timestamp column {ts_col!r} not present in {path!r} "
                    f"(columns: {list(frame.columns)})"
                )
            frame = frame.set_index(ts_col)
        frame.index = pd.DatetimeIndex(pd.to_datetime(frame.index))
        if frame.index.tz is None:
            frame.index = frame.index.tz_localize(self.tz)
        return frame.sort_index()

    def _column_for(self, tag: SensorTag) -> str:
        return self.tag_column_map.get(tag.name, tag.name)

    def _is_directory_layout(self) -> bool:
        return os.path.isdir(self.path)

    def _tag_file(self, tag: SensorTag) -> Optional[str]:
        column = self._column_for(tag)
        for ext in self._FORMATS:
            candidate = os.path.join(self.path, column + ext)
            if os.path.isfile(candidate):
                return candidate
        return None

    def _wide(self) -> pd.DataFrame:
        if self._wide_frame is None:
            self._wide_frame = self._read_frame(self.path)
        return self._wide_frame

    # -- provider contract ---------------------------------------------------

    def can_handle_tag(self, tag: SensorTag) -> bool:
        if self._is_directory_layout():
            return self._tag_file(tag) is not None
        try:
            return self._column_for(tag) in self._wide().columns
        except (OSError, ValueError):
            return False

    def _series_for(self, tag: SensorTag) -> pd.Series:
        if self._is_directory_layout():
            tag_file = self._tag_file(tag)
            if tag_file is None:
                raise ValueError(
                    f"No file for tag {tag.name!r} under {self.path!r}"
                )
            frame = self._read_frame(tag_file)
            column = self.value_column or frame.columns[0]
            if column not in frame.columns:
                raise ValueError(
                    f"Value column {column!r} not present in {tag_file!r}"
                )
            return frame[column].rename(tag.name)
        frame = self._wide()
        column = self._column_for(tag)
        if column not in frame.columns:
            raise ValueError(
                f"Tag {tag.name!r} (column {column!r}) not present in "
                f"{self.path!r} (columns: {list(frame.columns)})"
            )
        return frame[column].rename(tag.name)

    def load_series(
        self,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
        tag_list: List[SensorTag],
        dry_run: bool = False,
        **kwargs,
    ) -> Iterable[pd.Series]:
        if train_start_date >= train_end_date:
            raise ValueError(
                f"train_start_date ({train_start_date}) must be before "
                f"train_end_date ({train_end_date})"
            )
        for tag in normalize_sensor_tags(tag_list):
            series = self._series_for(tag)
            yield series[
                (series.index >= train_start_date) & (series.index < train_end_date)
            ]


class ListBackedDataProvider(GordoBaseDataProvider):
    """In-memory provider wrapping pre-built series; used by tests/tools."""

    @capture_args
    def __init__(self, series: Optional[List[pd.Series]] = None, **kwargs):
        self.series = series or []

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return any(s.name == tag.name for s in self.series)

    def load_series(
        self,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
        tag_list: List[SensorTag],
        dry_run: bool = False,
        **kwargs,
    ) -> Iterable[pd.Series]:
        by_name = {s.name: s for s in self.series}
        for tag in normalize_sensor_tags(tag_list):
            series = by_name[tag.name]
            yield series[(series.index >= train_start_date) & (series.index < train_end_date)]


class InfluxDataProvider(GordoBaseDataProvider):
    """
    Tag series from an InfluxDB (1.x line) time-series database — the
    production reader that closes the data loop the Influx *forwarder*
    opens (client/forwarders.py ForwardPredictionsIntoInflux; the
    reference ecosystem reads sensor data through gordo-core's influx
    provider, pinned at
    /root/reference/requirements/full_requirements.txt:139-142, and its
    Argo client step replays predictions into the same Influx the
    dashboards read — argo-workflow.yml.template:1374-1376).

    Two on-wire layouts:

    - **sensor layout** (default): one shared ``measurement`` whose rows
      are distinguished by an Influx tag (``tag_key``, default ``tag``)
      holding the sensor name, values in field ``value_name``::

          data_provider:
            type: InfluxDataProvider
            measurement: sensors
            uri: user:pass@influx:8086/dbname

    - **field layout** (``fields_are_tags: true``): sensor names are the
      measurement's *fields* — exactly what
      ``ForwardPredictionsIntoInflux`` writes (pipe-joined prediction
      columns as fields, one ``machine`` Influx tag), so a dataset can
      train on replayed predictions::

          data_provider:
            type: InfluxDataProvider
            measurement: predictions
            fields_are_tags: true
            where_tags: {machine: my-machine}

    ``client`` injects a ready ``influxdb.DataFrameClient``-compatible
    object (tests use an in-memory fake); otherwise ``uri`` is parsed
    exactly like the forwarder's
    (``<username>:<password>@<host>:<port>/<db_name>``).
    """

    @capture_args
    def __init__(
        self,
        measurement: str,
        value_name: str = "Value",
        tag_key: str = "tag",
        fields_are_tags: bool = False,
        where_tags: Optional[Dict[str, str]] = None,
        uri: Optional[str] = None,
        api_key: Optional[str] = None,
        api_key_header: str = "Ocp-Apim-Subscription-Key",
        client=None,
        **kwargs,
    ):
        self.measurement = measurement
        self.value_name = value_name
        self.tag_key = tag_key
        self.fields_are_tags = fields_are_tags
        self.where_tags = where_tags or {}
        self.uri = uri
        self.api_key = api_key
        self.api_key_header = api_key_header
        self.influx_client = client
        if self.influx_client is None and uri:
            self.influx_client = self._client_from_uri(uri)

    def _client_from_uri(self, uri: str):  # pragma: no cover - needs influxdb
        try:
            from influxdb import DataFrameClient
        except ImportError as exc:
            raise ImportError(
                "The influxdb package is required for InfluxDataProvider "
                "(or pass client=...)"
            ) from exc

        username, password, host, port, *_, db_name = (
            uri.replace("/", ":").replace("@", ":").split(":")
        )
        return DataFrameClient(
            host=host,
            port=int(port),
            username=username,
            password=password,
            database=db_name,
            headers={self.api_key_header: self.api_key} if self.api_key else None,
        )

    def _require_client(self):
        if self.influx_client is None:
            raise ValueError(
                "InfluxDataProvider has no client; pass uri=... or client=..."
            )
        return self.influx_client

    @staticmethod
    def _escape(identifier: str) -> str:
        # InfluxQL string literals backslash-escape; backslashes first so
        # a trailing backslash can't swallow the closing quote (or a
        # crafted value extend the WHERE clause)
        return identifier.replace("\\", "\\\\").replace("'", "\\'")

    def _query_series(
        self,
        tag: SensorTag,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
    ) -> pd.Series:
        client = self._require_client()
        start_ns = int(pd.Timestamp(train_start_date).value)
        end_ns = int(pd.Timestamp(train_end_date).value)
        conditions = [f"time >= {start_ns} AND time < {end_ns}"]
        if self.fields_are_tags:
            field = tag.name
        else:
            field = self.value_name
            conditions.append(
                f"\"{self.tag_key}\" = '{self._escape(tag.name)}'"
            )
        for key, value in self.where_tags.items():
            conditions.append(f"\"{key}\" = '{self._escape(str(value))}'")
        query = (
            f'SELECT "{field}" FROM "{self.measurement}" '
            f"WHERE {' AND '.join(conditions)}"
        )
        result = client.query(query)
        frame = result.get(self.measurement) if hasattr(result, "get") else None
        if frame is None or len(frame) == 0:
            raise ValueError(
                f"No data for tag {tag.name!r} in measurement "
                f"{self.measurement!r} over [{train_start_date}, "
                f"{train_end_date})"
            )
        series = frame[field].rename(tag.name)
        index = pd.DatetimeIndex(pd.to_datetime(series.index))
        if index.tz is None:
            index = index.tz_localize("UTC")
        series.index = index
        return series.sort_index()

    def can_handle_tag(self, tag: SensorTag) -> bool:
        # Availability is a per-window property in a TSDB; existence is
        # checked by the read itself (ValueError names the tag/window).
        return self.influx_client is not None or bool(self.uri)

    def load_series(
        self,
        train_start_date: pd.Timestamp,
        train_end_date: pd.Timestamp,
        tag_list: List[SensorTag],
        dry_run: bool = False,
        **kwargs,
    ) -> Iterable[pd.Series]:
        if train_start_date >= train_end_date:
            raise ValueError(
                f"train_start_date ({train_start_date}) must be before "
                f"train_end_date ({train_end_date})"
            )
        for tag in normalize_sensor_tags(tag_list):
            yield self._query_series(tag, train_start_date, train_end_date)
