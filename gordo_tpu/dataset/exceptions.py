"""
Dataset-layer exceptions.

Reference parity: gordo-core's exceptions as consumed by gordo's builder exit
-code map (gordo/cli/cli.py:26-39): ``ConfigException``,
``InsufficientDataError``, ``NoSuitableDataProviderError``.
"""


class ConfigException(ValueError):
    """Invalid dataset/machine configuration."""


class InsufficientDataError(ValueError):
    """Raised when the dataset resolves to fewer rows than required."""


class NoSuitableDataProviderError(ValueError):
    """No registered data provider can serve the requested tags."""
