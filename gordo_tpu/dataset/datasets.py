"""
Dataset layer: config-described time-series datasets yielding (X, y) frames.

Reference parity: gordo-core's ``GordoBaseDataset`` surface as consumed by
gordo (SURVEY.md §2.9): ``from_dict`` / ``to_dict`` / ``get_data`` /
``get_metadata``, ``TimeSeriesDataset`` (join + resample + filter of per-tag
series) and ``RandomDataset`` (synthetic provider variant used in every test
and example config).

TPU-first note: ``get_data`` returns host pandas frames (the provider/IO
plane), while ``trainable_arrays`` hands back float32 numpy ready for a
single ``jax.device_put`` — the fleet builder stages one stacked array per
compilation bucket instead of thousands of small transfers.
"""

import abc
import logging
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from ..serializer.import_utils import import_location
from ..utils import capture_args
from .data_provider import GordoBaseDataProvider, RandomDataProvider
from .exceptions import ConfigException, InsufficientDataError
from .sensor_tag import (
    SensorTag,
    normalize_sensor_tags,
    to_list_of_strings,
    unique_tag_names,
)

logger = logging.getLogger(__name__)

DEFAULT_RESOLUTION = "10min"

#: aggregations where an all-NaN bin stays NaN — the precondition for the
#: one-pass resample fast path's span-intersection trim ("sum"/"count"
#: would turn out-of-span bins into 0 and fabricate rows)
_NAN_PRESERVING_AGGS = frozenset(
    {"mean", "median", "min", "max", "first", "last", "std", "var"}
)


def _interpolate_linear_limited(data: pd.DataFrame, limit: int) -> pd.DataFrame:
    """
    ``DataFrame.interpolate(method="linear", limit=limit)`` in vectorized
    numpy — bit-identical to pandas (parity-tested against it in
    tests/dataset/test_datasets.py) but ~100× cheaper: pandas routes the
    limit logic through ``apply_along_axis`` per column, which measured
    ~0.25s per machine on the build path (minutes at 1000-machine scale).

    Pandas "linear" semantics (positional, ignores index spacing):
    leading NaNs stay NaN; interior gaps fill linearly between anchors but
    only the first ``limit`` positions of each gap; trailing NaNs repeat
    the last valid value, also up to ``limit``.
    """
    try:
        values = data.to_numpy(dtype=np.float64, copy=True)
    except (TypeError, ValueError):
        # non-numeric columns (never produced by resample, but a custom
        # provider could) — keep pandas' own path for them
        return data.interpolate(method="linear", limit=limit)
    n = len(values)
    if n == 0:
        return data
    positions = np.arange(n)
    for col in range(values.shape[1]):
        column = values[:, col]
        nan_mask = np.isnan(column)
        if not nan_mask.any():
            continue
        valid = ~nan_mask
        if not valid.any():
            continue
        valid_idx = np.flatnonzero(valid)
        filled = np.interp(positions, valid_idx, column[valid_idx])
        # distance to the previous valid observation gates the fill
        prev_valid = np.maximum.accumulate(np.where(valid, positions, -1))
        gap_run = positions - prev_valid
        fill = nan_mask & (prev_valid >= 0) & (gap_run <= limit)
        column[fill] = filled[fill]
    result = pd.DataFrame(values, index=data.index, columns=data.columns)
    # pandas.interpolate preserves per-column dtypes; the f64 work buffer
    # must not leak into the result for e.g. float32 input frames, or the
    # drop-in-replacement claim only holds for f64 callers. (Duplicate
    # column labels keep the f64 frame — astype-by-dict can't address
    # them, and the resample product path never produces duplicates.)
    if data.columns.is_unique and any(dt != np.float64 for dt in data.dtypes):
        result = result.astype(dict(zip(data.columns, data.dtypes)))
    return result


def normalize_frequency(resolution: str) -> str:
    """
    Accept legacy pandas offset aliases ('10T', '1H') alongside the modern
    spellings pandas ≥3 requires ('10min', '1h').

    >>> normalize_frequency("10T")
    '10min'
    >>> normalize_frequency("1H")
    '1h'
    >>> normalize_frequency("30s")
    '30s'
    """
    replacements = {"T": "min", "H": "h", "S": "s", "L": "ms"}
    for legacy, modern in replacements.items():
        if resolution.endswith(legacy):
            return resolution[: -len(legacy)] + modern
    return resolution


class GordoBaseDataset(abc.ABC):
    @abc.abstractmethod
    def get_data(self) -> Tuple[pd.DataFrame, pd.DataFrame]:
        """Return (X, y) training frames with aligned DatetimeIndex."""

    @abc.abstractmethod
    def get_metadata(self) -> dict:
        """Dataset build metadata recorded by the builder."""

    def to_dict(self) -> dict:
        params = dict(getattr(self, "_params", {}))
        if "data_provider" in params and isinstance(
            params["data_provider"], GordoBaseDataProvider
        ):
            params["data_provider"] = params["data_provider"].to_dict()
        params["tag_list"] = [
            tag.to_json() if isinstance(tag, SensorTag) else tag
            for tag in params.get("tag_list", [])
        ]
        if params.get("target_tag_list"):
            params["target_tag_list"] = [
                tag.to_json() if isinstance(tag, SensorTag) else tag
                for tag in params["target_tag_list"]
            ]
        for key in ("train_start_date", "train_end_date"):
            if key in params and isinstance(params[key], pd.Timestamp):
                params[key] = params[key].isoformat()
        params["type"] = f"{type(self).__module__}.{type(self).__name__}"
        return params

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "GordoBaseDataset":
        """
        Resolve ``config["type"]`` (default ``TimeSeriesDataset``) and
        construct the dataset; mirrors gordo-core's dataset factory consumed
        at gordo/machine/machine.py and builder/build_model.py.
        """
        config = dict(config)
        # gordo-core accepts `tags` / `target_tags` aliases (the reference's
        # examples/config.yaml uses `tags:`); normalize to the canonical keys.
        for alias, canonical in (("tags", "tag_list"), ("target_tags", "target_tag_list")):
            if alias in config and canonical not in config:
                config[canonical] = config.pop(alias)
        dataset_type = config.pop("type", None)
        if dataset_type is None or dataset_type in (
            "TimeSeriesDataset",
            "gordo_dataset.datasets.TimeSeriesDataset",
        ):
            DatasetClass: type = TimeSeriesDataset
        elif dataset_type in ("RandomDataset", "gordo_dataset.datasets.RandomDataset"):
            DatasetClass = RandomDataset
        else:
            DatasetClass = import_location(dataset_type)
        return DatasetClass(**config)


def _parse_timestamp(value: Union[str, pd.Timestamp]) -> pd.Timestamp:
    ts = pd.Timestamp(value) if not isinstance(value, pd.Timestamp) else value
    if ts.tz is None:
        raise ConfigException(
            f"Timestamp {value!r} must be timezone-aware (reference requires "
            "tz-aware datetimes: gordo/machine/validators.py:234-253)"
        )
    return ts


class TimeSeriesDataset(GordoBaseDataset):
    """
    Joins per-tag series from a data provider onto a uniform time grid.

    Steps in ``get_data``: load raw series → resample each to ``resolution``
    with ``aggregation_methods`` → inner-join across tags → apply
    ``row_filter`` / ``known_filter_periods`` → enforce
    ``n_samples_threshold`` → split into X (tag_list) and y
    (target_tag_list, defaulting to tag_list).
    """

    @capture_args
    def __init__(
        self,
        train_start_date: Union[str, pd.Timestamp],
        train_end_date: Union[str, pd.Timestamp],
        tag_list: List[Union[str, dict, SensorTag]],
        target_tag_list: Optional[List[Union[str, dict, SensorTag]]] = None,
        data_provider: Optional[Union[dict, GordoBaseDataProvider]] = None,
        resolution: str = DEFAULT_RESOLUTION,
        row_filter: str = "",
        known_filter_periods: Optional[List[Tuple[str, str]]] = None,
        aggregation_methods: Union[str, List[str]] = "mean",
        n_samples_threshold: int = 0,
        low_threshold: Optional[float] = None,
        high_threshold: Optional[float] = None,
        interpolation_method: str = "linear_interpolation",
        interpolation_limit: str = "8h",
        asset: Optional[str] = None,
        **kwargs,
    ):
        self.train_start_date = _parse_timestamp(train_start_date)
        self.train_end_date = _parse_timestamp(train_end_date)
        if self.train_start_date >= self.train_end_date:
            raise ConfigException(
                f"train_end_date ({self.train_end_date}) must be after "
                f"train_start_date ({self.train_start_date})"
            )
        self.tag_list = normalize_sensor_tags(tag_list, asset=asset)
        self.target_tag_list = (
            normalize_sensor_tags(target_tag_list, asset=asset)
            if target_tag_list
            else list(self.tag_list)
        )
        unique_tag_names(self.tag_list)
        if data_provider is None:
            data_provider = RandomDataProvider()
        self.data_provider = (
            GordoBaseDataProvider.from_dict(data_provider)
            if isinstance(data_provider, dict)
            else data_provider
        )
        self.resolution = normalize_frequency(resolution)
        self.row_filter = row_filter
        self.known_filter_periods = known_filter_periods or []
        self.aggregation_methods = aggregation_methods
        self.n_samples_threshold = n_samples_threshold
        self.low_threshold = low_threshold
        self.high_threshold = high_threshold
        self.interpolation_method = interpolation_method
        self.interpolation_limit = interpolation_limit
        self._metadata: Dict[str, Any] = {}

    def _load_and_join(self) -> pd.DataFrame:
        all_tags = unique_tag_names(list(self.tag_list) + list(self.target_tag_list))
        series_list = list(
            self.data_provider.load_series(
                self.train_start_date, self.train_end_date, list(all_tags.values())
            )
        )
        if not series_list:
            raise InsufficientDataError("Data provider returned no series")

        for series in series_list:
            if series.empty:
                raise InsufficientDataError(
                    f"Tag {series.name!r} has no data in "
                    f"[{self.train_start_date}, {self.train_end_date}]"
                )

        data = None
        if (
            isinstance(self.aggregation_methods, str)
            and self.aggregation_methods in _NAN_PRESERVING_AGGS
        ):
            seconds = pd.Timedelta(self.resolution).total_seconds()
            # one resample pass over an aligned frame is ~n_tags× faster
            # than per-series resampling, and bin-exact only when the
            # resolution divides a day (bins midnight-anchored for every
            # series regardless of its first observation's day)
            if seconds > 0 and 86400 % seconds == 0:
                try:
                    data = self._resample_joined(series_list)
                except (ValueError, TypeError, pd.errors.InvalidIndexError):
                    data = None  # ragged/duplicate indexes: per-series path
        if data is None:
            resampled = []
            for series in series_list:
                agg = series.resample(self.resolution).agg(self.aggregation_methods)
                if isinstance(agg, pd.DataFrame):  # multiple aggregation methods
                    agg.columns = [f"{series.name}_{m}" for m in agg.columns]
                resampled.append(agg)
            data = pd.concat(resampled, axis=1, join="inner")
            if isinstance(self.aggregation_methods, str):
                data.columns = [s.name for s in series_list]
        interp_limit = max(
            int(pd.Timedelta(self.interpolation_limit) / pd.Timedelta(self.resolution)),
            1,
        )
        if self.interpolation_method == "linear_interpolation":
            data = _interpolate_linear_limited(data, interp_limit)
        elif self.interpolation_method == "ffill":
            data = data.ffill(limit=interp_limit)
        return data.dropna()

    def _resample_joined(self, series_list: List[pd.Series]) -> pd.DataFrame:
        """
        Single-aggregation fast path: every tag resampled in ONE pass
        (only for the NaN-preserving aggregations in
        ``_NAN_PRESERVING_AGGS`` — a method like ``sum`` turns the all-NaN
        bins outside a tag's span into 0, which would defeat the
        span-intersection trim below and fabricate data).

        Equivalent to per-series resample + inner concat: the raw series
        are outer-aligned (NaN where a tag lacks a stamp; the NaN-skipping
        per-column agg then sees exactly each tag's own observations per
        bin), resampled as one frame, and trimmed to the intersection of
        per-tag spans — a tag's first/last valid bins are the bins holding
        its first/last observations, exactly where its own resample would
        start and end. Raises for ragged/duplicate indexes the aligner
        can't handle; the caller falls back to the per-series path.

        The outer alignment itself is a numpy int64-ns union +
        searchsorted scatter — ``pd.concat(axis=1, sort=True)`` does a
        k-way index union through per-series reindex machinery that
        measured ~20ms per machine on the build path (20 tags).
        """
        raw = self._outer_align(series_list)
        data = raw.resample(self.resolution).agg(self.aggregation_methods)
        # Trim by bin LABELS of each series' observed span (floor is
        # midnight-anchored like resample's origin for day-dividing
        # resolutions) — not by first/last valid aggregated values: a
        # boundary bin can legitimately aggregate to NaN (std of a single
        # observation, NaN-valued raw samples) and must still be kept,
        # exactly as the per-series inner join keeps it.
        start = max(s.index.min().floor(self.resolution) for s in series_list)
        end = min(s.index.max().floor(self.resolution) for s in series_list)
        return data.loc[start:end]

    @staticmethod
    def _outer_align(series_list: List[pd.Series]) -> pd.DataFrame:
        """NaN-padded outer join of the raw tag series, equivalent to
        ``pd.concat(series_list, axis=1, sort=True)`` for unique sorted
        tz-homogeneous indexes; raises InvalidIndexError otherwise (the
        resample-path caller falls back to per-series resampling, exactly
        as it does when pandas' own concat raises)."""
        def index_unit(index) -> str:
            dtype = index.dtype
            if hasattr(dtype, "unit"):  # tz-aware DatetimeTZDtype
                return dtype.unit
            return np.datetime_data(dtype)[0]

        tzs = {getattr(s.index, "tz", None) for s in series_list}
        int_indexes = []
        units = set()
        for s in series_list:
            if not isinstance(s.index, pd.DatetimeIndex) or not s.index.is_unique:
                raise pd.errors.InvalidIndexError(f"index of {s.name!r}")
            units.add(index_unit(s.index))
            int_indexes.append(s.index.asi8)
        # asi8 is in the index's own resolution (pandas ≥2 indexes can be
        # s/ms/us/ns), so the epoch ints only union across a single unit
        if len(tzs) > 1 or len(units) > 1:
            raise pd.errors.InvalidIndexError("mixed index timezones or units")
        unit = units.pop()
        union = np.unique(np.concatenate(int_indexes))
        values = np.full((len(union), len(series_list)), np.nan)
        for j, s in enumerate(series_list):
            values[np.searchsorted(union, int_indexes[j]), j] = s.to_numpy(
                dtype=np.float64, na_value=np.nan
            )
        index = pd.DatetimeIndex(union.view(f"M8[{unit}]"))
        tz = tzs.pop()
        if tz is not None:
            index = index.tz_localize("UTC").tz_convert(tz)
        return pd.DataFrame(
            values, index=index, columns=[s.name for s in series_list]
        )

    def _apply_filters(self, data: pd.DataFrame) -> pd.DataFrame:
        n_before = len(data)
        for period in self.known_filter_periods:
            if not period:
                continue
            start, end = pd.Timestamp(period[0]), pd.Timestamp(period[1])
            data = data[(data.index < start) | (data.index > end)]
        if self.row_filter:
            data = data.query(self.row_filter)
        if self.low_threshold is not None:
            data = data[(data > self.low_threshold).all(axis=1)]
        if self.high_threshold is not None:
            data = data[(data < self.high_threshold).all(axis=1)]
        self._metadata["filtered_rows"] = n_before - len(data)
        return data

    def get_data(self) -> Tuple[pd.DataFrame, pd.DataFrame]:
        data = self._apply_filters(self._load_and_join())
        if len(data) <= self.n_samples_threshold:
            raise InsufficientDataError(
                f"Dataset resolved to {len(data)} rows, below threshold "
                f"{self.n_samples_threshold}"
            )
        x_names = to_list_of_strings(self.tag_list)
        y_names = to_list_of_strings(self.target_tag_list)
        if not isinstance(self.aggregation_methods, str):
            # Multiple aggregations widen each tag into '{tag}_{method}'
            x_names = [
                f"{name}_{method}"
                for name in x_names
                for method in self.aggregation_methods
            ]
            y_names = [
                f"{name}_{method}"
                for name in y_names
                for method in self.aggregation_methods
            ]
        X = data[x_names]
        y = data[y_names]
        self._metadata.update(
            {
                "train_start_date": self.train_start_date.isoformat(),
                "train_end_date": self.train_end_date.isoformat(),
                "resolution": self.resolution,
                "row_count": len(X),
                "tag_list": [t.to_json() for t in self.tag_list],
                "target_tag_list": [t.to_json() for t in self.target_tag_list],
                "x_hist": self._column_histograms(X),
            }
        )
        return X, y

    @staticmethod
    def _column_histograms(X: pd.DataFrame) -> Dict[str, Dict[str, float]]:
        """Per-tag summary stats in four vectorized reductions (pandas'
        per-column Series reductions measured ~10ms/machine at 20 tags).
        ``ddof=1`` matches ``Series.std``; NaN-aware to keep parity on
        frames that skipped interpolation."""
        values = X.to_numpy(dtype=np.float64)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN columns
            mins = np.nanmin(values, axis=0)
            maxs = np.nanmax(values, axis=0)
            means = np.nanmean(values, axis=0)
            stds = np.nanstd(values, axis=0, ddof=1)
        return {
            str(name): {
                "min": float(mins[i]),
                "max": float(maxs[i]),
                "mean": float(means[i]),
                "std": float(stds[i]),
            }
            for i, name in enumerate(X.columns)
        }

    def trainable_arrays(self) -> Tuple[np.ndarray, np.ndarray, pd.Index]:
        """(X, y) as float32 numpy plus the shared index — one device_put away
        from TPU."""
        X, y = self.get_data()
        return (
            np.ascontiguousarray(X.to_numpy(), dtype=np.float32),
            np.ascontiguousarray(y.to_numpy(), dtype=np.float32),
            X.index,
        )

    def get_metadata(self) -> dict:
        return dict(self._metadata)


class RandomDataset(TimeSeriesDataset):
    """TimeSeriesDataset pinned to the deterministic RandomDataProvider."""

    @capture_args
    def __init__(
        self,
        train_start_date: Union[str, pd.Timestamp],
        train_end_date: Union[str, pd.Timestamp],
        tag_list: List[Union[str, dict, SensorTag]],
        **kwargs,
    ):
        kwargs.pop("data_provider", None)
        super().__init__(
            train_start_date=train_start_date,
            train_end_date=train_end_date,
            tag_list=tag_list,
            data_provider=RandomDataProvider(),
            **kwargs,
        )
