from .build_model import ModelBuilder
from .local_build import local_build
from .utils import create_model_builder

__all__ = ["ModelBuilder", "local_build", "create_model_builder"]
