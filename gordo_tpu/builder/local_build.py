"""
Dev/test loop: full YAML config → trained models, no orchestration plane.

Reference parity: gordo/builder/local_build.py:14-70 — parse the config
through NormalizedConfig and yield ``ModelBuilder(machine).build()`` per
machine. The whole test pyramid stands on this path (SURVEY.md §3.4).
"""

from io import StringIO
from typing import Iterable, Tuple

import yaml

from ..machine import Machine
from .build_model import ModelBuilder


def local_build(
    config_str: str, project_name: str = "local-build"
) -> Iterable[Tuple[object, Machine]]:
    """
    Build every machine in a YAML config locally.

    Example
    -------
    >>> import io
    >>> config = '''
    ... machines:
    ...   - name: machine-1
    ...     dataset:
    ...       type: RandomDataset
    ...       train_start_date: "2020-01-01T00:00:00+00:00"
    ...       train_end_date: "2020-02-01T00:00:00+00:00"
    ...       tag_list: [tag-1, tag-2]
    ...     model:
    ...       gordo_tpu.models.JaxAutoEncoder:
    ...         kind: feedforward_hourglass
    ...         epochs: 1
    ... '''  # doctest: +SKIP
    >>> model, machine = next(local_build(config))  # doctest: +SKIP
    """
    from ..workflow.config_elements.normalized_config import NormalizedConfig

    config = yaml.safe_load(StringIO(config_str))
    normalized = NormalizedConfig(config, project_name=project_name)
    for machine in normalized.machines:
        yield ModelBuilder(machine=machine).build()
