"""
ModelBuilder: the full train pipeline for one machine.

Reference parity: gordo/builder/build_model.py — seeding, dataset fetch,
model construction from definition, CV per ``evaluation.cv_mode``
(full_build / cross_val_only / build_only) with per-tag + aggregate metric
scorers, final fit, model-offset determination, metadata assembly, artifact
save, and the content-addressed build cache over the disk registry.

Engine difference: ``model.fit`` dispatches into the fused JAX training
program; the builder itself stays host-side orchestration.
"""

import datetime
import hashlib
import json
import logging
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd
from sklearn import metrics
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.model_selection import cross_validate
from sklearn.pipeline import Pipeline

import gordo_tpu
from .. import serializer
from ..dataset import GordoBaseDataset
from ..machine import Machine
from ..machine.metadata import (
    BuildMetadata,
    CrossValidationMetaData,
    DatasetBuildMetadata,
    DriftBaselineMetadata,
    ModelBuildMetadata,
    TrainingSummaryMetadata,
)
from ..models.base import GordoBase
from ..models.utils import metric_wrapper
from ..utils import disk_registry

logger = logging.getLogger(__name__)


class ModelBuilder:
    def __init__(self, machine: Machine):
        self.machine = machine
        self._cached_model_path: Optional[str] = None

    @property
    def cache_key(self) -> str:
        return self.calculate_cache_key(self.machine)

    @property
    def cached_model_path(self) -> Optional[str]:
        return self._cached_model_path

    def build(
        self,
        output_dir: Optional[Union[os.PathLike, str]] = None,
        model_register_dir: Optional[Union[os.PathLike, str]] = None,
        replace_cache: bool = False,
    ) -> Tuple[Union[BaseEstimator, Pipeline], Machine]:
        """
        Build the model; when a register dir is given, probe the
        content-addressed cache first and short-circuit on a hit
        (reference: build_model.py:104-190).
        """
        if not model_register_dir:
            model, machine = self._build()
        else:
            logger.debug(
                "Model register dir %s; cache key %s",
                model_register_dir,
                self.cache_key,
            )
            cached = self.load_cached(model_register_dir, replace_cache=replace_cache)
            if cached is not None:
                model, machine = cached
            else:
                model, machine = self._build()
                self.register(model, machine, model_register_dir)
        if output_dir:
            self._save_model(model, machine, output_dir)
        return model, machine

    def load_cached(
        self,
        model_register_dir: Union[os.PathLike, str],
        replace_cache: bool = False,
    ) -> Optional[Tuple[Union[BaseEstimator, Pipeline], Machine]]:
        """
        Probe the content-addressed cache; on a hit return the loaded model
        and its machine with the retrieval date stamped into user metadata
        (reference: build_model.py:135-183).
        """
        if replace_cache:
            self.delete_cached_model(model_register_dir)
        cached_model_path = self.check_cache(model_register_dir)
        if not cached_model_path:
            return None
        model = serializer.load(cached_model_path)
        metadata = serializer.load_metadata(cached_model_path)
        metadata["metadata"]["user_defined"]["date_of_retrieval"] = str(
            datetime.datetime.now(datetime.timezone.utc)
        )
        self._cached_model_path = cached_model_path
        return model, Machine.from_dict(metadata)

    def register(
        self,
        model: Union[BaseEstimator, Pipeline],
        machine: Machine,
        model_register_dir: Union[os.PathLike, str],
    ) -> str:
        """Save artifacts under ``builds/<cache_key>`` and record the path
        in the disk registry for future cache hits."""
        self._cached_model_path = self._save_model(
            model,
            machine,
            os.path.join(str(model_register_dir), "builds", self.cache_key),
        )
        disk_registry.write_key(
            model_register_dir, self.cache_key, self._cached_model_path
        )
        return self._cached_model_path

    def _build(self) -> Tuple[Union[BaseEstimator, Pipeline], Machine]:
        """Train: fetch data → build model → CV → fit → metadata."""
        self.set_seed(seed=1337)

        machine = self.machine.copy()

        # Fetch data (the IO hot spot; duration recorded as
        # query_duration_sec — reference build_model.py:208-215)
        logger.info("Fetching data for machine %s", machine.name)
        start = time.time()
        dataset = (
            machine.dataset
            if isinstance(machine.dataset, GordoBaseDataset)
            else GordoBaseDataset.from_dict(machine.dataset)
        )
        X, y = dataset.get_data()
        time_elapsed_data = time.time() - start

        model = serializer.from_definition(machine.model)

        cv_duration_sec: Optional[float] = None
        scores: Dict[str, Any] = {}
        split_metadata: Dict[str, Any] = {}

        cv_mode = machine.evaluation.get("cv_mode", "full_build").lower()
        if cv_mode in ("cross_val_only", "full_build"):
            metrics_list = self.metrics_from_list(machine.evaluation.get("metrics"))
            if hasattr(model, "predict"):
                logger.debug("Starting cross validation")
                start = time.time()
                scaler = machine.evaluation.get("scoring_scaler")
                metrics_dict = self.build_metrics_dict(metrics_list, y, scaler=scaler)

                split_obj = serializer.from_definition(
                    machine.evaluation.get(
                        "cv",
                        {"sklearn.model_selection.TimeSeriesSplit": {"n_splits": 3}},
                    )
                )
                split_metadata = self.build_split_dict(X, split_obj)

                cv_kwargs = dict(
                    X=X, y=y, scoring=metrics_dict, return_estimator=True, cv=split_obj
                )
                if hasattr(model, "cross_validate"):
                    cv = model.cross_validate(**cv_kwargs)
                else:
                    cv = cross_validate(model, **cv_kwargs)

                for metric_name in metrics_dict:
                    fold_values = cv[f"test_{metric_name}"]
                    val = {
                        "fold-mean": fold_values.mean(),
                        "fold-std": fold_values.std(),
                        "fold-max": fold_values.max(),
                        "fold-min": fold_values.min(),
                    }
                    val.update(
                        {
                            f"fold-{i + 1}": raw
                            for i, raw in enumerate(fold_values.tolist())
                        }
                    )
                    scores[metric_name] = val
                cv_duration_sec = time.time() - start
            else:
                logger.debug("Model has no predict; skipping scoring")

            if cv_mode == "cross_val_only":
                machine.metadata.build_metadata = BuildMetadata(
                    model=ModelBuildMetadata(
                        cross_validation=CrossValidationMetaData(
                            cv_duration_sec=cv_duration_sec,
                            scores=scores,
                            splits=split_metadata,
                        )
                    ),
                    dataset=DatasetBuildMetadata(
                        query_duration_sec=time_elapsed_data,
                        dataset_meta=dataset.get_metadata(),
                    ),
                )
                return model, machine

        logger.debug("Starting to train model")
        start = time.time()
        model.fit(X, y)
        time_elapsed_model = time.time() - start

        machine.metadata.build_metadata = BuildMetadata(
            model=ModelBuildMetadata(
                model_offset=self._determine_offset(model, X),
                model_creation_date=str(
                    datetime.datetime.now(datetime.timezone.utc).astimezone()
                ),
                model_builder_version=gordo_tpu.__version__,
                model_training_duration_sec=time_elapsed_model,
                cross_validation=CrossValidationMetaData(
                    cv_duration_sec=cv_duration_sec,
                    scores=scores,
                    splits=split_metadata,
                ),
                model_meta=self._extract_metadata_from_model(model),
                training=self._extract_training_summary(model),
            ),
            dataset=DatasetBuildMetadata(
                query_duration_sec=time_elapsed_data,
                dataset_meta=dataset.get_metadata(),
            ),
            drift_baseline=self._drift_baseline(X),
        )
        return model, machine

    @staticmethod
    def _drift_baseline(X) -> DriftBaselineMetadata:
        """The lifecycle drift monitor's training baseline (raw-input
        feature stats); a frame it cannot summarize — exotic dtypes from
        a custom provider — degrades to an empty baseline (the monitor
        then self-calibrates) rather than failing the build."""
        try:
            return DriftBaselineMetadata.from_frame(X)
        except Exception as exc:  # noqa: BLE001 - baseline is advisory
            logger.debug("No drift baseline for this frame: %r", exc)
            return DriftBaselineMetadata()

    @staticmethod
    def _extract_training_summary(model) -> TrainingSummaryMetadata:
        """Training-history summary (final/best loss, epochs, early
        stop) dug out of the fitted estimator's ``History`` carry, so
        sequential builds record the same ``training`` block as fleet
        builds (machines degraded out of the fleet path included)."""

        def find_history(obj, depth=0):
            if obj is None or depth > 4:
                return None
            if isinstance(obj, Pipeline):
                return find_history(obj.steps[-1][1], depth + 1)
            history = getattr(obj, "_history", None)
            if history is not None and hasattr(history, "history"):
                return history
            base = getattr(obj, "base_estimator", None)
            if base is not None and base is not obj:
                return find_history(base, depth + 1)
            return None

        history = find_history(model)
        if history is None:
            return TrainingSummaryMetadata()
        try:
            return TrainingSummaryMetadata.from_history(history)
        except (TypeError, ValueError, AttributeError):
            return TrainingSummaryMetadata()

    @staticmethod
    def set_seed(seed: int):
        # JAX RNG is explicit (threaded through fit as PRNG keys); numpy /
        # stdlib seeds cover sklearn shuffles and any host-side sampling.
        random.seed(seed)
        np.random.seed(seed)

    @staticmethod
    def build_split_dict(X: pd.DataFrame, split_obj) -> dict:
        """Record train/test index boundaries per CV fold."""
        split_metadata: Dict[str, Any] = {}
        for i, (train, test) in enumerate(split_obj.split(X)):
            split_metadata.update(
                {
                    f"fold-{i + 1}-train-start": _index_at(X, train[0]),
                    f"fold-{i + 1}-train-end": _index_at(X, train[-1]),
                    f"fold-{i + 1}-test-start": _index_at(X, test[0]),
                    f"fold-{i + 1}-test-end": _index_at(X, test[-1]),
                }
            )
        return split_metadata

    @staticmethod
    def metrics_from_list(metric_names: Optional[List[str]] = None) -> List[Callable]:
        """
        Resolve metric names (e.g. ``explained_variance_score``,
        ``sklearn.metrics.r2_score``) to callables; defaults to the
        reference's four (normalized_config.py:95-107).
        """
        default = [
            metrics.explained_variance_score,
            metrics.r2_score,
            metrics.mean_squared_error,
            metrics.mean_absolute_error,
        ]
        if not metric_names:
            return default
        resolved = []
        for name in metric_names:
            if callable(name):
                resolved.append(name)
            elif "." in name:
                from ..serializer.import_utils import import_location

                resolved.append(import_location(name))
            else:
                resolved.append(getattr(metrics, name))
        return resolved

    @staticmethod
    def build_metrics_dict(
        metrics_list: list,
        y: pd.DataFrame,
        scaler: Optional[Union[TransformerMixin, str, dict]] = None,
    ) -> dict:
        """
        Scorers keyed ``{score}-{tag}`` per target tag plus ``{score}`` for
        the all-tag aggregate; metric names are dashed, tags have spaces
        dashed (reference: build_model.py:377-446).
        """
        if scaler:
            if isinstance(scaler, (str, dict)):
                scaler = serializer.from_definition(scaler)
            scaler.fit(y)

        def _score_factory(metric_func, col_index):
            def _score_per_tag(y_true, y_pred):
                y_true = getattr(y_true, "values", y_true)
                y_pred = getattr(y_pred, "values", y_pred)
                return metric_func(y_true[:, col_index], y_pred[:, col_index])

            return _score_per_tag

        metrics_dict = {}
        for metric in metrics_list:
            metric_str = metric.__name__.replace("_", "-")
            for index, col in enumerate(y.columns):
                scorer_key = f"{metric_str}-{str(col).replace(' ', '-')}"
                metrics_dict[scorer_key] = metrics.make_scorer(
                    metric_wrapper(
                        _score_factory(metric_func=metric, col_index=index),
                        scaler=scaler,
                    )
                )
            metrics_dict[metric_str] = metrics.make_scorer(
                metric_wrapper(metric, scaler=scaler)
            )
        return metrics_dict

    @staticmethod
    def _determine_offset(model: BaseEstimator, X: Union[np.ndarray, pd.DataFrame]) -> int:
        """len(X) - len(model output): the LSTM lookback offset."""
        X = getattr(X, "values", X)
        out = model.predict(X) if hasattr(model, "predict") else model.transform(X)
        return len(X) - len(out)

    @staticmethod
    def _extract_metadata_from_model(
        model: BaseEstimator, metadata: Optional[dict] = None
    ) -> dict:
        """
        Recursively dig ``GordoBase.get_metadata()`` out of nested
        pipelines/estimators (reference: build_model.py:515-569).
        """
        metadata = metadata if metadata is not None else {}
        if isinstance(model, Pipeline):
            final = model.steps[-1][1]
            return ModelBuilder._extract_metadata_from_model(final, metadata)
        if isinstance(model, GordoBase):
            metadata.update(model.get_metadata())
            base = getattr(model, "base_estimator", None)
            if isinstance(base, BaseEstimator) and base is not model:
                ModelBuilder._extract_metadata_from_model(base, metadata)
            return metadata
        for attr_name in ("base_estimator", "estimator"):
            nested = getattr(model, attr_name, None)
            if isinstance(nested, BaseEstimator):
                ModelBuilder._extract_metadata_from_model(nested, metadata)
        return metadata

    @staticmethod
    def calculate_cache_key(machine: Machine) -> str:
        """
        Content hash over (name, model config, dataset config, evaluation
        config, framework major.minor — full version for unstable builds);
        reference: build_model.py:575-631.
        """
        dataset = machine.dataset
        dataset_config = (
            dataset.to_dict() if hasattr(dataset, "to_dict") else dataset
        )
        if gordo_tpu.version_is_stable():
            version = f"{gordo_tpu.MAJOR_VERSION}.{gordo_tpu.MINOR_VERSION}"
        else:
            version = gordo_tpu.__version__
        payload = json.dumps(
            {
                "name": machine.name,
                "model_config": machine.model,
                "data_config": dataset_config,
                "evaluation_config": machine.evaluation,
                "gordo-major-version": gordo_tpu.MAJOR_VERSION,
                "gordo-minor-version": gordo_tpu.MINOR_VERSION,
                "version": version,
            },
            sort_keys=True,
            default=str,
        )
        return hashlib.sha3_256(payload.encode()).hexdigest()

    @staticmethod
    def _cache_entry_valid(path: str) -> bool:
        """The ONE definition of a loadable cache entry — shared by the
        coordinator's check_cache and the read-only probe_cache mirror so
        multi-host processes can never disagree on cache hits."""
        return os.path.isdir(path) and os.path.isfile(
            os.path.join(path, "model.pkl")
        )

    @classmethod
    def probe_cache(
        cls, machine: Machine, model_register_dir: Union[os.PathLike, str]
    ) -> Optional[str]:
        """Read-only cache probe: like :meth:`check_cache` but with NO
        stale-key cleanup, so non-coordinator SPMD processes can mirror
        the coordinator's cache-hit machine filter without writing to the
        shared registry."""
        path = disk_registry.get_value(
            model_register_dir, cls.calculate_cache_key(machine)
        )
        if path is None or not cls._cache_entry_valid(path):
            return None
        return path

    def check_cache(self, model_register_dir: Union[os.PathLike, str]) -> Optional[str]:
        """Return the cached model path for this machine, if valid."""
        path = disk_registry.get_value(model_register_dir, self.cache_key)
        if path is None:
            return None
        if not self._cache_entry_valid(path):
            logger.warning("Registry key %s points at missing dir %s", self.cache_key, path)
            disk_registry.delete_value(model_register_dir, self.cache_key)
            return None
        return path

    def delete_cached_model(self, model_register_dir: Union[os.PathLike, str]):
        disk_registry.delete_value(model_register_dir, self.cache_key)

    @staticmethod
    def _save_model(
        model: BaseEstimator,
        machine: Union[Machine, dict],
        output_dir: Union[os.PathLike, str],
    ) -> str:
        output_dir = str(output_dir)
        metadata = machine.to_dict() if isinstance(machine, Machine) else machine
        # Atomic (staging dir + rename): a crash mid-save can never leave
        # a half-written model.pkl where the registry or a resume pass
        # would find it — same contract as the fleet builder's dumps.
        serializer.dump_atomic(model, output_dir, metadata=metadata)
        return output_dir


def _index_at(X, position: int):
    index = getattr(X, "index", None)
    if index is None:
        return int(position)
    value = index[position]
    return value.isoformat() if hasattr(value, "isoformat") else value
