"""Builder-class plugin point (reference: gordo/builder/utils.py:8-17)."""

from typing import Optional, Type

from ..serializer.import_utils import import_location
from .build_model import ModelBuilder


def create_model_builder(model_builder_class: Optional[str]) -> Type[ModelBuilder]:
    """Resolve ``--model-builder-class``; must subclass ModelBuilder."""
    if not model_builder_class:
        return ModelBuilder
    BuilderClass = import_location(model_builder_class)
    if not (isinstance(BuilderClass, type) and issubclass(BuilderClass, ModelBuilder)):
        raise ValueError(
            f"{model_builder_class} is not a subclass of "
            "gordo_tpu.builder.build_model.ModelBuilder"
        )
    return BuilderClass
