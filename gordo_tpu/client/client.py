"""
HTTP client for a deployed gordo-tpu project (reference: the external
``gordo-client`` package, pinned by gordo's full_requirements.txt:139 and
exercised by tests/gordo/client/test_client.py — SURVEY.md §2 intro).

For each target machine the client pulls the machine's own dataset config
from served metadata, fetches sensor data for the prediction window via
that dataset (optionally with an overridden data provider), POSTs it to
the anomaly-prediction route in row batches (JSON or parquet multipart),
joins the returned response frames, and optionally forwards them into a
:class:`~gordo_tpu.client.forwarders.PredictionForwarder` — the Argo
"client" replay step's behavior.
"""

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Union

import pandas as pd
import requests

from .. import serializer
from ..dataset import GordoBaseDataset
from ..machine import Machine
from ..server.utils import (
    dataframe_from_dict,
    dataframe_from_parquet_bytes,
    dataframe_into_parquet_bytes,
    dataframe_to_dict,
)
from .forwarders import PredictionForwarder
from .io import NotFound, _handle_response
from .utils import PredictionResult

logger = logging.getLogger(__name__)


class Client:
    """
    Client to a single gordo-tpu project deployment.

    Parameters
    ----------
    project
        Project name (the ``/gordo/v0/<project>`` path element).
    host / port / scheme
        Where the ML server lives.
    revision
        Pin all requests to a specific model revision (default: server's
        current).
    data_provider
        Override the data provider inside each machine's dataset config
        when fetching prediction-window data.
    prediction_forwarder
        Sink called with each machine's joined predictions.
    batch_size
        Max rows per prediction POST.
    parallelism
        Machines predicted concurrently (thread pool; requests release
        the GIL during IO).
    use_parquet
        Send/receive parquet instead of JSON payloads.
    use_arrow
        Send/receive columnar Arrow-IPC bodies (the server's wire fast
        path — zero JSON parse on either side). Takes precedence over
        ``use_parquet``; requires pyarrow on both ends.
    session
        A ``requests.Session``-compatible object (tests inject an
        in-process WSGI adapter here).
    """

    def __init__(
        self,
        project: str,
        host: str = "localhost",
        port: int = 443,
        scheme: str = "https",
        revision: Optional[str] = None,
        metadata: Optional[dict] = None,
        data_provider: Optional[dict] = None,
        prediction_forwarder: Optional[PredictionForwarder] = None,
        batch_size: int = 100000,
        parallelism: int = 10,
        n_retries: int = 5,
        use_parquet: bool = False,
        use_arrow: bool = False,
        session=None,
    ):
        self.project_name = project
        self.base_url = f"{scheme}://{host}:{port}/gordo/v0/{project}"
        self.revision = revision
        self.metadata = metadata if metadata is not None else {}
        self.data_provider = data_provider
        self.prediction_forwarder = prediction_forwarder
        self.batch_size = batch_size
        self.parallelism = parallelism
        self.n_retries = n_retries
        self.use_parquet = use_parquet
        self.use_arrow = use_arrow
        self.session = session if session is not None else requests.Session()

    # -- discovery -----------------------------------------------------------

    def _query_params(self) -> dict:
        return {"revision": self.revision} if self.revision else {}

    def get_revisions(self) -> dict:
        """``{"latest": ..., "available-revisions": [...]}`` from the server."""
        resp = self.session.get(
            f"{self.base_url}/revisions", params=self._query_params()
        )
        return _handle_response(resp, "revisions")

    def get_machine_names(self) -> List[str]:
        """Model names available from the (pinned or current) revision."""
        resp = self.session.get(f"{self.base_url}/models", params=self._query_params())
        return _handle_response(resp, "model list")["models"]

    def machine_metadata(self, name: str) -> dict:
        """Full served metadata for one machine."""
        resp = self.session.get(
            f"{self.base_url}/{name}/metadata", params=self._query_params()
        )
        return _handle_response(resp, f"metadata for {name}")

    def get_metadata(
        self, targets: Optional[List[str]] = None
    ) -> Dict[str, dict]:
        """``{machine-name: machine metadata dict}`` for all (or listed)
        machines."""
        return {
            machine.name: machine.to_dict()
            for machine in self.get_available_machines(targets)
        }

    def get_available_machines(
        self, targets: Optional[List[str]] = None
    ) -> List[Machine]:
        """Rehydrated :class:`Machine` objects from served metadata."""
        names = self.get_machine_names()
        if targets:
            missing = set(targets) - set(names)
            if missing:
                raise NotFound(f"Machines not deployed: {sorted(missing)}")
            names = [n for n in names if n in set(targets)]
        return [
            Machine.from_dict(self.machine_metadata(name)["metadata"])
            for name in names
        ]

    def download_model(
        self, targets: Optional[List[str]] = None
    ) -> Dict[str, object]:
        """``{machine-name: deserialized model}`` via ``/download-model``
        (the pickle wire format of serializer.dumps/loads)."""
        names = targets if targets else self.get_machine_names()
        models = {}
        for name in names:
            resp = self.session.get(
                f"{self.base_url}/{name}/download-model", params=self._query_params()
            )
            models[name] = serializer.loads(_handle_response(resp, f"model {name}"))
        return models

    # -- prediction ----------------------------------------------------------

    def predict(
        self,
        start: Union[str, pd.Timestamp],
        end: Union[str, pd.Timestamp],
        targets: Optional[List[str]] = None,
    ) -> List[PredictionResult]:
        """
        Replay the ``[start, end]`` window through every (or the listed)
        machines, in parallel, returning one :class:`PredictionResult`
        per machine.
        """
        machines = self.get_available_machines(targets)
        with ThreadPoolExecutor(max_workers=max(1, self.parallelism)) as executor:
            results = list(
                executor.map(
                    lambda m: self.predict_single_machine(m, start, end), machines
                )
            )
        if self.prediction_forwarder is not None:
            for machine, result in zip(machines, results):
                if result.predictions is not None and len(result.predictions):
                    self.prediction_forwarder.forward_predictions(
                        result.predictions, machine=machine, metadata=self.metadata
                    )
        return results

    def fleet_anomaly_scores(
        self,
        start: Union[str, pd.Timestamp],
        end: Union[str, pd.Timestamp],
        targets: Optional[List[str]] = None,
        full: bool = False,
    ) -> Dict[str, "PredictionResult"]:
        """
        Score many machines with ONE request via the server's batch
        ``prediction/fleet`` route: the server runs every same-architecture
        machine as a single fused device program (Pallas on TPU), instead
        of this client fanning one anomaly POST per machine. The lean wire
        format carries each machine's ``model-output`` columns plus the
        ``total-anomaly-unscaled`` per-row mse; ``full=True`` requests the
        complete anomaly frame per detector machine (tag/total anomalies,
        confidence — the series set the reference's replay client writes
        to Influx), still scored through the fused bucket.
        """
        machines = self.get_available_machines(targets)
        results: Dict[str, PredictionResult] = {}

        def fetch(machine):
            try:
                X, _ = self._data_for_window(machine, start, end)
                return machine.name, X, None
            except Exception as exc:  # noqa: BLE001 - per-machine isolation
                msg = f"Failed to fetch data for {machine.name}: {exc}"
                logger.error(msg)
                return machine.name, None, msg

        inputs: Dict[str, pd.DataFrame] = {}
        with ThreadPoolExecutor(max_workers=max(1, self.parallelism)) as executor:
            for name, X, error in executor.map(fetch, machines):
                if error is not None:
                    results[name] = PredictionResult(
                        name=name, predictions=None, error_messages=[error]
                    )
                else:
                    inputs[name] = X

        if inputs:
            # Chunk by rows like predict_single_machine does: one giant
            # body for a long window would blow past proxy limits where
            # the chunked per-machine path succeeds. Frames are sliced
            # with .iloc per chunk and serialized to the wire format
            # (dataframe_to_dict) only for the rows being sent; a machine
            # that failed server-side drops out of later chunks; a chunk
            # whose POST exhausts retries records a per-machine error and
            # the already-scored chunks survive.
            frames_by_name: Dict[str, List[pd.DataFrame]] = {}
            errors_by_name: Dict[str, List[str]] = {}
            max_rows = max(len(X) for X in inputs.values())
            for chunk_start in range(0, max_rows, self.batch_size):
                chunk_payload = {
                    name: dataframe_to_dict(
                        X.iloc[chunk_start : chunk_start + self.batch_size]
                    )
                    for name, X in inputs.items()
                    if name not in errors_by_name and len(X) > chunk_start
                }
                if not chunk_payload:
                    continue
                try:
                    body = self._post_fleet_request(chunk_payload, full=full)
                except Exception as exc:  # noqa: BLE001 - keep partials
                    msg = (
                        f"Fleet request for rows {chunk_start}-"
                        f"{chunk_start + self.batch_size} failed: {exc}"
                    )
                    logger.error(msg)
                    for name in chunk_payload:
                        errors_by_name.setdefault(name, []).append(msg)
                    continue
                for name, entry in body.get("data", {}).items():
                    # Lean vs full is decided by what the client ASKED for
                    # plus the entry's column groups — never by sniffing
                    # value nesting, which misreads a zero-row full frame
                    # (empty series) as lean. Even under full=True the
                    # server answers the lean shape for non-detector
                    # machines, and those entries carry exactly the two
                    # lean keys while a detector's anomaly frame always
                    # includes further groups (total-anomaly-scaled,
                    # anomaly-confidence, ...).
                    lean = not full or set(entry) <= {
                        "model-output",
                        "total-anomaly-unscaled",
                    }
                    if lean:
                        # lean entry: flat {ts: mse} + model-output columns
                        frame = dataframe_from_dict(entry["model-output"])
                        frame["total-anomaly-unscaled"] = dataframe_from_dict(
                            {"mse": entry["total-anomaly-unscaled"]}
                        )["mse"]
                    else:
                        # full anomaly frame (two-level column groups) —
                        # same wire shape as the single anomaly route
                        frame = dataframe_from_dict(entry)
                    frames_by_name.setdefault(name, []).append(frame)
                for name, error in (body.get("errors") or {}).items():
                    errors_by_name.setdefault(name, []).append(
                        str(error.get("error"))
                    )
            for name in inputs:
                frames = frames_by_name.get(name)
                results[name] = PredictionResult(
                    name=name,
                    predictions=(
                        pd.concat(frames).sort_index() if frames else None
                    ),
                    error_messages=errors_by_name.get(name, []),
                )
        if self.prediction_forwarder is not None:
            # same forwarding contract as predict(): one call per machine
            # with scored rows (the replay Job's Influx/parquet sink)
            for machine in machines:
                result = results.get(machine.name)
                if (
                    result is not None
                    and result.predictions is not None
                    and len(result.predictions)
                ):
                    self.prediction_forwarder.forward_predictions(
                        result.predictions,
                        machine=machine,
                        metadata=self.metadata,
                    )
        return results

    def _post_fleet_request(
        self, payload: Dict[str, dict], full: bool = False
    ) -> dict:
        """POST the batch body with the same transient-retry policy as the
        per-machine path; a 400 whose body carries the per-machine errors
        dict is a VALID outcome (every machine failed server-side), not an
        exception — the per-machine contract holds either way."""
        url = f"{self.base_url}/prediction/fleet"
        request_body: Dict[str, object] = {"X": payload}
        if full:
            request_body["full"] = True
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, self.n_retries)):
            try:
                resp = self.session.post(
                    url, json=request_body, params=self._query_params()
                )
                if resp.status_code == 400:
                    try:
                        body = resp.json()
                    except ValueError:
                        # non-JSON 400 (a proxy error page): not the
                        # server's errors contract — let _handle_response
                        # raise the typed, non-retryable exception
                        body = None
                    if isinstance(body, dict) and body.get("errors"):
                        return body
                return _handle_response(resp, "fleet prediction")
            except IOError as exc:  # 5xx / transport: retry
                last_exc = exc
                logger.warning(
                    "Fleet prediction attempt %d/%d failed: %s",
                    attempt + 1,
                    self.n_retries,
                    exc,
                )
        raise last_exc

    def predict_single_machine(
        self,
        machine: Machine,
        start: Union[str, pd.Timestamp],
        end: Union[str, pd.Timestamp],
    ) -> PredictionResult:
        """Fetch the machine's sensor data for the window and POST it in
        batches; join the per-batch response frames. Any failure — data
        fetch included — lands in ``error_messages`` rather than aborting
        the rest of the fleet's replay."""
        frames: List[pd.DataFrame] = []
        errors: List[str] = []
        try:
            X, y = self._data_for_window(machine, start, end)
        except Exception as exc:
            msg = f"Failed to fetch data for {machine.name}: {exc}"
            logger.error(msg)
            return PredictionResult(
                name=machine.name, predictions=None, error_messages=[msg]
            )
        for batch_start in range(0, len(X), self.batch_size):
            X_batch = X.iloc[batch_start : batch_start + self.batch_size]
            y_batch = (
                y.iloc[batch_start : batch_start + self.batch_size]
                if y is not None
                else None
            )
            try:
                frames.append(
                    self._send_prediction_request(machine.name, X_batch, y_batch)
                )
            except Exception as exc:
                msg = (
                    f"Failed prediction rows {batch_start}-"
                    f"{batch_start + len(X_batch)} for {machine.name}: {exc}"
                )
                logger.error(msg)
                errors.append(msg)
        predictions = pd.concat(frames).sort_index() if frames else None
        return PredictionResult(
            name=machine.name, predictions=predictions, error_messages=errors
        )

    def _data_for_window(self, machine: Machine, start, end):
        """The machine's own dataset config, re-pointed at the prediction
        window (and optionally at an overridden data provider)."""
        dataset_config = dict(
            machine.dataset.to_dict()
            if isinstance(machine.dataset, GordoBaseDataset)
            else machine.dataset
        )
        dataset_config["train_start_date"] = pd.Timestamp(start)
        dataset_config["train_end_date"] = pd.Timestamp(end)
        if self.data_provider is not None:
            dataset_config["data_provider"] = self.data_provider
        return GordoBaseDataset.from_dict(dataset_config).get_data()

    def _send_prediction_request(
        self,
        machine_name: str,
        X: pd.DataFrame,
        y: Optional[pd.DataFrame],
    ) -> pd.DataFrame:
        url = f"{self.base_url}/{machine_name}/anomaly/prediction"
        params = self._query_params()
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, self.n_retries)):
            try:
                if self.use_arrow:
                    # columnar wire: one IPC stream with role-tagged
                    # X/y columns out, a record batch back
                    from .utils import (
                        ARROW_CONTENT_TYPE,
                        dataframe_into_arrow_bytes,
                    )

                    resp = self.session.post(
                        url,
                        params=params,
                        data=dataframe_into_arrow_bytes(X, y),
                        headers={
                            "Content-Type": ARROW_CONTENT_TYPE,
                            "Accept": ARROW_CONTENT_TYPE,
                        },
                    )
                elif self.use_parquet:
                    params = {**params, "format": "parquet"}
                    files = {"X": dataframe_into_parquet_bytes(X)}
                    if y is not None:
                        files["y"] = dataframe_into_parquet_bytes(y)
                    resp = self.session.post(url, params=params, files=files)
                else:
                    body = {"X": dataframe_to_dict(X)}
                    if y is not None:
                        body["y"] = dataframe_to_dict(y)
                    resp = self.session.post(url, params=params, json=body)
                payload = _handle_response(resp, f"prediction for {machine_name}")
                break
            except IOError as exc:  # 5xx / transport: retry
                last_exc = exc
                logger.warning(
                    "Prediction attempt %d/%d for %s failed: %s",
                    attempt + 1,
                    self.n_retries,
                    machine_name,
                    exc,
                )
        else:
            raise last_exc
        if isinstance(payload, bytes):
            if self.use_arrow:
                from .utils import dataframe_from_arrow_bytes

                return dataframe_from_arrow_bytes(payload)
            return dataframe_from_parquet_bytes(payload)
        return dataframe_from_dict(payload["data"])
