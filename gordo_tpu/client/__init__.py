from .client import Client
from .forwarders import (
    ForwardPredictionsIntoInflux,
    ForwardPredictionsToDisk,
    PredictionForwarder,
)
from .utils import PredictionResult

__all__ = [
    "Client",
    "PredictionResult",
    "PredictionForwarder",
    "ForwardPredictionsToDisk",
    "ForwardPredictionsIntoInflux",
]
