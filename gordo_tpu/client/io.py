"""
HTTP response handling for the client (reference: gordo-client ``io``
module): map the server's failure statuses onto typed exceptions so
callers can distinguish "your input is bad" (422), "bad request" (4xx),
"no such model" (404) and "revision deleted" (410).
"""

from typing import Union


class HttpUnprocessableEntity(Exception):
    """HTTP 422: the server understood the request but refused the input
    (e.g. anomaly prediction against a non-anomaly model)."""


class BadGordoRequest(Exception):
    """Any other 4xx client-side error."""


class NotFound(Exception):
    """HTTP 404: no such project/model/revision."""


class ResourceGone(Exception):
    """HTTP 410: the requested revision is gone (deleted from disk)."""


def _handle_response(resp, resource_name: str = None) -> Union[dict, bytes]:
    """
    Decode a successful response (JSON dict or raw bytes), or raise the
    typed exception for the status code.
    """
    if 200 <= resp.status_code <= 299:
        is_json = "application/json" in resp.headers.get("content-type", "")
        return resp.json() if is_json else resp.content
    context = f" ({resource_name})" if resource_name else ""
    content = getattr(resp, "text", "")[:150]
    msg = f"HTTP {resp.status_code}{context}: {content}"
    if resp.status_code == 422:
        raise HttpUnprocessableEntity(msg)
    if resp.status_code == 410:
        raise ResourceGone(msg)
    if resp.status_code == 404:
        raise NotFound(msg)
    if 400 <= resp.status_code <= 499:
        raise BadGordoRequest(msg)
    raise IOError(msg)
