"""
Client-side helper types (reference: gordo-client ``utils`` module —
``PredictionResult`` carrying one machine's joined predictions plus any
per-batch error messages) and the columnar-wire decode helpers: thin
client-facing wrappers over the server's shared codec
(``gordo_tpu.server.wire`` — the one place the Arrow schema conventions
live, so client and server can never drift).
"""

from collections import namedtuple
from typing import Optional, Tuple

import pandas as pd

from ..server.wire.arrow_codec import ARROW_CONTENT_TYPE  # noqa: F401

PredictionResult = namedtuple("PredictionResult", "name predictions error_messages")


def dataframe_into_arrow_bytes(
    X: pd.DataFrame, y: Optional[pd.DataFrame] = None
) -> bytes:
    """``X`` (and optionally ``y``) as one role-tagged Arrow IPC stream —
    the columnar request body the server's wire fast path decodes
    zero-copy."""
    from ..server.wire.arrow_codec import encode_request

    return encode_request(X, y)


def dataframe_from_arrow_bytes(buf: bytes) -> pd.DataFrame:
    """An Arrow response body as the same MultiIndex-column frame
    ``dataframe_from_dict(response["data"])`` yields for JSON clients
    (envelope metadata — revision, time-seconds — is dropped; use
    :func:`arrow_response_with_meta` to keep it)."""
    frame, _ = arrow_response_with_meta(buf)
    return frame


def arrow_response_with_meta(buf: bytes) -> Tuple[pd.DataFrame, dict]:
    """An Arrow response body as ``(frame, envelope)`` where
    ``envelope`` carries the scalar response fields (``revision``,
    ``time-seconds``)."""
    from ..server.wire.arrow_codec import decode_response

    return decode_response(buf)
