"""
Client-side helper types (reference: gordo-client ``utils`` module —
``PredictionResult`` carrying one machine's joined predictions plus any
per-batch error messages).
"""

from collections import namedtuple

PredictionResult = namedtuple("PredictionResult", "name predictions error_messages")
