"""
Prediction forwarders: sinks the client pushes joined prediction frames
into after each machine's replay (reference: gordo-client ``forwarders``
— ``ForwardPredictionsIntoInflux`` used by the Argo client step,
argo-workflow.yml.template:1374-1376).

The influx forwarder needs the ``influxdb`` package (not baked into this
environment) and is import-gated; :class:`ForwardPredictionsToDisk`
provides the dependency-free local sink (parquet per machine) used by
tests and air-gapped runs.
"""

import abc
import logging
import os
from typing import Optional

import pandas as pd

logger = logging.getLogger(__name__)


class PredictionForwarder(abc.ABC):
    """One call per machine with the joined prediction frame."""

    @abc.abstractmethod
    def forward_predictions(
        self,
        predictions: pd.DataFrame,
        machine=None,
        metadata: Optional[dict] = None,
    ) -> None:
        ...


def flatten_columns(predictions: pd.DataFrame) -> pd.DataFrame:
    """MultiIndex response columns as flat pipe-joined names — THE sink
    column format (disk/Influx forwarders and the `score` CLI all write
    it; one definition so backfills always match the live sink schema).
    Frames with flat columns pass through as a copy."""
    frame = predictions.copy()
    if isinstance(frame.columns, pd.MultiIndex):
        frame.columns = ["|".join(map(str, c)).rstrip("|") for c in frame.columns]
    return frame


#: retained pre-r4 private name
_flatten_columns = flatten_columns


class ForwardPredictionsToDisk(PredictionForwarder):
    """Append predictions as ``<destination>/<machine-name>.parquet``."""

    def __init__(self, destination: str):
        self.destination = destination
        os.makedirs(destination, exist_ok=True)

    def forward_predictions(
        self,
        predictions: pd.DataFrame,
        machine=None,
        metadata: Optional[dict] = None,
    ) -> None:
        name = machine.name if machine is not None else "predictions"
        path = os.path.join(self.destination, f"{name}.parquet")
        frame = _flatten_columns(predictions)
        if os.path.exists(path):
            frame = pd.concat([pd.read_parquet(path), frame])
        frame.to_parquet(path)
        logger.info("Forwarded %d rows for %s to %s", len(predictions), name, path)


class ForwardPredictionsIntoInflux(PredictionForwarder):
    """
    Write prediction columns as InfluxDB measurements (the reference Argo
    "client" step's sink). Requires the ``influxdb`` package.
    """

    def __init__(
        self,
        destination_influx_uri: Optional[str] = None,
        destination_influx_api_key: Optional[str] = None,
        destination_influx_recreate: bool = False,
        n_retries: int = 5,
    ):
        try:
            from influxdb import DataFrameClient  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "The influxdb package is required for ForwardPredictionsIntoInflux; "
                "use ForwardPredictionsToDisk for a dependency-free sink"
            ) from exc
        if not destination_influx_uri:
            raise ValueError(
                "destination_influx_uri is required "
                "(<username>:<password>@<host>:<port>/<db_name>)"
            )
        self.destination_influx_uri = destination_influx_uri
        self.destination_influx_api_key = destination_influx_api_key
        self.destination_influx_recreate = destination_influx_recreate
        self.n_retries = n_retries
        self.client = self._create_client()

    def _create_client(self):  # pragma: no cover - requires influxdb
        from influxdb import DataFrameClient

        # uri format: <username>:<password>@<host>:<port>/<optional-path>/<db_name>
        username, password, host, port, *_, db_name = (
            self.destination_influx_uri.replace("/", ":").replace("@", ":").split(":")
        )
        client = DataFrameClient(
            host=host,
            port=int(port),
            username=username,
            password=password,
            database=db_name,
            headers={"Ocp-Apim-Subscription-Key": self.destination_influx_api_key}
            if self.destination_influx_api_key
            else None,
        )
        if self.destination_influx_recreate:
            client.drop_database(db_name)
            client.create_database(db_name)
        return client

    def forward_predictions(
        self,
        predictions: pd.DataFrame,
        machine=None,
        metadata: Optional[dict] = None,
    ) -> None:  # pragma: no cover - requires influxdb
        name = machine.name if machine is not None else "predictions"
        frame = _flatten_columns(predictions)
        for attempt in range(self.n_retries):
            try:
                self.client.write_points(
                    dataframe=frame, measurement="predictions", tags={"machine": name}
                )
                return
            except Exception:
                if attempt == self.n_retries - 1:
                    raise
                logger.warning("Influx write retry %d for %s", attempt + 1, name)
