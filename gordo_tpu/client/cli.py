"""
``gordo-tpu-client`` CLI (reference: gordo-client's ``gordo_client.cli.client``
entry point used by the Argo client replay step).
"""

import json
import sys

import click

from .client import Client
from .forwarders import ForwardPredictionsToDisk


def _make_client(ctx_params, **extra) -> Client:
    return Client(
        project=ctx_params["project"],
        host=ctx_params["host"],
        port=ctx_params["port"],
        scheme=ctx_params["scheme"],
        revision=ctx_params.get("revision"),
        **extra,
    )


@click.group("client")
@click.option("--project", required=True, help="Project name")
@click.option("--host", default="localhost", envvar="GORDO_CLIENT_HOST")
@click.option("--port", default=443, type=int, envvar="GORDO_CLIENT_PORT")
@click.option("--scheme", default="https", envvar="GORDO_CLIENT_SCHEME")
@click.option("--revision", default=None, help="Pin to a model revision")
@click.pass_context
def client_cli(ctx, project, host, port, scheme, revision):
    """Interact with a deployed gordo-tpu project."""
    ctx.ensure_object(dict)
    ctx.obj.update(
        project=project, host=host, port=port, scheme=scheme, revision=revision
    )


@client_cli.command("metadata")
@click.option("--target", multiple=True, help="Limit to these machines")
@click.option("--output-file", type=click.File("w"), default=None)
@click.pass_context
def metadata(ctx, target, output_file):
    """Fetch metadata for all (or the listed) machines as JSON."""
    client = _make_client(ctx.obj)
    payload = client.get_metadata(list(target) or None)
    stream = output_file if output_file else sys.stdout
    json.dump(payload, stream, indent=2, default=str)


@client_cli.command("download-model")
@click.argument("output-dir", type=click.Path(exists=True, file_okay=False))
@click.option("--target", multiple=True)
@click.pass_context
def download_model(ctx, output_dir, target):
    """Download and save serialized models to OUTPUT_DIR/<name>/."""
    from .. import serializer

    client = _make_client(ctx.obj)
    for name, model in client.download_model(list(target) or None).items():
        out = f"{output_dir}/{name}"
        serializer.dump(model, out)
        click.echo(f"Saved {name} to {out}")


@client_cli.command("predict")
@click.argument("start")
@click.argument("end")
@click.option("--target", multiple=True)
@click.option("--destination", default=None, help="Forward predictions as parquet here")
@click.option("--parquet/--no-parquet", default=True, help="Parquet wire format")
@click.option("--batch-size", default=100000, type=int)
@click.option("--parallelism", default=10, type=int)
@click.option(
    "--fleet/--per-machine",
    default=False,
    help=(
        "Score through the batch prediction/fleet route (one fused device "
        "program per architecture, full anomaly frames) instead of one "
        "anomaly POST per machine"
    ),
)
@click.pass_context
def predict(
    ctx, start, end, target, destination, parquet, batch_size, parallelism, fleet
):
    """Replay [START, END] through deployed machines (the Argo client
    step's job)."""
    forwarder = ForwardPredictionsToDisk(destination) if destination else None
    client = _make_client(
        ctx.obj,
        prediction_forwarder=forwarder,
        use_parquet=parquet,
        batch_size=batch_size,
        parallelism=parallelism,
    )
    if fleet:
        results = list(
            client.fleet_anomaly_scores(
                start, end, list(target) or None, full=True
            ).values()
        )
    else:
        results = client.predict(start, end, list(target) or None)
    failed = False
    for result in results:
        n = len(result.predictions) if result.predictions is not None else 0
        click.echo(f"{result.name}: {n} rows, {len(result.error_messages)} errors")
        for msg in result.error_messages:
            failed = True
            click.echo(f"  {msg}", err=True)
    if failed:
        sys.exit(1)
