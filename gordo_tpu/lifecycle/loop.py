"""
The self-healing fleet supervisor: drift → incremental rebuild → canary
→ gated promotion (or rollback), with serving never interrupted.

One :class:`LifecycleSupervisor` owns one served collection directory
(the "anchor" — what the server's ``MODEL_COLLECTION_DIR`` points at)
and runs cycles over scored data:

1. **observe** — score incoming frames through the serving fleet and
   fold them into the per-machine drift statistics (``drift.py``);
2. **detect** — machines whose drift verdict trips become the *stale
   set*; everything else is left alone;
3. **rebuild** — ONLY the stale members retrain
   (:func:`gordo_tpu.parallel.rebuild_stale`), journaled and resumable,
   replaying the base build's FleetPlan so pad targets — and therefore
   trained parameters — stay stable across crashes and restarts;
4. **canary** — the rebuilt members are assembled into a full canary
   revision (hardlinks for the untouched majority, ``revision.py``) and
   a configurable slice of traffic routes to it
   (``FleetModelStore.set_canary``);
5. **gate** — threshold-parity / error-rate / residual-parity gates
   (``gates.py``) on a probe window scored against BOTH fleets;
6. **promote** — a passing canary hot-swaps into serving
   (``FleetModelStore.swap``): in-flight requests finish against the
   fleet object they resolved, new requests route to the pre-warmed
   canary — nothing drops, nothing 500s;
7. **rollback** — a failing canary loses its traffic slice immediately,
   lands in the quarantine record with every gate failure, and serving
   stays on the last-good revision.

Every phase boundary persists to ``state.json`` (``state.py``) BEFORE
its side effects, and every failure path carries a fault-injection site
(``drift_eval``, ``canary_build``, ``promote_swap``, ``rollback``), so
a crash at any instant is a drill, not an incident: a restarted
supervisor resumes the interrupted phase and converges.
"""

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import telemetry
from ..utils.env import env_float, env_str
from ..utils.faults import fault_point
from .drift import DriftConfig, DriftMonitor, DriftVerdict
from .gates import GateConfig, GateReport, evaluate_canary
from .revision import list_revisions, next_revision, publish_canary
from .state import LIFECYCLE_DIR, LifecycleState

logger = logging.getLogger(__name__)

#: the JSONL the supervisor's spans append to (build_trace-style)
LIFECYCLE_TRACE_FILE = "lifecycle_trace.jsonl"


@dataclass
class LifecycleConfig:
    """Supervisor knobs; drift and gate sub-configs ride along."""

    #: slice of traffic the canary takes while under evaluation
    canary_fraction: float = 0.25
    #: promote automatically when the gates pass (False = operators run
    #: ``gordo-tpu lifecycle promote`` after their own checks)
    auto_promote: bool = True
    #: warm the canary/promoted fleet (artifact loads + fused-program
    #: precompile when the serve engine is on) before it takes traffic
    warm_swaps: bool = True
    #: a machine whose canary was quarantined this recently is NOT
    #: re-tripped by drift — without a cooldown a persistent drift with
    #: a broken rebuild path would canary-storm (rebuild, fail gates,
    #: roll back, repeat) every cycle
    quarantine_cooldown_s: float = 3600.0
    #: hold auto-promotions while a page-severity SLO burn alert is
    #: FIRING (telemetry/slo.py): swapping artifacts mid-incident
    #: destroys the evidence an operator is debugging against, and a
    #: canary gated on a probe window says nothing about the live burn.
    #: The canary keeps serving its slice; `lifecycle promote --force`
    #: and gate failures (rollbacks) are never held.
    slo_gate: bool = True
    #: treat members whose SERVING circuit breaker tripped (the ledger's
    #: `breaker` section, fed by the serve engine) as rebuild candidates
    #: alongside drifted ones: a member whose device programs keep
    #: failing is stale in the way that matters most — it cannot serve
    breaker_rebuild: bool = True
    drift: DriftConfig = field(default_factory=DriftConfig)
    gates: GateConfig = field(default_factory=GateConfig)

    @classmethod
    def from_env(cls) -> "LifecycleConfig":
        from ..utils.env import env_bool

        return cls(
            canary_fraction=env_float("GORDO_TPU_CANARY_FRACTION", 0.25),
            quarantine_cooldown_s=env_float(
                "GORDO_TPU_QUARANTINE_COOLDOWN", 3600.0
            ),
            slo_gate=env_bool("GORDO_TPU_GATE_SLO_BURN", True),
            breaker_rebuild=env_bool(
                "GORDO_TPU_LIFECYCLE_BREAKER_REBUILD", True
            ),
            drift=DriftConfig.from_env(),
            gates=GateConfig.from_env(),
        )


@dataclass
class CycleReport:
    """What one :meth:`LifecycleSupervisor.run_cycle` did."""

    phase: str = "idle"
    drifted: Dict[str, List[str]] = field(default_factory=dict)
    stale: List[str] = field(default_factory=list)
    canary_revision: Optional[str] = None
    promoted: bool = False
    rolled_back: bool = False
    gate: Optional[Dict[str, Any]] = None
    details: Dict[str, Any] = field(default_factory=dict)


class LifecycleSupervisor:
    """The drift-triggered rebuild/canary/promote loop for one served
    collection directory."""

    def __init__(
        self,
        machines: Sequence[Any],
        collection_dir: str,
        store: Any = None,
        config: Optional[LifecycleConfig] = None,
    ):
        from ..server.fleet_store import STORE

        self.machines = list(machines)
        self.collection_dir = os.path.normpath(collection_dir)
        self.models_root = os.path.dirname(self.collection_dir)
        self.anchor_revision = os.path.basename(self.collection_dir)
        self.store = store if store is not None else STORE
        self.config = config or LifecycleConfig.from_env()
        self.state = LifecycleState.load(self.models_root)
        if self.state.anchor_revision not in (None, self.anchor_revision):
            # a NEW deploy moved the served revision out from under the
            # recorded lifecycle history: disk truth wins, start fresh
            # (quarantine records are append-only and survive)
            logger.warning(
                "lifecycle state anchored to revision %s but serving %s; "
                "starting a fresh lifecycle",
                self.state.anchor_revision,
                self.anchor_revision,
            )
            self.state = LifecycleState(self.models_root)
        if self.state.anchor_revision is None:
            self.state.update(
                anchor_revision=self.anchor_revision,
                serving_revision=self.anchor_revision,
            )
        self.recorder: Any = telemetry.NULL_RECORDER
        if telemetry.enabled():
            trace_dir = env_str(telemetry.TRACE_DIR_ENV, None) or os.path.join(
                self.models_root, LIFECYCLE_DIR
            )
            try:
                os.makedirs(trace_dir, exist_ok=True)
                self.recorder = telemetry.SpanRecorder(
                    sink_path=os.path.join(trace_dir, LIFECYCLE_TRACE_FILE),
                    service="gordo-tpu-lifecycle",
                )
            except OSError as exc:
                logger.debug("no lifecycle trace sink: %r", exc)
        self.monitor = DriftMonitor.from_revision(
            self.serving_dir, self.config.drift
        )
        self.monitor.restore(self.state.doc.get("drift") or {})
        self._probe_frames: Optional[Dict[str, Any]] = None
        self._project = (
            getattr(self.machines[0], "project_name", "") if self.machines else ""
        )
        # Per-member health ledger (telemetry/fleet_health.py), keyed to
        # the ANCHOR collection dir — the operator's stable handle, the
        # same dir the server's fleet-health route reads — so drift
        # verdicts, quarantines and promotions survive revision swaps.
        self._ledger: Any = telemetry.ledger_for(
            self.collection_dir, project=self._project
        )

    # -- identity -----------------------------------------------------------

    @property
    def serving_revision(self) -> str:
        return self.state.serving_revision or self.anchor_revision

    @property
    def serving_dir(self) -> str:
        return os.path.join(self.models_root, self.serving_revision)

    def canary_dir(self, revision: Optional[str] = None) -> Optional[str]:
        revision = revision or self.state.canary_revision
        return (
            os.path.join(self.models_root, revision) if revision else None
        )

    def _build_dir(self, revision: str) -> str:
        return os.path.join(self.models_root, LIFECYCLE_DIR, f"build-{revision}")

    def close(self) -> None:
        self.recorder.close()

    def attach_stream(self, plane: Any) -> None:
        """Wire the streaming scoring plane's windows into this
        supervisor's drift statistics. Duck-typed on purpose:
        ``gordo_tpu.stream`` must not import lifecycle (layering), so
        the supervisor reaches down and hands its monitor over — every
        streamed window then feeds the same drift verdicts as
        request/response observation."""
        plane.attach_drift(self.monitor)

    # -- observation --------------------------------------------------------

    def observe(self, frames: Dict[str, Any]) -> Tuple[Dict, Dict]:
        """Score ``frames`` through the SERVING fleet and fold the
        results into the drift statistics; returns ``(scores, errors)``
        exactly like ``RevisionFleet.fleet_scores`` (callers may serve
        them — observation never double-scores traffic)."""
        fleet = self.store.fleet(self.serving_dir)
        with self.recorder.span(
            "lifecycle_observe", machines=len(frames)
        ):
            scores, errors = fleet.fleet_scores(frames)
        self.monitor.observe_scores(frames, scores)
        self._probe_frames = dict(frames)
        self._feed_scores(frames, scores)
        return scores, errors

    def _feed_scores(self, frames: Dict[str, Any], scores: Dict) -> None:
        """Rolling per-machine residual means into the health ledger
        (one snapshot write for the whole window)."""
        try:
            import numpy as np

            for name, entry in scores.items():
                frame = frames.get(name)
                rows = len(frame) if frame is not None else 0
                residuals = np.asarray(entry[1], dtype=float).ravel()
                residuals = residuals[np.isfinite(residuals)]
                self._ledger.record_scores(
                    name,
                    rows,
                    float(residuals.mean()) if len(residuals) else None,
                    write=False,
                )
            self._ledger.write()
        except Exception as exc:  # noqa: BLE001 - the ledger is advisory
            logger.debug("health ledger scores not recorded: %r", exc)

    def evaluate_drift(self) -> Dict[str, DriftVerdict]:
        """Every machine's drift verdict (windows reset)."""
        with self.recorder.span(
            "drift_eval", machines=len(self.monitor.machines())
        ):
            verdicts = self.monitor.evaluate()
        for name, verdict in verdicts.items():
            if verdict.drifted:
                self.recorder.event(
                    "machine_drifted",
                    machine=name,
                    reasons=verdict.reasons,
                    **{
                        k: v
                        for k, v in verdict.stats.items()
                        if isinstance(v, (int, float))
                    },
                )
        try:
            for name, verdict in verdicts.items():
                self._ledger.record_drift(
                    name,
                    verdict.drifted,
                    verdict.reasons,
                    verdict.stats,
                    write=False,
                )
            self._ledger.flush()
        except Exception as exc:  # noqa: BLE001 - the ledger is advisory
            logger.debug("health ledger drift not recorded: %r", exc)
        return verdicts

    # -- the cycle ----------------------------------------------------------

    def run_cycle(self, frames: Optional[Dict[str, Any]] = None) -> CycleReport:
        """One supervision cycle: observe (when ``frames`` given), then
        advance the state machine as far as it can go — a fresh drift
        verdict can ride all the way to a promoted (or rolled-back)
        canary in one call; an interrupted prior cycle resumes its
        phase first."""
        report = CycleReport(phase=self.state.phase)
        with self.recorder.span("lifecycle_cycle", phase=self.state.phase):
            if frames:
                self.observe(frames)
            if self.state.phase == "rolling_back":
                self._finish_rollback(report)
            if self.state.phase == "idle":
                self._detect(report)
            if self.state.phase == "canary_building":
                self._build_and_publish(report)
            if self.state.phase == "canary_serving":
                self._gate_and_settle(report)
            # drift accumulators survive restarts (windows in progress
            # when the process dies are evidence, not noise)
            self.state.update(drift=self.monitor.snapshot())
            self._maybe_recalibrate(report)
        report.phase = self.state.phase
        self._export_status(report)
        return report

    def _maybe_recalibrate(self, report: CycleReport) -> None:
        """Online perfmodel recalibration, once per cycle: refit the
        learned cost regressors from the telemetry corpus and promote
        only if the holdout gate passes (``perfmodel.service``). Gated
        on ``GORDO_TPU_PERFMODEL_RECAL`` (default off) and advisory by
        contract — any failure is a debug log, never a broken cycle."""
        from ..utils.env import env_bool

        if not env_bool("GORDO_TPU_PERFMODEL_RECAL", False):
            return
        try:
            from ..perfmodel.service import maybe_recalibrate

            corpus = env_str(telemetry.TRACE_DIR_ENV, None) or self.collection_dir
            result = maybe_recalibrate(corpus)
            if result is None:
                return
            report.details["perfmodel"] = {
                "promoted": bool(result.get("promoted")),
                "reason": result.get("reason"),
                "models": len(result.get("models") or []),
            }
            self.recorder.event(
                "perfmodel_recalibrated",
                corpus=corpus,
                promoted=bool(result.get("promoted")),
                reason=str(result.get("reason", ""))[:200],
                models=len(result.get("models") or []),
            )
        except Exception as exc:  # noqa: BLE001 - recalibration is advisory
            logger.debug("perfmodel recalibration skipped: %r", exc)

    # -- phase steps --------------------------------------------------------

    def _detect(self, report: CycleReport) -> None:
        verdicts = self.evaluate_drift()
        report.drifted = {
            name: verdict.reasons
            for name, verdict in verdicts.items()
            if verdict.drifted
        }
        # serving-plane casualties: members whose circuit breaker the
        # serve engine tripped (repeated isolated device failures) are
        # rebuild candidates too — read from the merged health ledger,
        # the one arrow between serve and lifecycle
        tripped = self._breaker_candidates()
        if tripped:
            report.details["breaker_tripped"] = tripped
            logger.warning(
                "serving breaker tripped for %d machine(s) (%s); "
                "nominating for rebuild",
                len(tripped),
                ", ".join(tripped[:5]),
            )
        candidates = set(report.drifted) | set(tripped)
        buildable = {m.name for m in self.machines}
        stale = sorted(candidates & buildable)
        unbuildable = sorted(candidates - buildable)
        if unbuildable:
            logger.warning(
                "drifted machines with no machine config (cannot rebuild): %s",
                ", ".join(unbuildable),
            )
            report.details["unbuildable"] = unbuildable
        cooling = self._quarantine_cooldown() & set(stale)
        if cooling:
            logger.warning(
                "drifted machines in quarantine cooldown (a recent canary "
                "for them was rolled back): %s",
                ", ".join(sorted(cooling)),
            )
            report.details["cooldown"] = sorted(cooling)
            stale = sorted(set(stale) - cooling)
        if not stale:
            return
        report.stale = stale
        revision = next_revision(self.models_root)
        logger.info(
            "drift tripped %d machine(s) (%s); canary revision %s",
            len(stale),
            ", ".join(stale[:5]),
            revision,
        )
        self.state.transition(
            "canary_building",
            event="drift_detected",
            stale=stale,
            canary_revision=revision,
            drift=self.monitor.snapshot(),
        )
        self.recorder.event(
            "canary_started", canary_revision=revision, stale=stale
        )

    def _build_and_publish(self, report: CycleReport) -> None:
        from ..parallel.fleet_build import rebuild_stale
        from ..planner import PLAN_FILE

        stale = self.state.stale
        revision = self.state.canary_revision
        report.stale = stale
        report.canary_revision = revision
        fault_point("canary_build", revision or "")
        build_dir = self._build_dir(revision)
        with self.recorder.span(
            "canary_build", canary_revision=revision, stale=len(stale)
        ):
            builder = rebuild_stale(
                self.machines,
                stale,
                build_dir,
                base_plan_path=os.path.join(self.serving_dir, PLAN_FILE),
                resume=True,
                # rebuilt members' provenance (fresh losses, cleared or
                # re-tripped degrade flags) lands in the ANCHOR ledger
                # the fleet-status surfaces read, not in a ledger keyed
                # to this staging build dir
                health_ledger=self._ledger,
            )
        failed = sorted(builder.build_errors)
        rebuilt = sorted(set(stale) - set(failed))
        report.details["rebuilt"] = rebuilt
        report.details["resumed"] = sorted(builder.resumed)
        if failed:
            report.details["rebuild_failed"] = failed
        if not rebuilt:
            logger.error(
                "canary %s: every stale member failed to rebuild; "
                "serving stays on %s",
                revision,
                self.serving_revision,
            )
            reasons = [
                f"{name}: rebuild failed ({exc!r})"
                for name, exc in sorted(builder.build_errors.items())
            ]
            self.state.quarantine(
                {
                    "canary_revision": revision,
                    "machines": stale,
                    "reasons": reasons,
                }
            )
            self.state.transition(
                "idle", event="canary_build_failed", canary_revision=None,
                stale=[], rebuilt=[],
            )
            self._count_event("rollbacks")
            self._ledger.record_quarantine(stale, revision, reasons)
            report.rolled_back = True
            return
        canary_path = publish_canary(
            self.models_root,
            self.serving_revision,
            build_dir,
            rebuilt,
            revision,
        )
        self.recorder.event(
            "canary_published",
            canary_revision=revision,
            rebuilt=rebuilt,
            failed=failed,
        )
        fleet = self.store.set_canary(
            self.collection_dir,
            canary_path,
            self.config.canary_fraction,
            warm=self.config.warm_swaps,
        )
        self._warm_programs(fleet)
        self.state.transition(
            "canary_serving", event="canary_serving", rebuilt=rebuilt
        )
        self._count_event("rebuilds", len(rebuilt))

    def _gate_and_settle(self, report: CycleReport) -> None:
        revision = self.state.canary_revision
        report.canary_revision = revision
        canary_path = self.canary_dir(revision)
        # routing is process memory: a restarted supervisor re-installs
        # the canary slice before gating (idempotent when already set)
        if self.store.canary_status() is None and canary_path:
            self.store.set_canary(
                self.collection_dir,
                canary_path,
                self.config.canary_fraction,
                warm=self.config.warm_swaps,
            )
        probe = self._probe_frames
        if not probe:
            report.details["gate"] = "awaiting probe data"
            return
        rebuilt = list(self.state.doc.get("rebuilt") or self.state.stale)
        try:
            with self.recorder.span(
                "canary_gate", canary_revision=revision, rebuilt=len(rebuilt)
            ):
                gate = evaluate_canary(
                    self.store.fleet(self.serving_dir),
                    self.store.fleet(canary_path),
                    probe,
                    rebuilt,
                    self.config.gates,
                )
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:  # noqa: BLE001 - an unevaluable canary
            # is a failed canary, never a crashed loop
            gate = GateReport()
            gate.fail(f"gate evaluation crashed: {exc!r}")
        report.gate = {
            "passed": gate.passed,
            "failures": gate.failures,
            "checks": gate.checks,
        }
        self.recorder.event(
            "canary_gate",
            canary_revision=revision,
            passed=gate.passed,
            failures=gate.failures,
        )
        if not gate.passed:
            self._rollback(report, gate.failures)
            return
        holding = self._slo_hold()
        if holding:
            # the alert state machine feeds the gate inputs: a passing
            # canary does NOT auto-promote into a burning deployment —
            # it keeps its slice and re-gates next cycle (resolved
            # alerts release the hold; `promote --force` bypasses)
            report.details["gate"] = (
                "passed; auto-promotion held: SLO page alert firing "
                f"({', '.join(holding)})"
            )
            report.details["slo_hold"] = holding
            logger.warning(
                "canary %s passed gates but auto-promotion is held: "
                "firing SLO page alert(s) %s",
                revision,
                ", ".join(holding),
            )
        elif self.config.auto_promote:
            self._promote(report)
        else:
            report.details["gate"] = "passed; awaiting manual promote"

    def _promote(self, report: CycleReport) -> None:
        revision = self.state.canary_revision
        canary_path = self.canary_dir(revision)
        fault_point("promote_swap", revision or "")
        start = time.monotonic()
        with self.recorder.span("promote_swap", canary_revision=revision):
            self.store.swap(
                self.collection_dir, canary_path, warm=self.config.warm_swaps
            )
        swap_seconds = time.monotonic() - start
        rebuilt = list(self.state.doc.get("rebuilt") or self.state.stale)
        self.state.transition(
            "idle",
            event="promoted",
            serving_revision=revision,
            canary_revision=None,
            stale=[],
            rebuilt=[],
        )
        self._ledger.record_promotion(revision, rebuilt)
        logger.info(
            "promoted canary %s into serving (swap %.3fs)",
            revision,
            swap_seconds,
        )
        self.recorder.event(
            "promoted", revision=revision, swap_seconds=round(swap_seconds, 4)
        )
        # fresh baselines: rebuilt members' artifacts carry new training
        # stats, and every window restarts against the promoted fleet
        self.monitor = DriftMonitor.from_revision(
            self.serving_dir, self.config.drift
        )
        report.promoted = True
        report.details["swap_seconds"] = round(swap_seconds, 4)
        self._count_event("promotions")
        self._observe_swap(swap_seconds)

    def _rollback(self, report: CycleReport, reasons: List[str]) -> None:
        self.state.transition(
            "rolling_back", event="canary_rejected", reasons=reasons
        )
        self._finish_rollback(report, reasons=reasons)

    def _finish_rollback(
        self, report: CycleReport, reasons: Optional[List[str]] = None
    ) -> None:
        revision = self.state.canary_revision
        reasons = reasons or list(self.state.doc.get("reasons") or [])
        quarantined = self.state.stale
        fault_point("rollback", revision or "")
        with self.recorder.span("rollback", canary_revision=revision):
            self.store.clear_canary(self.collection_dir)
            # serving never left the last-good revision for non-canary
            # traffic; re-assert the redirect in case a crashed promote
            # landed its swap without its state transition
            self.store.swap(
                self.collection_dir, self.serving_dir, warm=False
            )
            self.state.quarantine(
                {
                    "canary_revision": revision,
                    "machines": self.state.stale,
                    "reasons": reasons,
                }
            )
            self.state.transition(
                "idle",
                event="rolled_back",
                canary_revision=None,
                stale=[],
                rebuilt=[],
                reasons=[],
            )
        logger.warning(
            "canary %s rolled back (%s); serving stays on %s",
            revision,
            "; ".join(reasons[:3]) or "no reasons recorded",
            self.serving_revision,
        )
        self.recorder.event(
            "rolled_back", canary_revision=revision, reasons=reasons
        )
        report.rolled_back = True
        report.details["quarantined"] = revision
        self._count_event("rollbacks")
        self._ledger.record_quarantine(quarantined, revision, reasons)

    def _breaker_candidates(self) -> List[str]:
        """Machines whose serving circuit breaker is tripped, from the
        merged health snapshots under the anchor dir (stale records
        expire — a dead server's forgotten `open` must not drive canary
        storms; the quarantine cooldown applies on top, like drift)."""
        if not self.config.breaker_rebuild:
            return []
        try:
            from ..telemetry import breaker_tripped_machines

            return sorted(breaker_tripped_machines(self.collection_dir))
        except Exception as exc:  # noqa: BLE001 - the feed is advisory;
            # a malformed snapshot must not stop drift-driven cycles
            logger.debug("breaker candidates not read: %r", exc)
            return []

    def _quarantine_cooldown(self) -> set:
        """Machines whose canaries were quarantined within the cooldown
        window — excluded from new stale sets so a persistent drift
        with a broken rebuild path cannot canary-storm."""
        cooldown = self.config.quarantine_cooldown_s
        if cooldown <= 0:
            return set()
        cutoff = time.time() - cooldown
        cooling: set = set()
        for record in self.state.quarantined():
            if float(record.get("time") or 0.0) >= cutoff:
                cooling.update(record.get("machines") or [])
        return cooling

    def _slo_hold(self) -> List[str]:
        """Firing page-severity SLO alert ids for this deployment's
        telemetry dir (the persisted state machine — no aggregation
        runs here), or [] when the SLO gate is off / never evaluated."""
        if not self.config.slo_gate:
            return []
        try:
            from ..telemetry import slo as slo_engine

            directory = slo_engine.slo_directory(self.collection_dir)
            if not directory:
                return []
            return [
                alert["id"]
                for alert in slo_engine.firing_alerts(
                    directory,
                    severity="page",
                    # a dead evaluator's stale 'firing' must not hold
                    # the self-healing loop forever
                    max_age_s=slo_engine.STALE_ALERT_HOLD_S,
                )
            ]
        except Exception as exc:  # noqa: BLE001 - a broken SLO state
            # file must not wedge the lifecycle loop
            logger.debug("slo hold check failed: %r", exc)
            return []

    # -- manual controls (CLI) ----------------------------------------------

    def promote(self, force: bool = False) -> CycleReport:
        """Operator promote: gate the current canary with the last probe
        window (unless ``force``) and swap it in."""
        report = CycleReport(phase=self.state.phase)
        if self.state.phase != "canary_serving":
            raise RuntimeError(
                f"no canary to promote (phase {self.state.phase})"
            )
        if force:
            report.canary_revision = self.state.canary_revision
            self._promote(report)
        else:
            previous, self.config.auto_promote = self.config.auto_promote, True
            try:
                self._gate_and_settle(report)
            finally:
                self.config.auto_promote = previous
            if report.details.get("slo_hold"):
                raise RuntimeError(
                    "promotion held: SLO page alert(s) firing "
                    f"({', '.join(report.details['slo_hold'])}); "
                    "resolve the burn or use --force"
                )
            if not (report.promoted or report.rolled_back):
                raise RuntimeError(
                    "gates could not run (no probe data scored yet); "
                    "re-run after traffic or use --force"
                )
        report.phase = self.state.phase
        return report

    def rollback(self, reason: str = "operator rollback") -> CycleReport:
        """Operator rollback of the current canary (or a re-run of an
        interrupted one)."""
        report = CycleReport(phase=self.state.phase)
        if self.state.phase not in ("canary_serving", "rolling_back"):
            raise RuntimeError(
                f"no canary to roll back (phase {self.state.phase})"
            )
        report.canary_revision = self.state.canary_revision
        if self.state.phase == "canary_serving":
            self._rollback(report, [reason])
        else:
            self._finish_rollback(report, reasons=[reason])
        report.phase = self.state.phase
        return report

    # -- best-effort exports ------------------------------------------------

    def _warm_programs(self, fleet: Any) -> None:
        """Precompile the fused serving programs for a fleet about to
        take traffic (only when the micro-batching engine is on)."""
        try:
            from ..serve import get_engine

            engine = get_engine()
            if engine is not None:
                engine.warmup_fleet(fleet)
        except Exception as exc:  # noqa: BLE001 - warmup is an optimization
            logger.debug("canary program warmup skipped: %r", exc)

    def _count_event(self, event: str, n: int = 1) -> None:
        try:
            from ..server.prometheus.metrics import record_fleet_lifecycle_event

            record_fleet_lifecycle_event(self._project, event, n)
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("lifecycle event not exported: %r", exc)

    def _observe_swap(self, seconds: float) -> None:
        try:
            from ..server.prometheus.metrics import observe_lifecycle_swap

            observe_lifecycle_swap(self._project, seconds)
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("swap duration not exported: %r", exc)

    def _export_status(self, report: CycleReport) -> None:
        try:
            from ..server.prometheus.metrics import set_fleet_lifecycle_status

            canary = self.store.canary_status()
            set_fleet_lifecycle_status(
                self._project,
                drifted=len(report.drifted),
                stale=len(self.state.stale),
                canary_fraction=float(canary["fraction"]) if canary else 0.0,
            )
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("lifecycle status not exported: %r", exc)


def restore_serving_state(collection_dir: str) -> Optional[str]:
    """Re-install a promoted revision's hot-swap redirect at server
    boot: when the lifecycle state anchored to ``collection_dir``
    records a different serving revision that still exists on disk, the
    store routes requests there (lazily loaded — the boot warmup pass
    handles residency). Returns the restored revision or None."""
    from ..server.fleet_store import STORE

    normalized = os.path.normpath(collection_dir)
    root = os.path.dirname(normalized)
    anchor = os.path.basename(normalized)
    state = LifecycleState.load(root)
    if state.anchor_revision != anchor:
        return None
    serving = state.serving_revision
    if not serving or serving == anchor:
        return None
    target = os.path.join(root, serving)
    if serving not in list_revisions(root) or not os.path.isdir(target):
        logger.warning(
            "lifecycle state serves revision %s but it is gone; serving %s",
            serving,
            anchor,
        )
        return None
    STORE.swap(normalized, target, warm=False)
    logger.info(
        "restored lifecycle serving state: %s routes to revision %s",
        normalized,
        serving,
    )
    return serving
