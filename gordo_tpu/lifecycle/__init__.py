"""
Self-healing fleet lifecycle: drift-triggered incremental rebuilds,
canary promotion with auto-rollback, and zero-downtime hot-swap.

The production scenario is not a one-shot build: thousands of
per-machine anomaly models must stay calibrated for months under
continuously arriving sensor data. This package turns the one-shot
subsystems into that loop — drift statistics over scored data
(``drift.py``), partial rebuilds of only the stale members (via
``gordo_tpu.parallel.rebuild_stale`` + FleetPlan replay), hardlinked
canary revisions (``revision.py``), promotion gates (``gates.py``),
crash-safe supervision state (``state.py``), and the supervisor itself
(``loop.py``). Serving integration lives in
``gordo_tpu.server.fleet_store`` (canary routing + hot swap). See
``docs/lifecycle.md``.
"""

from .drift import DriftConfig, DriftMonitor, DriftVerdict, MachineDrift
from .gates import GateConfig, GateReport, evaluate_canary
from .loop import (
    LIFECYCLE_TRACE_FILE,
    CycleReport,
    LifecycleConfig,
    LifecycleSupervisor,
    restore_serving_state,
)
from .revision import (
    delete_revision_dir,
    list_revisions,
    next_revision,
    publish_canary,
    revision_complete,
)
from .state import LIFECYCLE_DIR, QUARANTINE_FILE, STATE_FILE, LifecycleState

__all__ = [
    "CycleReport",
    "DriftConfig",
    "DriftMonitor",
    "DriftVerdict",
    "GateConfig",
    "GateReport",
    "LIFECYCLE_DIR",
    "LIFECYCLE_TRACE_FILE",
    "LifecycleConfig",
    "LifecycleState",
    "LifecycleSupervisor",
    "MachineDrift",
    "QUARANTINE_FILE",
    "STATE_FILE",
    "delete_revision_dir",
    "evaluate_canary",
    "list_revisions",
    "next_revision",
    "publish_canary",
    "restore_serving_state",
    "revision_complete",
]
