"""
Per-machine drift statistics: the trigger of the self-healing loop.

A fleet that lives for months under continuously arriving sensor data
goes stale machine by machine, not all at once — the lifecycle loop
therefore tracks TWO per-machine signals over the data it scores:

- **feature drift** — the running mean of each raw input tag, compared
  against the training baseline persisted in
  ``BuildMetadata.drift_baseline`` (``machine/metadata.py``). A tag
  whose serving-window mean has moved more than
  ``GORDO_TPU_DRIFT_SIGMA`` training standard deviations counts as
  shifted; a machine whose shifted-tag fraction reaches
  ``GORDO_TPU_DRIFT_FEATURE_QUORUM`` is feature-drifted.
- **residual drift** — the running mean of the per-row reconstruction
  error (the raw-target-space mse ``fleet_scores`` already computes).
  Training loss lives in the estimator's scaled space, so the serving
  baseline is calibrated online from the machine's first
  ``GORDO_TPU_DRIFT_CALIBRATION`` scored batches; once calibrated, a
  window whose mean residual exceeds ``GORDO_TPU_DRIFT_RESIDUAL_RATIO``
  × baseline is residual-drifted (the model no longer reconstructs what
  it is seeing).

Either signal trips the machine (``DriftVerdict.drifted``) once at
least ``GORDO_TPU_DRIFT_MIN_SAMPLES`` rows are in the window — a drift
verdict triggers a rebuild, so it must never fire off a handful of
rows. All accumulators are plain Welford-style sums, snapshot/restore
round-trip through JSON (the supervisor persists them in its state
file), and evaluation is deterministic given the observed data.

>>> config = DriftConfig(min_samples=4, sigma=1.0, calibration_batches=1)
>>> machine = MachineDrift(
...     "m-1",
...     baseline={"feature_means": [0.0], "feature_stds": [1.0],
...               "tags": ["t"], "n_samples": 100},
...     config=config,
... )
>>> machine.observe([[5.0], [5.1], [4.9], [5.0]])
>>> verdict = machine.evaluate()
>>> verdict.drifted, verdict.reasons[0].startswith("feature-shift")
(True, True)
"""

import logging
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.env import env_float, env_int
from ..utils.faults import fault_point

logger = logging.getLogger(__name__)

#: guard against degenerate (constant-tag) baselines: a zero training
#: std would make any noise look like infinite drift
_STD_FLOOR = 1e-9


@dataclass
class DriftConfig:
    """Drift-detection knobs, all env-overridable (``from_env``)."""

    #: mean shift, in training-stds, for one tag to count as shifted.
    #: 2.0 by default: sensor series are autocorrelated, so a short
    #: window's mean routinely wanders ~1σ from the training mean
    #: without the distribution having moved — a 1σ trigger would
    #: rebuild-storm on healthy random walks
    sigma: float = 2.0
    #: fraction of tags that must shift for feature drift (≥1 tag always)
    feature_quorum: float = 0.25
    #: window residual mean / calibrated baseline ratio for residual drift
    residual_ratio: float = 2.0
    #: rows required in the window before any verdict can fire
    min_samples: int = 64
    #: scored batches that form the online residual baseline
    calibration_batches: int = 3

    @classmethod
    def from_env(cls) -> "DriftConfig":
        return cls(
            sigma=env_float("GORDO_TPU_DRIFT_SIGMA", 2.0),
            feature_quorum=env_float("GORDO_TPU_DRIFT_FEATURE_QUORUM", 0.25),
            residual_ratio=env_float("GORDO_TPU_DRIFT_RESIDUAL_RATIO", 2.0),
            min_samples=env_int("GORDO_TPU_DRIFT_MIN_SAMPLES", 64),
            calibration_batches=env_int("GORDO_TPU_DRIFT_CALIBRATION", 3),
        )


@dataclass
class DriftVerdict:
    """One machine's evaluation: drifted or not, with the why."""

    machine: str
    drifted: bool = False
    reasons: List[str] = field(default_factory=list)
    stats: Dict[str, Any] = field(default_factory=dict)


class MachineDrift:
    """Welford-style window accumulators + drift tests for one machine.

    ``baseline`` is the ``drift_baseline`` dict out of the machine's
    build metadata (missing/empty baselines disable the feature test —
    the machine can still residual-drift)."""

    def __init__(
        self,
        name: str,
        baseline: Optional[Dict[str, Any]] = None,
        config: Optional[DriftConfig] = None,
    ):
        self.name = name
        self.config = config or DriftConfig()
        self.baseline = baseline if baseline and baseline.get("tags") else None
        # current window (cleared on every verdict); sums and counts
        # are per-feature and NaN-aware — raw sensor frames routinely
        # carry NaN rows, and one NaN must not poison (and thereby
        # silently disable) the whole feature test
        self._n = 0
        self._sum: Optional[np.ndarray] = None
        self._counts: Optional[np.ndarray] = None
        self._res_n = 0
        self._res_sum = 0.0
        # online residual baseline (first calibration_batches batches)
        self._cal_batches = 0
        self._cal_n = 0
        self._cal_sum = 0.0

    # -- observation --------------------------------------------------------

    def observe(self, X: Any, residuals: Any = None) -> None:
        """Fold one scored batch into the window: ``X`` the raw input
        rows (array/DataFrame), ``residuals`` the per-row mse the
        scoring path computed (optional — metadata-only probes)."""
        values = np.asarray(
            X.to_numpy() if hasattr(X, "to_numpy") else X, dtype=float
        )
        if values.ndim == 1:
            values = values[:, None]
        if len(values):
            finite = np.isfinite(values)
            batch_sum = np.where(finite, values, 0.0).sum(axis=0)
            if self._sum is None or self._sum.shape != batch_sum.shape:
                self._sum = np.zeros_like(batch_sum)
                self._counts = np.zeros(batch_sum.shape, dtype=np.int64)
                self._n = 0
            self._sum += batch_sum
            self._counts += finite.sum(axis=0)
            self._n += len(values)
        if residuals is None:
            return
        res = np.asarray(residuals, dtype=float).ravel()
        res = res[np.isfinite(res)]
        if not len(res):
            return
        if self._cal_batches < self.config.calibration_batches:
            self._cal_batches += 1
            self._cal_n += len(res)
            self._cal_sum += float(res.sum())
        else:
            self._res_n += len(res)
            self._res_sum += float(res.sum())

    # -- evaluation ---------------------------------------------------------

    @property
    def residual_baseline(self) -> Optional[float]:
        """The calibrated per-row residual baseline (None until the
        calibration window completes)."""
        if self._cal_batches < self.config.calibration_batches or not self._cal_n:
            return None
        return self._cal_sum / self._cal_n

    def evaluate(self, reset: bool = True) -> DriftVerdict:
        """The machine's drift verdict over the current window. Each
        signal's accumulator resets only once that signal was actually
        TESTABLE (its window reached ``min_samples``): a machine fed
        small per-cycle batches keeps accumulating evidence across
        cycles instead of having every sub-threshold window discarded
        — which would make drift permanently undetectable for it."""
        fault_point("drift_eval", self.name)
        verdict = DriftVerdict(machine=self.name)
        config = self.config
        verdict.stats["window_rows"] = self._n
        features_tested = residuals_tested = False
        try:
            if self._n >= config.min_samples and self.baseline is not None:
                features_tested = True
                self._feature_test(verdict)
            if self._res_n >= config.min_samples:
                residuals_tested = True
                self._residual_test(verdict)
        finally:
            if reset:
                if features_tested:
                    self._reset_features()
                if residuals_tested:
                    self._reset_residuals()
        verdict.drifted = bool(verdict.reasons)
        return verdict

    def _feature_test(self, verdict: DriftVerdict) -> None:
        means = np.asarray(
            [
                v if v is not None else np.nan
                for v in (self.baseline.get("feature_means") or [])
            ],
            float,
        )
        stds = np.asarray(
            [
                v if v is not None else np.nan
                for v in (self.baseline.get("feature_stds") or [])
            ],
            float,
        )
        # a column with ZERO finite rows in the window (offline sensor)
        # is NaN — not 0.0, which would read as a giant shift from any
        # nonzero baseline and trip drift off a dead sensor
        window_mean = np.where(
            self._counts > 0,
            self._sum / np.maximum(self._counts, 1),
            np.nan,
        )
        if means.shape != window_mean.shape or stds.shape != means.shape:
            # tag set changed since the baseline was built — the NEXT
            # rebuild records a fresh one; no feature verdict until then
            verdict.stats["feature_baseline"] = "shape-mismatch"
            return
        shift = np.abs(window_mean - means) / np.maximum(stds, _STD_FLOOR)
        # a tag whose baseline stat or window mean is non-finite (NaN
        # training column, all-NaN window) cannot vote either way —
        # NaN comparisons being always-False must never read as "no
        # drift" for the tags that ARE measurable
        shift = np.where(np.isfinite(shift), shift, 0.0)
        measurable = int(
            np.isfinite(means).sum()
        )  # quorum over tags that can actually be tested
        if not measurable:
            verdict.stats["feature_baseline"] = "no-finite-baseline"
            return
        tags = list(self.baseline.get("tags") or [])
        needed = max(1, int(math.ceil(self.config.feature_quorum * measurable)))
        shifted = [i for i in range(len(shift)) if shift[i] > self.config.sigma]
        verdict.stats["feature_shift_max"] = round(float(shift.max()), 4)
        verdict.stats["feature_shifted"] = len(shifted)
        if len(shifted) >= needed:
            worst = max(shifted, key=lambda i: shift[i])
            tag = tags[worst] if worst < len(tags) else str(worst)
            verdict.reasons.append(
                f"feature-shift {tag} ({shift[worst]:.2f}σ, "
                f"{len(shifted)}/{len(shift)} tags)"
            )

    def _residual_test(self, verdict: DriftVerdict) -> None:
        baseline = self.residual_baseline
        if baseline is None or baseline <= 0:
            verdict.stats["residual_baseline"] = "uncalibrated"
            return
        window = self._res_sum / self._res_n
        ratio = window / baseline
        verdict.stats["residual_ratio"] = round(float(ratio), 4)
        if ratio > self.config.residual_ratio:
            verdict.reasons.append(
                f"residual-ratio {ratio:.2f}x over the calibrated baseline"
            )

    def _reset_features(self) -> None:
        self._n = 0
        self._sum = None
        self._counts = None

    def _reset_residuals(self) -> None:
        self._res_n = 0
        self._res_sum = 0.0

    def reset_window(self) -> None:
        self._reset_features()
        self._reset_residuals()

    # -- persistence --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-roundtrippable accumulator state (supervisor state file)."""
        return {
            "n": self._n,
            "sum": list(self._sum) if self._sum is not None else None,
            "counts": (
                [int(c) for c in self._counts]
                if self._counts is not None
                else None
            ),
            "res_n": self._res_n,
            "res_sum": self._res_sum,
            "cal_batches": self._cal_batches,
            "cal_n": self._cal_n,
            "cal_sum": self._cal_sum,
        }

    def restore(self, snapshot: Dict[str, Any]) -> None:
        self._n = int(snapshot.get("n") or 0)
        raw = snapshot.get("sum")
        self._sum = np.asarray(raw, float) if raw is not None else None
        raw_counts = snapshot.get("counts")
        if raw_counts is not None:
            self._counts = np.asarray(raw_counts, np.int64)
        elif self._sum is not None:
            # snapshot from before per-feature counts: every row finite
            self._counts = np.full(self._sum.shape, self._n, np.int64)
        else:
            self._counts = None
        self._res_n = int(snapshot.get("res_n") or 0)
        self._res_sum = float(snapshot.get("res_sum") or 0.0)
        self._cal_batches = int(snapshot.get("cal_batches") or 0)
        self._cal_n = int(snapshot.get("cal_n") or 0)
        self._cal_sum = float(snapshot.get("cal_sum") or 0.0)


class DriftMonitor:
    """The fleet's per-machine :class:`MachineDrift` set, loadable from
    a served revision's artifact metadata."""

    def __init__(self, config: Optional[DriftConfig] = None):
        self.config = config or DriftConfig.from_env()
        self._machines: Dict[str, MachineDrift] = {}

    @classmethod
    def from_revision(
        cls, collection_dir: str, config: Optional[DriftConfig] = None
    ) -> "DriftMonitor":
        """A monitor seeded with every artifact's persisted
        ``drift_baseline`` (machines without one — older artifacts,
        exotic providers — still join, feature test disabled)."""
        from .. import serializer

        monitor = cls(config)
        for name in serializer.list_model_dirs(collection_dir):
            monitor.ensure(name, baseline=_load_baseline(collection_dir, name))
        return monitor

    def ensure(
        self, name: str, baseline: Optional[Dict[str, Any]] = None
    ) -> MachineDrift:
        machine = self._machines.get(name)
        if machine is None:
            machine = MachineDrift(name, baseline=baseline, config=self.config)
            self._machines[name] = machine
        return machine

    def machines(self) -> List[str]:
        return sorted(self._machines)

    def observe_scores(
        self,
        frames: Dict[str, Any],
        scores: Dict[str, Any],
    ) -> None:
        """Feed one scored request window: ``frames[name] -> X`` raw
        input rows, ``scores[name] -> (reconstruction, per-row mse)``
        as returned by ``RevisionFleet.fleet_scores``. Machines whose
        scoring failed contribute no residuals (their errors are the
        serving path's concern, not a drift signal)."""
        for name, X in frames.items():
            entry = scores.get(name)
            residuals = entry[1] if entry is not None else None
            try:
                self.ensure(name).observe(X, residuals)
            except Exception as exc:  # noqa: BLE001 - one machine's bad
                # frame must not poison the whole window's statistics
                logger.warning("drift observe failed for %s: %r", name, exc)

    def evaluate(self, reset: bool = True) -> Dict[str, DriftVerdict]:
        """Every machine's verdict. Per-machine isolation: an evaluation
        error marks that machine not-drifted (logged) instead of taking
        the loop down — process-fatal signals still propagate."""
        verdicts: Dict[str, DriftVerdict] = {}
        for name, machine in sorted(self._machines.items()):
            try:
                verdicts[name] = machine.evaluate(reset=reset)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as exc:  # noqa: BLE001 - per-machine isolation
                logger.warning("drift evaluation failed for %s: %r", name, exc)
                verdicts[name] = DriftVerdict(
                    machine=name, stats={"error": repr(exc)}
                )
        return verdicts

    def snapshot(self) -> Dict[str, Any]:
        return {
            name: machine.snapshot() for name, machine in self._machines.items()
        }

    def restore(self, snapshot: Dict[str, Any]) -> None:
        for name, machine_snapshot in (snapshot or {}).items():
            try:
                self.ensure(name).restore(machine_snapshot)
            except (TypeError, ValueError) as exc:
                logger.warning("drift snapshot for %s ignored: %r", name, exc)


def _load_baseline(collection_dir: str, name: str) -> Optional[Dict[str, Any]]:
    """The persisted drift baseline out of one artifact's metadata.json
    (None for artifacts predating the baseline, or torn metadata)."""
    import json
    import os

    path = os.path.join(collection_dir, name, "metadata.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        return (
            doc.get("metadata", {})
            .get("build_metadata", {})
            .get("drift_baseline")
        )
    except (OSError, ValueError, AttributeError) as exc:
        logger.debug("no drift baseline for %s/%s: %r", collection_dir, name, exc)
        return None
