"""
Canary promotion gates: a rebuilt fleet slice earns traffic, it is
never granted it.

Before a canary revision is hot-swapped into serving, every REBUILT
member must pass, on the same probe window scored against both the
base and the canary fleets:

- **load/score gate** — the canary artifact loads and scores the probe
  rows without error and with finite outputs; the per-canary error
  rate must stay at ``GORDO_TPU_GATE_MAX_ERROR_RATE`` (default 0: one
  broken rebuild blocks promotion);
- **threshold-parity gate** — a rebuilt anomaly detector's aggregate
  threshold must stay within ``GORDO_TPU_GATE_THRESHOLD_RATIO`` × of
  the base model's (either direction). Retraining on drifted data
  legitimately moves thresholds; a threshold orders of magnitude away
  means the rebuild trained on garbage and would flag everything (or
  nothing) the moment it took traffic;
- **residual-parity gate** — the canary's mean reconstruction error on
  the probe window must not exceed ``GORDO_TPU_GATE_RESIDUAL_RATIO`` ×
  the base model's on the same rows. The base is the STALE model, so a
  healthy rebuild usually scores far below it — a canary that scores
  materially WORSE than a model already flagged as drifted is broken,
  whatever its training loss claimed.

Gate failures are collected (not short-circuited) so the quarantine
record explains every reason at once.
"""

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils.env import env_float

logger = logging.getLogger(__name__)


@dataclass
class GateConfig:
    """Promotion-gate knobs, env-overridable (``from_env``)."""

    max_error_rate: float = 0.0
    threshold_ratio: float = 4.0
    residual_ratio: float = 2.0

    @classmethod
    def from_env(cls) -> "GateConfig":
        return cls(
            max_error_rate=env_float("GORDO_TPU_GATE_MAX_ERROR_RATE", 0.0),
            threshold_ratio=env_float("GORDO_TPU_GATE_THRESHOLD_RATIO", 4.0),
            residual_ratio=env_float("GORDO_TPU_GATE_RESIDUAL_RATIO", 2.0),
        )


@dataclass
class GateReport:
    """The full gate evaluation: pass/fail plus per-check evidence."""

    passed: bool = True
    failures: List[str] = field(default_factory=list)
    checks: Dict[str, Any] = field(default_factory=dict)

    def fail(self, reason: str) -> None:
        self.passed = False
        self.failures.append(reason)


def _aggregate_threshold(model: Any) -> Optional[float]:
    value = getattr(model, "aggregate_threshold_", None)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if np.isfinite(value) and value > 0 else None


def evaluate_canary(
    base_fleet: Any,
    canary_fleet: Any,
    frames: Dict[str, Any],
    rebuilt_names: Sequence[str],
    config: Optional[GateConfig] = None,
) -> GateReport:
    """
    Gate ``rebuilt_names`` for promotion: score the probe ``frames``
    (``name -> X``) on both fleets and apply the three gates above.
    Members without probe data still pass the load/threshold gates
    (their artifacts are checked) but skip residual parity — promotion
    with zero probe coverage of a rebuilt member is reported in
    ``checks`` so operators can see what the gate could not test.
    """
    config = config or GateConfig.from_env()
    report = GateReport()
    rebuilt = sorted(set(rebuilt_names))
    probe = {name: frames[name] for name in rebuilt if name in frames}
    report.checks["rebuilt"] = rebuilt
    report.checks["probed"] = sorted(probe)
    unprobed = sorted(set(rebuilt) - set(probe))
    if unprobed:
        report.checks["unprobed"] = unprobed

    base_scores, base_errors = (
        base_fleet.fleet_scores(probe) if probe else ({}, {})
    )
    canary_scores, canary_errors = (
        canary_fleet.fleet_scores(probe) if probe else ({}, {})
    )

    # -- load/score gate ----------------------------------------------------
    errored = sorted(canary_errors)
    nonfinite = sorted(
        name
        for name, (recon, mse) in canary_scores.items()
        if not (np.all(np.isfinite(recon)) and np.all(np.isfinite(mse)))
    )
    bad = sorted(set(errored) | set(nonfinite))
    error_rate = len(bad) / len(probe) if probe else 0.0
    report.checks["error_rate"] = round(error_rate, 4)
    if error_rate > config.max_error_rate:
        report.fail(
            f"canary error rate {error_rate:.2%} over "
            f"{config.max_error_rate:.2%} ({', '.join(bad[:5])})"
        )

    # -- threshold-parity gate ----------------------------------------------
    parity: Dict[str, Any] = {}
    for name in rebuilt:
        try:
            base_thr = _aggregate_threshold(base_fleet.model(name))
            canary_thr = _aggregate_threshold(canary_fleet.model(name))
        except Exception as exc:  # noqa: BLE001 - a load failure here is
            # the load gate's finding when probed; unprobed members must
            # still surface it
            if name not in bad:
                report.fail(f"{name}: canary model unloadable ({exc!r})")
            continue
        if base_thr is None:
            continue  # base is not a fitted detector: nothing to compare
        if canary_thr is None:
            report.fail(f"{name}: canary lost its anomaly threshold")
            continue
        ratio = max(base_thr, canary_thr) / min(base_thr, canary_thr)
        parity[name] = round(ratio, 4)
        if ratio > config.threshold_ratio:
            report.fail(
                f"{name}: threshold parity {ratio:.2f}x over "
                f"{config.threshold_ratio:.2f}x "
                f"(base {base_thr:.4g}, canary {canary_thr:.4g})"
            )
    report.checks["threshold_parity"] = parity

    # -- residual-parity gate -----------------------------------------------
    residual: Dict[str, Any] = {}
    for name in sorted(probe):
        base_entry = base_scores.get(name)
        canary_entry = canary_scores.get(name)
        if base_entry is None or canary_entry is None:
            continue
        base_mse = float(np.mean(base_entry[1]))
        canary_mse = float(np.mean(canary_entry[1]))
        if not np.isfinite(base_mse) or base_mse <= 0:
            continue
        ratio = canary_mse / base_mse
        residual[name] = round(ratio, 4)
        if ratio > config.residual_ratio:
            report.fail(
                f"{name}: canary residual {ratio:.2f}x the (already stale) "
                f"base on the probe window"
            )
    report.checks["residual_parity"] = residual
    if base_errors:
        # informational: the stale base failing to score the probe does
        # not block the canary (it is what the rebuild is fixing)
        report.checks["base_errors"] = sorted(base_errors)
    return report
