"""
Canary promotion gates: a rebuilt fleet slice earns traffic, it is
never granted it.

Before a canary revision is hot-swapped into serving, every REBUILT
member must pass, on the same probe window scored against both the
base and the canary fleets:

- **load/score gate** — the canary artifact loads and scores the probe
  rows without error and with finite outputs; the per-canary error
  rate must stay at ``GORDO_TPU_GATE_MAX_ERROR_RATE`` (default 0: one
  broken rebuild blocks promotion);
- **threshold-parity gate** — a rebuilt anomaly detector's aggregate
  threshold must stay within ``GORDO_TPU_GATE_THRESHOLD_RATIO`` × of
  the base model's (either direction). Retraining on drifted data
  legitimately moves thresholds; a threshold orders of magnitude away
  means the rebuild trained on garbage and would flag everything (or
  nothing) the moment it took traffic;
- **residual-parity gate** — the canary's mean reconstruction error on
  the probe window must not exceed ``GORDO_TPU_GATE_RESIDUAL_RATIO`` ×
  the base model's on the same rows. The base is the STALE model, so a
  healthy rebuild usually scores far below it — a canary that scores
  materially WORSE than a model already flagged as drifted is broken,
  whatever its training loss claimed;
- **precision-parity gate** — the threshold-parity idea promoted onto
  the serving precision ladder (PR 14): when the active serving
  precision is reduced (``GORDO_TPU_SERVE_PRECISION``/per-spec
  ``precision:``), the canary's bf16/int8 anomaly VERDICTS must agree
  with its own f32 verdicts within
  ``GORDO_TPU_GATE_PRECISION_AGREEMENT`` on a deterministic probe
  window (the shared math in ``gordo_tpu.serve.precision``). A canary
  whose rebuilt weights quantize badly must not be promoted into a
  reduced-precision fleet — and at serve time the same check gates each
  revision's buckets, degrading to f32 instead of erroring.

Gate failures are collected (not short-circuited) so the quarantine
record explains every reason at once.
"""

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils.env import env_float

logger = logging.getLogger(__name__)


@dataclass
class GateConfig:
    """Promotion-gate knobs, env-overridable (``from_env``)."""

    max_error_rate: float = 0.0
    threshold_ratio: float = 4.0
    residual_ratio: float = 2.0
    #: minimum reduced-vs-f32 verdict agreement (the precision-parity
    #: gate; only evaluated when the active serving precision is not f32)
    precision_agreement: float = 0.98

    @classmethod
    def from_env(cls) -> "GateConfig":
        return cls(
            max_error_rate=env_float("GORDO_TPU_GATE_MAX_ERROR_RATE", 0.0),
            threshold_ratio=env_float("GORDO_TPU_GATE_THRESHOLD_RATIO", 4.0),
            residual_ratio=env_float("GORDO_TPU_GATE_RESIDUAL_RATIO", 2.0),
            precision_agreement=env_float(
                "GORDO_TPU_GATE_PRECISION_AGREEMENT", 0.98
            ),
        )


@dataclass
class GateReport:
    """The full gate evaluation: pass/fail plus per-check evidence."""

    passed: bool = True
    failures: List[str] = field(default_factory=list)
    checks: Dict[str, Any] = field(default_factory=dict)

    def fail(self, reason: str) -> None:
        self.passed = False
        self.failures.append(reason)


def _aggregate_threshold(model: Any) -> Optional[float]:
    value = getattr(model, "aggregate_threshold_", None)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if np.isfinite(value) and value > 0 else None


def evaluate_canary(
    base_fleet: Any,
    canary_fleet: Any,
    frames: Dict[str, Any],
    rebuilt_names: Sequence[str],
    config: Optional[GateConfig] = None,
) -> GateReport:
    """
    Gate ``rebuilt_names`` for promotion: score the probe ``frames``
    (``name -> X``) on both fleets and apply the three gates above.
    Members without probe data still pass the load/threshold gates
    (their artifacts are checked) but skip residual parity — promotion
    with zero probe coverage of a rebuilt member is reported in
    ``checks`` so operators can see what the gate could not test.
    """
    config = config or GateConfig.from_env()
    report = GateReport()
    rebuilt = sorted(set(rebuilt_names))
    probe = {name: frames[name] for name in rebuilt if name in frames}
    report.checks["rebuilt"] = rebuilt
    report.checks["probed"] = sorted(probe)
    unprobed = sorted(set(rebuilt) - set(probe))
    if unprobed:
        report.checks["unprobed"] = unprobed

    base_scores, base_errors = (
        base_fleet.fleet_scores(probe) if probe else ({}, {})
    )
    canary_scores, canary_errors = (
        canary_fleet.fleet_scores(probe) if probe else ({}, {})
    )

    # -- load/score gate ----------------------------------------------------
    errored = sorted(canary_errors)
    nonfinite = sorted(
        name
        for name, (recon, mse) in canary_scores.items()
        if not (np.all(np.isfinite(recon)) and np.all(np.isfinite(mse)))
    )
    bad = sorted(set(errored) | set(nonfinite))
    error_rate = len(bad) / len(probe) if probe else 0.0
    report.checks["error_rate"] = round(error_rate, 4)
    if error_rate > config.max_error_rate:
        report.fail(
            f"canary error rate {error_rate:.2%} over "
            f"{config.max_error_rate:.2%} ({', '.join(bad[:5])})"
        )

    # -- threshold-parity gate ----------------------------------------------
    parity: Dict[str, Any] = {}
    for name in rebuilt:
        try:
            base_thr = _aggregate_threshold(base_fleet.model(name))
            canary_thr = _aggregate_threshold(canary_fleet.model(name))
        except Exception as exc:  # noqa: BLE001 - a load failure here is
            # the load gate's finding when probed; unprobed members must
            # still surface it
            if name not in bad:
                report.fail(f"{name}: canary model unloadable ({exc!r})")
            continue
        if base_thr is None:
            continue  # base is not a fitted detector: nothing to compare
        if canary_thr is None:
            report.fail(f"{name}: canary lost its anomaly threshold")
            continue
        ratio = max(base_thr, canary_thr) / min(base_thr, canary_thr)
        parity[name] = round(ratio, 4)
        if ratio > config.threshold_ratio:
            report.fail(
                f"{name}: threshold parity {ratio:.2f}x over "
                f"{config.threshold_ratio:.2f}x "
                f"(base {base_thr:.4g}, canary {canary_thr:.4g})"
            )
    report.checks["threshold_parity"] = parity

    # -- residual-parity gate -----------------------------------------------
    residual: Dict[str, Any] = {}
    for name in sorted(probe):
        base_entry = base_scores.get(name)
        canary_entry = canary_scores.get(name)
        if base_entry is None or canary_entry is None:
            continue
        base_mse = float(np.mean(base_entry[1]))
        canary_mse = float(np.mean(canary_entry[1]))
        if not np.isfinite(base_mse) or base_mse <= 0:
            continue
        ratio = canary_mse / base_mse
        residual[name] = round(ratio, 4)
        if ratio > config.residual_ratio:
            report.fail(
                f"{name}: canary residual {ratio:.2f}x the (already stale) "
                f"base on the probe window"
            )
    report.checks["residual_parity"] = residual
    if base_errors:
        # informational: the stale base failing to score the probe does
        # not block the canary (it is what the rebuild is fixing)
        report.checks["base_errors"] = sorted(base_errors)

    # -- precision-parity gate ----------------------------------------------
    # only engaged when the fleet would actually serve reduced: a canary
    # promoted into a bf16/int8 deployment must prove its quantized
    # verdicts first (serve-time gating then re-checks per revision and
    # degrades rather than erroring — this promotion-time check exists
    # so a badly-quantizing rebuild never even takes its canary slice
    # into the reduced ladder)
    _apply_precision_parity(canary_fleet, report, config)
    return report


def _apply_precision_parity(
    canary_fleet: Any, report: GateReport, config: GateConfig
) -> None:
    try:
        from ..serve.precision import ParityConfig, resolve_precision
    except Exception:  # noqa: BLE001 - serve package unavailable: the
        # classic gates still stand
        return
    from ..models.spec import FeedForwardSpec

    specs = {
        spec
        for spec in canary_fleet.loaded_specs().values()
        if isinstance(spec, FeedForwardSpec)
    }
    active = sorted(
        {
            (resolve_precision(spec), spec)
            for spec in specs
            if resolve_precision(spec) != "f32"
        },
        key=lambda pair: (pair[0], repr(pair[1])),
    )
    if not active:
        return
    parity_config = ParityConfig.from_env()
    parity_config.agreement = config.precision_agreement
    results: Dict[str, Any] = {}
    for precision, spec in active:
        gate = evaluate_precision_parity(
            canary_fleet, spec, precision, parity_config
        )
        key = f"{precision}:{type(spec).__name__}[{spec.n_features}]"
        results[key] = gate.checks.get("parity")
        if not gate.passed:
            report.failures.extend(gate.failures)
            report.passed = False
    report.checks["precision_parity"] = results


def evaluate_precision_parity(
    fleet: Any,
    spec: Any,
    precision: str,
    config: Optional["Any"] = None,
) -> GateReport:
    """
    The precision-parity gate for one fleet's spec bucket, as a
    :class:`GateReport`: scores a deterministic probe window through the
    f32 AND the reduced-precision fused programs
    (``gordo_tpu.serve.precision.evaluate_parity`` — the same math the
    serve engine's governor runs) and fails when any member's anomaly
    verdicts diverge past tolerance. Crashing evaluation is a FAILED
    gate, never an exception — the caller's rollback/degrade machinery
    handles both identically.
    """
    from ..serve.precision import ParityConfig, evaluate_parity

    if config is None:
        config = ParityConfig.from_env()
    report = GateReport()
    try:
        parity = evaluate_parity(fleet, spec, precision, config)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as exc:  # noqa: BLE001 - see docstring
        report.fail(f"precision parity evaluation crashed: {exc!r}")
        report.checks["parity"] = {"precision": precision, "error": repr(exc)}
        return report
    report.checks["parity"] = {
        "precision": parity.get("precision"),
        "agreement_min": parity.get("agreement_min"),
        "agreement_threshold": parity.get("agreement_threshold"),
        "members": {
            name: member.get("agreement")
            for name, member in (parity.get("members") or {}).items()
        },
    }
    if not parity.get("passed"):
        report.fail(
            parity.get("detail")
            or f"{precision} verdicts diverge from f32 past tolerance"
        )
    return report
