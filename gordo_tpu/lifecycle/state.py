"""
Crash-safe lifecycle state: ``<models_root>/.lifecycle/state.json``.

The supervisor is a long-running loop that may die at ANY point of a
cycle — the state file is what makes every phase resumable. It records
the phase machine (``idle → canary_building → canary_serving →
[promoted | rolling_back] → idle``), the identities the phases need
(anchor/serving/canary revisions, the stale member set), the drift
monitor's accumulator snapshot, and a bounded event history. Every
write is an atomic tempfile-then-``os.replace`` (the journal's
convention), so a kill mid-write leaves the previous complete state.

The quarantine record (``quarantine.json``, same directory) is
append-only evidence: every rolled-back canary lands there with its
revision, members and gate failures, so "why did this rebuild never
take traffic" has a durable answer.
"""

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

#: supervisor working directory under the models root (dotted: never a
#: revision, and the serving store ignores non-numeric entries anyway)
LIFECYCLE_DIR = ".lifecycle"
STATE_FILE = "state.json"
QUARANTINE_FILE = "quarantine.json"

#: phases of the lifecycle state machine (``promoted``/``rolled_back``
#: are history events, not phases — the machine rests in ``idle``)
PHASES = ("idle", "canary_building", "canary_serving", "rolling_back")

#: bounded history length (state.json must stay a small document)
MAX_HISTORY = 50


class LifecycleState:
    """The persisted document plus its accessors; one per models root."""

    def __init__(self, models_root: str):
        self.models_root = models_root
        self.directory = os.path.join(models_root, LIFECYCLE_DIR)
        self.path = os.path.join(self.directory, STATE_FILE)
        self.quarantine_path = os.path.join(self.directory, QUARANTINE_FILE)
        self.doc: Dict[str, Any] = {
            "version": 1,
            "phase": "idle",
            "anchor_revision": None,
            "serving_revision": None,
            "canary_revision": None,
            "stale": [],
            "drift": {},
            "history": [],
        }

    @classmethod
    def load(cls, models_root: str) -> "LifecycleState":
        """Read the persisted state; missing or torn files yield a fresh
        idle state (the supervisor then re-derives from disk truth)."""
        state = cls(models_root)
        try:
            with open(state.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and doc.get("version") == 1:
                state.doc.update(doc)
                if state.doc.get("phase") not in PHASES:
                    logger.warning(
                        "unknown lifecycle phase %r; resetting to idle",
                        state.doc.get("phase"),
                    )
                    state.doc["phase"] = "idle"
        except FileNotFoundError:
            pass
        except (OSError, ValueError) as exc:
            logger.warning(
                "unreadable lifecycle state %s (%r); starting idle",
                state.path,
                exc,
            )
        return state

    # -- accessors ----------------------------------------------------------

    @property
    def phase(self) -> str:
        return str(self.doc.get("phase") or "idle")

    @property
    def anchor_revision(self) -> Optional[str]:
        return self.doc.get("anchor_revision")

    @property
    def serving_revision(self) -> Optional[str]:
        return self.doc.get("serving_revision")

    @property
    def canary_revision(self) -> Optional[str]:
        return self.doc.get("canary_revision")

    @property
    def stale(self) -> List[str]:
        return list(self.doc.get("stale") or [])

    # -- mutation -----------------------------------------------------------

    def update(self, **fields: Any) -> None:
        """Merge fields and persist — no history entry (drift snapshot
        refreshes etc.)."""
        self.doc.update(fields)
        self.save()

    def transition(
        self, phase: str, event: Optional[str] = None, **fields: Any
    ) -> None:
        """Move the state machine and persist atomically; ``event``
        (default: the phase name) lands in the bounded history with a
        timestamp and the fields' identity keys."""
        if phase not in PHASES:
            raise ValueError(f"unknown lifecycle phase {phase!r}")
        self.doc.update(fields)
        self.doc["phase"] = phase
        entry = {
            "time": time.time(),
            "event": event or phase,
            "serving_revision": self.doc.get("serving_revision"),
            "canary_revision": self.doc.get("canary_revision"),
        }
        history = list(self.doc.get("history") or [])
        history.append(entry)
        self.doc["history"] = history[-MAX_HISTORY:]
        self.save()

    def save(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        payload = json.dumps(self.doc, indent=1, sort_keys=True, default=str)
        tmp = os.path.join(self.directory, f".{STATE_FILE}.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self.path)

    # -- quarantine ---------------------------------------------------------

    def quarantine(self, record: Dict[str, Any]) -> None:
        """Append one rolled-back canary's evidence (atomic rewrite of
        the whole — small — document)."""
        records = self.quarantined()
        records.append({"time": time.time(), **record})
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(
            self.directory, f".{QUARANTINE_FILE}.tmp-{os.getpid()}"
        )
        with open(tmp, "w") as f:
            json.dump(records, f, indent=1, sort_keys=True, default=str)
        os.replace(tmp, self.quarantine_path)

    def quarantined(self) -> List[Dict[str, Any]]:
        try:
            with open(self.quarantine_path) as f:
                records = json.load(f)
            return records if isinstance(records, list) else []
        except (OSError, ValueError):
            return []
