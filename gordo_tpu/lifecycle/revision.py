"""
Canary revision assembly: an incremental rebuild becomes a FULL
revision directory without retraining (or even copying) the untouched
majority.

Revisions are numeric directories under the models root (the layout
``run-server``/``cleanup-revisions``/the DELETE route already share).
:func:`publish_canary` assembles ``<root>/<revision>`` from the base
revision plus the rebuilt artifacts: untouched members are HARDLINKED
file-by-file (same volume, O(files) metadata ops, zero bytes copied —
with a copy fallback for cross-device layouts), rebuilt members come
from the lifecycle build directory. Assembly happens in a dotted
``.<revision>.tmp-<pid>`` staging dir — the same atomic-publish
convention as artifact dumps, so every discovery path already
classifies a crashed half-assembled canary as a staging leftover
(swept by ``clean_staging_dirs``) and a revision directory, once
visible, is always complete.
"""

import logging
import os
import shutil
from typing import List, Optional, Sequence

from .. import serializer
from ..parallel.journal import artifact_complete
from ..planner import PLAN_FILE

logger = logging.getLogger(__name__)


def list_revisions(models_root: str) -> List[str]:
    """Numeric revision directories under ``models_root``, oldest
    first (numeric order: '1000' is newer than '999')."""
    try:
        entries = os.listdir(models_root)
    except FileNotFoundError:
        return []
    return sorted(
        (
            entry
            for entry in entries
            if entry.isdigit() and os.path.isdir(os.path.join(models_root, entry))
        ),
        key=int,
    )


def next_revision(models_root: str) -> str:
    """The next free numeric revision name (max + 1; '1' for an empty
    root). Deterministic on purpose: the lifecycle state file records
    the chosen name BEFORE the build starts, so a crashed canary
    resumes into the same revision id."""
    revisions = list_revisions(models_root)
    return str(int(revisions[-1]) + 1) if revisions else "1"


def revision_complete(revision_dir: str) -> bool:
    """Every artifact in ``revision_dir`` checksum-complete (and at
    least one present) — the idempotence check a resumed publish uses
    before trusting an already-visible revision."""
    names = serializer.list_model_dirs(revision_dir)
    return bool(names) and all(
        artifact_complete(os.path.join(revision_dir, name)) for name in names
    )


def publish_canary(
    models_root: str,
    base_revision: str,
    rebuilt_dir: str,
    rebuilt_names: Sequence[str],
    revision: str,
) -> str:
    """
    Assemble and atomically publish ``<models_root>/<revision>`` from
    the base revision's artifacts with ``rebuilt_names`` taken from
    ``rebuilt_dir`` instead. Returns the revision directory path.

    Idempotent: a complete already-published revision (a crash landed
    between rename and state update, or a resumed supervisor re-runs
    the step) is returned as-is. A crash mid-assembly leaves only a
    dotted staging dir — never a torn revision.
    """
    target = os.path.join(models_root, revision)
    if os.path.isdir(target):
        if revision_complete(target):
            logger.info("canary revision %s already published", revision)
            return target
        raise RuntimeError(
            f"revision {revision} exists but is incomplete — refusing to "
            "overwrite a directory this process did not stage"
        )
    base_dir = os.path.join(models_root, base_revision)
    base_names = serializer.list_model_dirs(base_dir)
    rebuilt = set(rebuilt_names)
    missing = [
        name
        for name in rebuilt
        if not artifact_complete(os.path.join(rebuilt_dir, name))
    ]
    if missing:
        raise RuntimeError(
            f"rebuilt artifacts incomplete for {sorted(missing)}; canary "
            "cannot publish"
        )
    staging = os.path.join(models_root, f".{revision}.tmp-{os.getpid()}")
    if os.path.isdir(staging):
        shutil.rmtree(staging)
    os.makedirs(staging)
    try:
        for name in sorted(set(base_names) | rebuilt):
            source = os.path.join(
                rebuilt_dir if name in rebuilt else base_dir, name
            )
            _link_tree(source, os.path.join(staging, name))
        # the base build's full-fleet plan rides along: the NEXT
        # incremental rebuild replays it so pad targets stay stable
        plan_path = os.path.join(base_dir, PLAN_FILE)
        if os.path.isfile(plan_path):
            _link_file(plan_path, os.path.join(staging, PLAN_FILE))
        os.rename(staging, target)
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    logger.info(
        "published canary revision %s (%d rebuilt, %d inherited from %s)",
        revision,
        len(rebuilt),
        len(set(base_names) - rebuilt),
        base_revision,
    )
    return target


def _link_file(source: str, target: str) -> None:
    try:
        os.link(source, target)
    except OSError:  # cross-device / FS without hardlinks
        shutil.copy2(source, target)


def _link_tree(source: str, target: str) -> None:
    """Hardlink-or-copy one artifact directory tree."""
    os.makedirs(target, exist_ok=True)
    for entry in os.listdir(source):
        src = os.path.join(source, entry)
        dst = os.path.join(target, entry)
        if os.path.isdir(src):
            _link_tree(src, dst)
        else:
            _link_file(src, dst)


def delete_revision_dir(models_root: str, revision: str) -> Optional[str]:
    """Remove one revision directory (quarantined canary cleanup);
    returns the removed path or None when absent."""
    target = os.path.join(models_root, revision)
    if not os.path.isdir(target):
        return None
    shutil.rmtree(target, ignore_errors=True)
    return target
