"""
``gordo-tpu workflow generate``: project config → deployable k8s manifests.

Reference parity: gordo/cli/workflow_generator.py — same front-end
(NormalizedConfig with globals defaulting and per-machine validation),
same config-surface options (split-workflows, HPA type k8s_cpu/keda with
prometheus query templating, labels JSON, security contexts, owner
references, builder exception report level, reporter auto-injection).

Engine difference: the emitter targets the TPU fleet plane — machines are
grouped into shard-batches, one k8s Job per TPU slice running
``build-fleet`` — instead of one Argo pod per machine; and there is no
``argo`` binary dependency at all (the reference shells out to detect the
argo version; our manifests are plain k8s).
"""

import json
import logging
import os
import time
from typing import Any, Dict, List, cast

import click
import yaml
from jinja2 import BaseLoader, Environment

import gordo_tpu
from ..cli.exceptions_reporter import ReportLevel
from ..machine.encoders import MachineJSONEncoder
from ..utils.version import parse_version
from ..workflow.config_elements.normalized_config import NormalizedConfig
from ..workflow.config_elements.schemas import (
    EnvVar,
    PodSecurityContext,
    SecurityContext,
)
from ..workflow.workflow_generator import workflow_generator as wg
from ..workflow.workflow_generator.tpu import gke_accelerator_label, slice_geometry
from .custom_types import JSONParam

logger = logging.getLogger(__name__)

PREFIX = "WORKFLOW_GENERATOR"
DEFAULT_BUILDER_EXCEPTIONS_REPORT_LEVEL = ReportLevel.TRACEBACK

ML_SERVER_HPA_TYPES = ["none", "k8s_cpu", "keda"]
DEFAULT_ML_SERVER_HPA_TYPE = "k8s_cpu"

DEFAULT_KEDA_PROMETHEUS_METRIC_NAME = "gordo_server_request_duration_seconds_count"
DEFAULT_KEDA_PROMETHEUS_QUERY = (
    "sum(rate(gordo_server_request_duration_seconds_count"
    '{project=~"{{project_name}}",path=~".*prediction"}[30s]))'
)
DEFAULT_KEDA_PROMETHEUS_THRESHOLD = "1.0"
DEFAULT_CUSTOM_MODEL_BUILDER_ENVS = "[]"


def resolve_exceptions_report_level(config: NormalizedConfig) -> ReportLevel:
    """
    The ``ReportLevel`` the fleet builder should emit on failure — from
    ``runtime.builder.exceptions_report_level`` in the project globals,
    defaulting to TRACEBACK (config surface parity with reference
    cli/workflow_generator.py:45-62).
    """
    builder_runtime = config.globals.get("runtime", {}).get("builder", {})
    name = builder_runtime.get("exceptions_report_level")
    if name is None:
        return DEFAULT_BUILDER_EXCEPTIONS_REPORT_LEVEL
    level = ReportLevel.get_by_name(name)
    if level is None:
        valid = ", ".join(l.name for l in ReportLevel)
        raise ValueError(
            f"runtime.builder.exceptions_report_level={name!r} is not one "
            f"of: {valid}"
        )
    return level


#: The worst non-project chars any generated name carries. Candidates:
#: ConfigMap "gordo-tpu-fleet-config-<P>-r<8>-<wf:3>-<shard:2>" = 40, and
#: builder pod hostname "gordo-fleet-<P>-r<8>-<wf:3>-<shard:2>-<idx:2>" =
#: 33 — everything must stay within k8s' 63-char name/DNS labels or
#: kubectl rejects the deploy.
_NAME_OVERHEAD = max(
    len("gordo-tpu-fleet-config-") + len("-r12345678-999-99"),
    len("gordo-fleet-") + len("-r12345678-999-99-99"),
)


def check_project_name_fits(project_name: str) -> None:
    budget = 63 - _NAME_OVERHEAD
    if len(project_name) > budget:
        raise click.ClickException(
            f"--project-name {project_name!r} is {len(project_name)} chars; "
            f"at most {budget} fit within k8s' 63-char resource-name labels "
            "once revision/workflow/shard suffixes are added"
        )


def check_keda_flags(context: Dict[str, Any]) -> None:
    """KEDA autoscaling needs both the feature flag and a Prometheus URL."""
    if context["ml_server_hpa_type"] != "keda":
        return
    missing = None
    if not context["with_keda"]:
        missing = "--with-keda"
    elif not context["prometheus_server_address"]:
        missing = "--prometheus-server-address"
    if missing:
        raise click.ClickException(
            f"--ml-server-hpa-type=keda requires {missing}"
        )


def render_keda_query(query: str, project_name: str) -> str:
    """
    Expand the ``{{project_name}}`` placeholder in a KEDA Prometheus query
    (queries are user-configurable jinja strings scoped to the project).
    """
    if not query:
        return query
    return (
        Environment(loader=BaseLoader())
        .from_string(query)
        .render(project_name=project_name)
    )


def parse_label_overrides(value: str, flag: str = "--resources-labels") -> Dict[str, Any]:
    """
    A ``--*-labels`` JSON-dict CLI value as a plain dict; empty string means
    no overrides. Raises a ClickException naming the flag on malformed input.
    """
    if not value:
        return {}
    try:
        labels = json.loads(value)
    except json.JSONDecodeError as exc:
        raise click.ClickException(f"{flag}: not valid JSON ({exc})")
    if not isinstance(labels, dict):
        raise click.ClickException(
            f"{flag}: expected a JSON object, got {type(labels).__name__}"
        )
    return labels


def _k8s_resources(resources: Dict[str, Dict[str, int]]) -> Dict[str, Dict[str, str]]:
    """Config resource ints (MB / millicores) → k8s quantity strings."""
    return {
        bound: {
            "memory": f"{values['memory']}M",
            "cpu": f"{values['cpu']}m",
        }
        for bound, values in resources.items()
        if bound in ("requests", "limits")
    }


def _machines_yaml(machines) -> str:
    """A machine shard as the YAML document ``build-fleet`` consumes."""
    dicts = [
        json.loads(json.dumps(machine.to_dict(), cls=MachineJSONEncoder))
        for machine in machines
    ]
    return yaml.safe_dump({"machines": dicts}, default_flow_style=False)


@click.group("workflow")
@click.pass_context
def workflow_cli(gordo_ctx):
    pass


@click.command("generate")
@click.option(
    "--machine-config",
    type=str,
    help="Machine configuration file",
    envvar=f"{PREFIX}_MACHINE_CONFIG",
    required=True,
)
@click.option("--workflow-template", type=str, help="Template to expand")
@click.option(
    "--validate/--no-validate",
    "validate_manifests_flag",
    default=True,
    help="Validate every rendered document against the vendored k8s "
    "schemas and cross-document invariants before emitting (the offline "
    "analog of the reference's `argo lint` step); --no-validate skips it.",
    envvar=f"{PREFIX}_VALIDATE",
)
@click.option(
    "--owner-references",
    type=wg._valid_owner_ref,
    default=None,
    allow_from_autoenv=True,
    help="Kubernetes owner references to inject into all created resources. "
    "Should be a nonempty yaml/json list of owner-references, each a dict "
    "containing at least the keys 'uid', 'name', 'kind', and 'apiVersion'",
    envvar=f"{PREFIX}_OWNER_REFERENCES",
)
@click.option(
    "--gordo-version",
    type=str,
    default=wg._docker_friendly_version(gordo_tpu.__version__),
    help="Version of gordo-tpu to use, if different than this one",
    envvar=f"{PREFIX}_GORDO_VERSION",
)
@click.option(
    "--project-name",
    type=str,
    help="Name of the project which owns the workflow.",
    allow_from_autoenv=True,
    envvar=f"{PREFIX}_PROJECT_NAME",
    required=True,
)
@click.option(
    "--project-revision",
    type=str,
    default=str(int(time.time() * 1000)),  # unix time milliseconds
    help="Revision of the project which owns the workflow.",
    envvar=f"{PREFIX}_PROJECT_REVISION",
)
@click.option(
    "--output-file",
    type=str,
    required=False,
    help="Optional file to render to",
    envvar=f"{PREFIX}_OUTPUT_FILE",
)
@click.option(
    "--namespace",
    type=str,
    default="kubeflow",
    help="Which namespace to deploy services into",
    envvar=f"{PREFIX}_NAMESPACE",
)
@click.option(
    "--split-workflows",
    type=int,
    default=30,
    help="Split configs containing more than this number of machines into "
    "several workflow documents, output sequentially with '---' between, "
    "so kubectl can apply them all at once.",
    envvar=f"{PREFIX}_SPLIT_WORKFLOWS",
)
@click.option(
    "--n-servers",
    type=int,
    default=None,
    help="Max number of ML Servers to use, defaults to N machines * 10",
    envvar=f"{PREFIX}_N_SERVERS",
)
@click.option(
    "--docker-repository",
    type=str,
    default="equinor",
    help="The docker repo to use for pulling component images from",
    envvar=f"{PREFIX}_DOCKER_REPOSITORY",
)
@click.option(
    "--docker-registry",
    type=str,
    default="ghcr.io",
    help="The docker registry to use for pulling component images from",
    envvar=f"{PREFIX}_DOCKER_REGISTRY",
)
@click.option(
    "--retry-backoff-limit",
    type=int,
    default=6,
    help="backoffLimit for fleet-builder Jobs (k8s-native retry; replaces "
    "the reference's Argo retryStrategy backoff)",
    envvar=f"{PREFIX}_RETRY_BACKOFF_LIMIT",
)
@click.option(
    "--gordo-server-workers",
    type=int,
    help="The number of worker processes for handling server requests.",
    envvar=f"{PREFIX}_GORDO_SERVER_WORKERS",
)
@click.option(
    "--gordo-server-threads",
    type=int,
    help="The number of worker threads for handling requests.",
    envvar=f"{PREFIX}_GORDO_SERVER_THREADS",
)
@click.option(
    "--gordo-server-probe-timeout",
    type=int,
    help="timeoutSeconds for liveness/readiness probes of the server",
    envvar=f"{PREFIX}_GORDO_SERVER_PROBE_TIMEOUT",
)
@click.option(
    "--without-prometheus",
    is_flag=True,
    help="Do not deploy Prometheus metrics for server monitoring",
    envvar=f"{PREFIX}_WITHOUT_PROMETHEUS",
)
@click.option(
    "--image-pull-policy",
    help="Default imagePullPolicy for all images",
    envvar=f"{PREFIX}_IMAGE_PULL_POLICY",
)
@click.option(
    "--with-keda",
    is_flag=True,
    help="Enable support for the KEDA autoscaler",
    envvar=f"{PREFIX}_WITH_KEDA",
)
@click.option(
    "--ml-server-hpa-type",
    help="HPA type for the ML server",
    envvar=f"{PREFIX}_ML_SERVER_HPA_TYPE",
    type=click.Choice(ML_SERVER_HPA_TYPES),
    default=DEFAULT_ML_SERVER_HPA_TYPE,
)
@click.option(
    "--custom-model-builder-envs",
    help="JSON list of custom environment variables for the fleet builder",
    envvar=f"{PREFIX}_CUSTOM_MODEL_BUILDER_ENVS",
    default=DEFAULT_CUSTOM_MODEL_BUILDER_ENVS,
    type=JSONParam(List[EnvVar]),
)
@click.option(
    "--prometheus-server-address",
    help='Prometheus url. Required for "--ml-server-hpa-type=keda"',
    envvar=f"{PREFIX}_PROMETHEUS_SERVER_ADDRESS",
)
@click.option(
    "--keda-prometheus-metric-name",
    help="metricName value for the KEDA prometheus scaler",
    envvar=f"{PREFIX}_KEDA_PROMETHEUS_METRIC_NAME",
    default=DEFAULT_KEDA_PROMETHEUS_METRIC_NAME,
)
@click.option(
    "--keda-prometheus-query",
    help="query value for the KEDA prometheus scaler",
    envvar=f"{PREFIX}_KEDA_PROMETHEUS_QUERY",
    default=DEFAULT_KEDA_PROMETHEUS_QUERY,
)
@click.option(
    "--keda-prometheus-threshold",
    help="threshold value for the KEDA prometheus scaler",
    envvar=f"{PREFIX}_KEDA_PROMETHEUS_THRESHOLD",
    default=DEFAULT_KEDA_PROMETHEUS_THRESHOLD,
)
@click.option(
    "--resources-labels",
    help="Additional labels for resources, as a JSON dict",
    envvar=f"{PREFIX}_RESOURCE_LABELS",
    default="",
)
@click.option(
    "--model-builder-labels",
    help="Additional labels for fleet-builder Jobs, as a JSON dict",
    envvar=f"{PREFIX}_MODEL_BUILDER_LABELS",
    default="",
)
@click.option(
    "--server-labels",
    help="Additional labels for the server, as a JSON dict",
    envvar=f"{PREFIX}_SERVER_LABELS",
    default="",
)
@click.option(
    "--server-termination-grace-period",
    help="terminationGracePeriodSeconds for the server",
    envvar=f"{PREFIX}_SERVER_TERMINATION_GRACE_PERIOD",
    type=int,
    default=60,
)
@click.option(
    "--server-target-cpu-utilization-percentage",
    help="targetCPUUtilizationPercentage for the server's HPA",
    envvar=f"{PREFIX}_SERVER_TARGET_CPU_UTILIZATION_PERCENTAGE",
    type=int,
    default=50,
)
@click.option(
    "--gordo-server-readiness-initial-delay",
    help="initialDelaySeconds for the server's readinessProbe",
    envvar=f"{PREFIX}_GORDO_SERVER_READINESS_INITIAL_DELAY",
    type=int,
    default=5,
)
@click.option(
    "--gordo-server-liveness-initial-delay",
    help="initialDelaySeconds for the server's livenessProbe",
    envvar=f"{PREFIX}_GORDO_SERVER_LIVENESS_INITIAL_DELAY",
    type=int,
    default=600,
)
@click.option(
    "--security-context",
    help="Containers securityContext in JSON format",
    envvar=f"{PREFIX}_SECURITY_CONTEXT",
    type=JSONParam(SecurityContext),
)
@click.option(
    "--pod-security-context",
    help="Global workload securityContext in JSON format",
    envvar=f"{PREFIX}_POD_SECURITY_CONTEXT",
    type=JSONParam(PodSecurityContext),
)
@click.option(
    "--model-builder-class",
    help="ModelBuilder class",
    envvar="MODEL_BUILDER_CLASS",
)
@click.option(
    "--models-storage-size",
    help="Size of the shared model-artifact volume",
    envvar=f"{PREFIX}_MODELS_STORAGE_SIZE",
    default="10Gi",
)
@click.option(
    "--with-istio",
    is_flag=True,
    help="Emit an Istio VirtualService routing /gordo/v0/<project>/ to the server",
    envvar=f"{PREFIX}_WITH_ISTIO",
)
@click.option(
    "--istio-gateway",
    default="istio-system/ingressgateway",
    help="Gateway the VirtualService binds to",
    envvar=f"{PREFIX}_ISTIO_GATEWAY",
)
@click.option(
    "--istio-host",
    default="*",
    help="Host the VirtualService matches",
    envvar=f"{PREFIX}_ISTIO_HOST",
)
@click.option(
    "--with-prediction-replay",
    is_flag=True,
    help="Emit a replay Job that scores every built model through the "
    "server and forwards parquet predictions onto the model volume",
    envvar=f"{PREFIX}_WITH_PREDICTION_REPLAY",
)
@click.option(
    "--replay-start",
    default=None,
    help="Replay window start (ISO, tz-aware). Default: 24h before generation",
    envvar=f"{PREFIX}_REPLAY_START",
)
@click.option(
    "--replay-end",
    default=None,
    help="Replay window end (ISO, tz-aware). Default: generation time",
    envvar=f"{PREFIX}_REPLAY_END",
)
@click.option(
    "--client-max-instances",
    type=int,
    default=30,
    help="Concurrent prediction requests during replay (reference's client "
    "concurrency cap)",
    envvar=f"{PREFIX}_CLIENT_MAX_INSTANCES",
)
@click.option(
    "--revisions-to-keep",
    type=int,
    default=3,
    help="Old revisions retained on the model volume by the cleanup Job; "
    "0 disables cleanup",
    envvar=f"{PREFIX}_REVISIONS_TO_KEEP",
)
@click.option(
    "--without-model-crds",
    is_flag=True,
    help="Skip the per-machine Model custom resources (they need the "
    "gordo-controller CRD installed in the cluster)",
    envvar=f"{PREFIX}_WITHOUT_MODEL_CRDS",
)
@click.option(
    "--infra-storage-size",
    default="10Gi",
    help="Volume size for each infra statefulset (InfluxDB, Postgres, Grafana)",
    envvar=f"{PREFIX}_INFRA_STORAGE_SIZE",
)
@click.option(
    "--job-ttl-seconds",
    type=int,
    default=7 * 24 * 3600,
    help="ttlSecondsAfterFinished for builder/replay/cleanup Jobs — "
    "per-revision Jobs would otherwise accumulate forever",
    envvar=f"{PREFIX}_JOB_TTL_SECONDS",
)
@click.pass_context
def workflow_generator_cli(gordo_ctx, **ctx):
    """Machine configuration to TPU fleet workflow manifests."""
    context: Dict[Any, Any] = ctx.copy()
    yaml_content = wg.get_dict_from_yaml(context["machine_config"])

    model_builder_env = None
    if context["custom_model_builder_envs"]:
        custom_model_builder_envs = cast(
            List[EnvVar], context["custom_model_builder_envs"]
        )
        model_builder_env = [
            env_var.model_dump(exclude_none=True)
            for env_var in custom_model_builder_envs
        ]

    config = NormalizedConfig(
        yaml_content,
        project_name=context["project_name"],
        model_builder_env=model_builder_env,
    )

    try:
        log_level = config.globals["runtime"]["log_level"]
    except KeyError:
        log_level = os.getenv(
            "GORDO_LOG_LEVEL", (gordo_ctx.obj or {}).get("log_level", "INFO")
        )
    logging.getLogger("gordo_tpu").setLevel(log_level.upper())
    context["log_level"] = log_level.upper()

    check_keda_flags(context)
    check_project_name_fits(context["project_name"])

    resources_labels = parse_label_overrides(context["resources_labels"])
    model_builder_labels = parse_label_overrides(
        context["model_builder_labels"], "--model-builder-labels"
    )
    server_labels = parse_label_overrides(
        context["server_labels"], "--server-labels"
    )
    # Pre-merged label dicts; the template renders them as JSON flow
    # mappings (valid YAML) to avoid indentation-sensitive templating.
    context["common_labels"] = {
        "app.kubernetes.io/component": "gordo-tpu",
        "app.kubernetes.io/managed-by": "gordo-tpu",
        "applications.gordo.equinor.com/project-name": context["project_name"],
        "applications.gordo.equinor.com/project-revision": context["project_revision"],
        **resources_labels,
    }
    context["builder_labels"] = {
        **context["common_labels"],
        **model_builder_labels,
    }
    context["server_labels_merged"] = {
        **context["common_labels"],
        **server_labels,
    }

    for key in ("pod_security_context", "security_context"):
        if context[key]:
            context[key] = context[key].model_dump(exclude_none=True)
        else:
            context.pop(key)

    version = parse_version(context["gordo_version"])
    if not context.get("image_pull_policy"):
        context["image_pull_policy"] = wg.default_image_pull_policy(version)
    logger.info(
        "Generate config with gordo_version=%s and imagePullPolicy=%s",
        context["gordo_version"],
        context["image_pull_policy"],
    )

    context["max_server_replicas"] = (
        context.pop("n_servers") or len(config.machines) * 10
    )

    # Fleet-builder pod spec pieces
    builder_runtime = config.globals["runtime"]["builder"]
    builder_resources = builder_runtime["resources"]
    context["model_builder_resources_requests_memory"] = builder_resources["requests"]["memory"]
    context["model_builder_resources_requests_cpu"] = builder_resources["requests"]["cpu"]
    context["model_builder_resources_limits_memory"] = builder_resources["limits"]["memory"]
    context["model_builder_resources_limits_cpu"] = builder_resources["limits"]["cpu"]

    builder_runtime_env = list(builder_runtime.get("env") or [])
    if context["model_builder_class"]:
        builder_runtime_env.append(
            {"name": "MODEL_BUILDER_CLASS", "value": context["model_builder_class"]}
        )
    context["builder_runtime_env"] = builder_runtime_env
    context["builder_volumes"] = builder_runtime.get("volumes") or []
    context["builder_volume_mounts"] = builder_runtime.get("volumeMounts") or []

    context["server_resources_k8s"] = _k8s_resources(
        config.globals["runtime"]["server"]["resources"]
    )
    context["prometheus_metrics_server_resources_k8s"] = _k8s_resources(
        config.globals["runtime"]["prometheus_metrics_server"]["resources"]
    )

    # TPU fleet geometry
    fleet = config.globals["runtime"]["fleet"]
    context["slice_geometry"] = slice_geometry(fleet["accelerator_type"])
    context["tpu_accelerator_label"] = gke_accelerator_label(fleet["accelerator_type"])
    machines_per_slice = fleet["machines_per_slice"]

    context["keda_prometheus_query"] = render_keda_query(
        context["keda_prometheus_query"], context["project_name"]
    )

    # Replay window defaults: the 24 hours leading up to generation.
    import datetime as _datetime

    generated_at = _datetime.datetime.now(_datetime.timezone.utc).replace(
        microsecond=0
    )
    if not context["replay_end"]:
        context["replay_end"] = generated_at.isoformat()
    if not context["replay_start"]:
        context["replay_start"] = (
            generated_at - _datetime.timedelta(hours=24)
        ).isoformat()

    # Auto-attach reporters: a Postgres row per machine when influx/grafana
    # are in play, MLflow opt-in per machine (reference cli lines 538-557).
    enable_influx = any(
        machine.runtime.get("influx", {}).get("enable", True)
        for machine in config.machines
    )
    # The infra plane (InfluxDB + Grafana + Postgres statefulsets) rides
    # the same switch that injects the Postgres reporter: a reporter with
    # no database to write to would fail every build.
    context["with_influx"] = enable_influx
    context["influx_resources_k8s"] = _k8s_resources(
        config.globals["runtime"]["influx"]["resources"]
    )
    if enable_influx:
        pg_reporter = {
            "gordo_tpu.reporters.postgres.PostgresReporter": {
                "host": f"gordo-postgres-{config.project_name}"
            }
        }
        for machine in config.machines:
            machine.runtime.setdefault("reporters", []).append(pg_reporter)
    for machine in config.machines:
        try:
            enabled = machine.runtime["builder"]["remote_logging"]["enable"]
        except KeyError:
            continue
        if enabled:
            machine.runtime.setdefault("reporters", []).append(
                "gordo_tpu.reporters.mlflow.MlFlowReporter"
            )

    context["target_names"] = [machine.name for machine in config.machines]

    if context["owner_references"]:
        context["owner_references"] = json.dumps(context["owner_references"])
    else:
        context.pop("owner_references")

    context["builder_exceptions_report_level"] = resolve_exceptions_report_level(
        config
    ).name
    context["builder_exceptions_report_file"] = "/dev/termination-log"

    if context["workflow_template"]:
        template = wg.load_workflow_template(context["workflow_template"])
    else:
        template = wg.load_workflow_template(wg.default_workflow_template())

    if context["output_file"]:
        open(context["output_file"], "w").close()
    validate = bool(context.get("validate_manifests_flag", True))
    rendered_chunks: List[str] = []
    project_workflow = 0
    for i in range(0, len(config.machines), context["split_workflows"]):
        logger.info(
            "Generating workflow for machines %d to %d",
            i,
            i + context["split_workflows"],
        )
        chunk = config.machines[i : i + context["split_workflows"]]
        context["machines"] = chunk
        context["machine_shards"] = [
            {"machines_yaml": _machines_yaml(chunk[j : j + machines_per_slice])}
            for j in range(0, len(chunk), machines_per_slice)
        ]
        context["project_workflow"] = str(project_workflow)
        # Project-level resources (PVC, serving plane, infra statefulsets,
        # replay/cleanup Jobs) render once, in the first chunk only —
        # duplicate same-name documents break kustomize/ArgoCD/SSA even
        # though plain `kubectl apply` tolerates them. Later chunks emit
        # only their shard ConfigMaps+Jobs and their machines' Model CRs.
        context["first_workflow"] = project_workflow == 0

        if context["output_file"]:
            s = template.stream(**context)
            with open(context["output_file"], "a") as f:
                if i != 0:
                    f.write("\n---\n")
                s.dump(f)
        else:
            output = template.render(**context)
            rendered_chunks.append(output)
            if not validate:
                # With the gate off, stream chunks as they render; with
                # it on, printing waits until validation passes so that
                # `generate | kubectl apply -f -` can never feed invalid
                # documents to the consumer before the command fails.
                if i != 0:
                    print("\n---\n")
                print(output)
        project_workflow += 1

    if validate:
        # Offline schema gate before anything ships (the analog of the
        # reference's `argo lint` dockertest — see
        # workflow/manifest_validation.py): a template or config slip
        # fails THIS command, not the cluster apply.
        from ..workflow.manifest_validation import validate_manifests

        if context["output_file"]:
            with open(context["output_file"]) as f:
                text = f.read()
        else:
            text = "\n---\n".join(rendered_chunks)
        try:
            documents = list(yaml.safe_load_all(text))
        except yaml.YAMLError as exc:
            raise click.ClickException(
                "Rendered manifests are not parseable YAML "
                f"(--no-validate to bypass): {exc}"
            )
        errors = validate_manifests(documents)
        if errors:
            shown = "\n  ".join(errors[:20])
            more = f"\n  ... and {len(errors) - 20} more" if len(errors) > 20 else ""
            raise click.ClickException(
                f"Rendered manifests failed schema validation "
                f"({len(errors)} error(s); --no-validate to bypass):\n  "
                f"{shown}{more}"
            )
        logger.info("Rendered manifests validated against vendored schemas")
        if not context["output_file"]:
            print("\n---\n".join(rendered_chunks))


workflow_cli.add_command(workflow_generator_cli)

if __name__ == "__main__":
    workflow_cli()
