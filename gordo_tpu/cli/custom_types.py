"""
Click parameter types (reference: gordo/cli/custom_types.py): JSON
validated against a pydantic schema, regex-validated strings, host IPs,
and ``key,value`` pairs.
"""

import ipaddress
import json
import re
from typing import Any, Generic, Optional, Tuple, Type, TypeVar

import click
from pydantic import TypeAdapter, ValidationError

T = TypeVar("T")


class JSONParam(click.ParamType, Generic[T]):
    """Parse JSON and validate it against a pydantic schema."""

    name = "JSON"

    def __init__(self, schema: Type[T]):
        self.schema = schema
        self._adapter = TypeAdapter(schema)

    def convert(
        self, value: Any, param: Optional[click.Parameter], ctx: Optional[click.Context]
    ) -> Optional[T]:
        if value is None:
            return None
        try:
            data = json.loads(value)
        except json.JSONDecodeError as e:
            self.fail("Malformed JSON string - %s" % str(e))
        try:
            return self._adapter.validate_python(data)
        except ValidationError as e:
            self.fail("Schema validation error - %s" % str(e))


class REParam(click.ParamType):
    """Validate an argument against a regular expression."""

    name = "REGEXP"

    def __init__(self, pattern: str):
        self.pattern = pattern
        self.re = re.compile(pattern)

    def convert(
        self, value: Any, param: Optional[click.Parameter], ctx: Optional[click.Context]
    ):
        if not self.re.match(value):
            self.fail("Value '%s' not match '%s'" % (value, self.pattern))
        return value


class HostIP(click.ParamType):
    """Validate the input is an IP address."""

    name = "host"

    def convert(
        self, value: Any, param: Optional[click.Parameter], ctx: Optional[click.Context]
    ):
        try:
            ipaddress.ip_address(value)
            return value
        except ValueError as e:
            self.fail(str(e))


def key_value_par(val) -> Tuple[str, str]:
    """Split a CLI ``key,value`` pair."""
    return val.split(",")
