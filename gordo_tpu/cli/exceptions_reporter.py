"""
Failure contracts for containerized CLI runs: exception → exit code, and
a bounded JSON post-mortem for the k8s termination-message file.

Contract parity with the reference (gordo/cli/exceptions_reporter.py):
the most-derived registered exception type decides the exit code, report
verbosity is one of EXIT_CODE/TYPE/MESSAGE/TRACEBACK, payloads are
scrubbed to ASCII and trimmed to fit k8s's 2024-byte termination-message
limit. The mechanism here is original: exit codes resolve by walking the
raised type's own ``__mro__`` against a flat registry (no issubclass
scans over a depth-sorted list), and reports are assembled by per-level
field builders.
"""

import json
import traceback
from enum import Enum
from types import TracebackType
from typing import IO, Dict, Iterable, List, Optional, Tuple, Type

from ..utils.text import replace_all_non_ascii_chars

DEFAULT_EXIT_CODE = 1

#: Room left in the termination message for the JSON syntax and keys
#: around the payload strings.
_ELLIPSIS = "..."


class ReportLevel(Enum):
    """How much of a failure the termination report spells out."""

    EXIT_CODE = 0  # empty report: the exit code itself is the message
    TYPE = 1  # exception class name only
    MESSAGE = 2  # class name + str(exception)
    TRACEBACK = 3  # class name + formatted traceback tail

    @classmethod
    def get_by_name(
        cls, name: str, default: Optional["ReportLevel"] = None
    ) -> Optional["ReportLevel"]:
        return cls.__members__.get(name, default)

    @classmethod
    def get_names(cls) -> List[str]:
        return list(cls.__members__)


def _ascii(text: str) -> str:
    return replace_all_non_ascii_chars(text, "?")


def _clip(text: str, budget: int) -> str:
    """``text`` within ``budget`` characters, ellipsized when cut; a
    budget too small to hold anything beyond the ellipsis yields ''."""
    if len(text) <= budget:
        return text
    if budget <= len(_ELLIPSIS):
        return ""
    return text[: budget - len(_ELLIPSIS)] + _ELLIPSIS


def _traceback_tail(lines: List[str], budget: int) -> List[str]:
    """The innermost traceback lines that fit ``budget``, with a leading
    '...\\n' marker whenever outer frames were dropped."""
    marker = "...\n"
    if sum(map(len, lines)) <= budget:
        return lines
    tail: List[str] = []
    used = len(marker)
    for line in reversed(lines):
        if used + len(line) > budget:
            break
        tail.append(line)
        used += len(line)
    return [marker] + tail[::-1]


class ExceptionsReporter:
    """
    Flat ``{exception type: exit code}`` registry with MRO-based
    resolution, plus the JSON report writer for pod post-mortems.

    Resolution walks the *raised* type's method resolution order and
    takes the first registered class it meets — the most-derived
    registered ancestor by construction, with no ordering requirements
    on the registry itself.
    """

    def __init__(
        self,
        exceptions: Iterable[Tuple[Type[Exception], int]],
        default_exit_code: int = DEFAULT_EXIT_CODE,
        traceback_limit: Optional[int] = None,
    ):
        self._registry: Dict[Type[BaseException], int] = dict(exceptions)
        self.default_exit_code = default_exit_code
        self.traceback_limit = traceback_limit

    def _resolve(
        self, exc_type: Type[BaseException]
    ) -> Optional[Type[BaseException]]:
        for klass in exc_type.__mro__:
            if klass in self._registry:
                return klass
        return None

    def exception_exit_code(self, exc_type: Optional[Type[BaseException]]) -> int:
        """The ``sys.exit`` code for an exception type (0 for None)."""
        if exc_type is None:
            return 0
        match = self._resolve(exc_type)
        return self._registry[match] if match else self.default_exit_code

    # -- report assembly ----------------------------------------------------

    def _message_field(self, exc_value, budget: Optional[int]) -> str:
        text = _ascii(str(exc_value))
        return _clip(text, budget) if budget is not None else text

    def _traceback_field(
        self, exc_type, exc_value, exc_traceback, budget: Optional[int]
    ) -> str:
        lines = [
            _ascii(line)
            for line in traceback.format_exception(
                exc_type, exc_value, exc_traceback, limit=self.traceback_limit
            )
        ]
        if budget is not None:
            lines = _traceback_tail(lines, budget)
        return "".join(lines)

    def report(
        self,
        level: ReportLevel,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        exc_traceback: Optional[TracebackType],
        report_file: IO[str],
        max_message_len: Optional[int] = None,
    ):
        """Write the JSON report at the requested verbosity. Exceptions
        outside the registry (and the EXIT_CODE level) report ``{}`` —
        the exit code already tells the orchestrator everything."""
        payload: Dict[str, str] = {}
        have_failure = (
            exc_type is not None
            and exc_value is not None
            and exc_traceback is not None
        )
        if have_failure and level is not ReportLevel.EXIT_CODE:
            if self._resolve(exc_type) is not None:
                payload["type"] = _ascii(exc_type.__name__)
                if level is ReportLevel.MESSAGE:
                    payload["message"] = self._message_field(
                        exc_value, max_message_len
                    )
                elif level is ReportLevel.TRACEBACK:
                    payload["traceback"] = self._traceback_field(
                        exc_type, exc_value, exc_traceback, max_message_len
                    )
        json.dump(payload, report_file)

    def safe_report(
        self,
        level: ReportLevel,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        exc_traceback: Optional[TracebackType],
        report_file_path: str,
        max_message_len: Optional[int] = None,
    ):
        """``report`` that never raises (best-effort pod post-mortem)."""
        try:
            with open(report_file_path, "w") as report_file:
                self.report(
                    level,
                    exc_type,
                    exc_value,
                    exc_traceback,
                    report_file,
                    max_message_len,
                )
        except Exception:
            traceback.print_exc()
