"""
The ``gordo-tpu`` CLI.

Reference parity: gordo/cli/cli.py — subcommands ``build`` (env-var driven
the way an orchestrated build pod invokes it: ``MACHINE``, ``OUTPUT_DIR``,
``MODEL_REGISTER_DIR``), ``run-server``, and ``workflow`` (see
workflow_generator.py). The build command jinja-expands
``--model-parameter`` values into string model templates, freezes model
defaults by round-tripping the config through the serializer, reports the
built machine, optionally prints CV scores for hyperparameter tuners, and
maps exceptions to exit codes with a JSON report written for the k8s
termination-message path.

(The reference's ``if "err" in machine.name`` crash at cli.py:156-157 is
planted fault code, deliberately not reproduced — SURVEY.md preamble.)
"""

import json
import logging
import os
import sys
import time
import traceback
from typing import Any, List, Optional, Tuple, cast

import click
import jinja2
import yaml

import gordo_tpu
from ..builder.utils import create_model_builder
from .. import serializer
from ..dataset.exceptions import (
    ConfigException,
    InsufficientDataError,
    NoSuitableDataProviderError,
)
from ..dataset.sensor_tag import SensorTagNormalizationError
from ..machine import Machine, load_model_config
from ..reporters.base import ReporterException
from ..server import run_server
from ..client.cli import client_cli
from .custom_types import HostIP, key_value_par
from .exceptions_reporter import ExceptionsReporter, ReportLevel
from .workflow_generator import workflow_cli

_exceptions_reporter = ExceptionsReporter(
    (
        (Exception, 1),
        (ValueError, 2),
        (PermissionError, 20),
        (FileNotFoundError, 30),
        (SensorTagNormalizationError, 60),
        (NoSuitableDataProviderError, 70),
        (InsufficientDataError, 80),
        (ImportError, 85),
        (ReporterException, 90),
        (ConfigException, 100),
    )
)

logger = logging.getLogger(__name__)


@click.group("gordo-tpu")
@click.version_option(version=gordo_tpu.__version__, message=gordo_tpu.__version__)
@click.option(
    "--log-level",
    type=str,
    default="INFO",
    help="Run with custom log-level.",
    envvar="GORDO_LOG_LEVEL",
)
@click.option(
    "--jax-platform",
    type=str,
    default=None,
    help=(
        "Force the JAX platform (e.g. 'cpu', 'tpu'). TPU plugins may "
        "override JAX_PLATFORMS through jax.config, so this sets the config "
        "value directly — the escape hatch when a builder pod must run "
        "CPU-only or a TPU runtime is unreachable."
    ),
    envvar="GORDO_TPU_PLATFORM",
)
@click.pass_context
def gordo_tpu_cli(gordo_ctx: click.Context, **ctx):
    """The gordo-tpu command line interface."""
    logging.basicConfig(
        level=getattr(logging, str(gordo_ctx.params.get("log_level")).upper()),
        format=(
            "[%(asctime)s] %(levelname)s "
            "[%(name)s.%(funcName)s:%(lineno)d] %(message)s"
        ),
    )
    platform = gordo_ctx.params.get("jax_platform")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    gordo_ctx.obj = gordo_ctx.params


@click.command()
@click.argument("machine-config", envvar="MACHINE", type=yaml.safe_load)
@click.argument("output-dir", default="/data", envvar="OUTPUT_DIR")
@click.option(
    "--model-register-dir",
    default=None,
    envvar="MODEL_REGISTER_DIR",
    type=click.Path(
        exists=False, file_okay=False, dir_okay=True, writable=True, readable=True
    ),
)
@click.option(
    "--model-builder-class",
    help="ModelBuilder class import path; must subclass "
    "gordo_tpu.builder.build_model.ModelBuilder",
    envvar="MODEL_BUILDER_CLASS",
)
@click.option(
    "--print-cv-scores", help="Prints CV scores to stdout", is_flag=True, default=False
)
@click.option(
    "--model-parameter",
    type=key_value_par,
    multiple=True,
    default=(),
    help="Key-value pair for a model parameter, separated by a comma; may be "
    "given multiple times: --model-parameter key,val",
)
@click.option(
    "--exceptions-reporter-file",
    envvar="EXCEPTIONS_REPORTER_FILE",
    help="JSON output file for exception information",
)
@click.option(
    "--exceptions-report-level",
    type=click.Choice(ReportLevel.get_names(), case_sensitive=False),
    default=ReportLevel.MESSAGE.name,
    envvar="EXCEPTIONS_REPORT_LEVEL",
    help="Detail level for exception reporting",
)
def build(
    machine_config: dict,
    output_dir: str,
    model_register_dir: click.Path,
    model_builder_class: str,
    print_cv_scores: bool,
    model_parameter: List[Tuple[str, Any]],
    exceptions_reporter_file: str,
    exceptions_report_level: str,
):
    """Build a model and deposit it into OUTPUT_DIR."""
    try:
        if model_parameter and isinstance(machine_config["model"], str):
            parameters = dict(model_parameter)
            machine_config["model"] = expand_model(machine_config["model"], parameters)

        machine: Machine = Machine.from_config(
            cast(dict, load_model_config(machine_config)),
            project_name=machine_config["project_name"],
        )

        logger.info("Building, output will be at: %s", output_dir)
        logger.info("Register dir: %s", model_register_dir)

        # Round-trip the model config through the serializer so every
        # default parameter is frozen into the stored definition.
        logger.debug("Ensuring the passed model config is fully expanded.")
        machine.model = serializer.into_definition(
            serializer.from_definition(machine.model)
        )

        cls = create_model_builder(model_builder_class)
        builder = cls(machine=machine)

        _, machine_out = builder.build(output_dir, model_register_dir)

        logger.debug("Reporting built machine.")
        machine_out.report()
        logger.debug("Finished reporting.")

        if print_cv_scores:
            for score in get_all_score_strings(machine_out):
                print(score)

    except Exception:
        traceback.print_exc()
        exc_type, exc_value, exc_traceback = sys.exc_info()

        exit_code = _exceptions_reporter.exception_exit_code(exc_type)
        if exceptions_reporter_file:
            _exceptions_reporter.safe_report(
                cast(
                    ReportLevel,
                    ReportLevel.get_by_name(
                        exceptions_report_level, ReportLevel.EXIT_CODE
                    ),
                ),
                exc_type,
                exc_value,
                exc_traceback,
                exceptions_reporter_file,
                # k8s termination messages cap at 2024 bytes; leave headroom
                # for the JSON envelope.
                max_message_len=2024 - 500,
            )
        sys.exit(exit_code)
    else:
        return 0


def expand_model(model_config: str, model_parameters: dict) -> dict:
    """
    Expand a jinja-templated model config string with ``model_parameters``;
    undefined variables are an error.
    """
    try:
        model_template = jinja2.Environment(
            loader=jinja2.BaseLoader(), undefined=jinja2.StrictUndefined
        ).from_string(model_config)
        model_config = model_template.render(**model_parameters)
    except jinja2.exceptions.UndefinedError as e:
        raise ValueError("Model parameter missing value!") from e
    logger.info("Expanded model config: %s", model_config)
    return yaml.safe_load(model_config)


def get_all_score_strings(machine) -> List[str]:
    """
    CV scores as ``{metric}_{fold}={value}`` lines — the stdout format
    hyperparameter tuners (Katib) scrape from the build pod's log.
    """
    all_scores = []
    for (
        metric_name,
        scores,
    ) in machine.metadata.build_metadata.model.cross_validation.scores.items():
        metric_name = metric_name.replace(" ", "-")
        for score_name, score_val in scores.items():
            score_name = score_name.replace(" ", "-")
            all_scores.append(f"{metric_name}_{score_name}={score_val}")
    return all_scores


@click.command("run-server")
@click.option(
    "--host",
    type=HostIP(),
    help="The host to run the server on.",
    default="0.0.0.0",
    envvar="GORDO_SERVER_HOST",
    show_default=True,
)
@click.option(
    "--port",
    type=click.IntRange(1, 65535),
    help="The port to run the server on.",
    default=5555,
    envvar="GORDO_SERVER_PORT",
    show_default=True,
)
@click.option(
    "--workers",
    type=click.IntRange(1, 4),
    help="The number of worker processes for handling requests.",
    default=2,
    envvar="GORDO_SERVER_WORKERS",
    show_default=True,
)
@click.option(
    "--worker-connections",
    type=click.IntRange(1, 4000),
    help="The maximum number of simultaneous clients per worker process.",
    default=50,
    envvar="GORDO_SERVER_WORKER_CONNECTIONS",
    show_default=True,
)
@click.option(
    "--threads",
    type=int,
    help="The number of worker threads for handling requests "
    "(only with --worker-class=gthread).",
    default=8,
    envvar="GORDO_SERVER_THREADS",
)
@click.option(
    "--worker-class",
    help="The type of workers to use.",
    default="gthread",
    envvar="GORDO_SERVER_WORKER_CLASS",
    show_default=True,
)
@click.option(
    "--log-level",
    type=click.Choice(["debug", "info", "warning", "error", "critical"]),
    help="The log level for the server.",
    default="debug",
    envvar="GORDO_SERVER_LOG_LEVEL",
    show_default=True,
)
@click.option(
    "--server-app",
    help="The application to run",
    default="gordo_tpu.server.app:build_app()",
    envvar="GORDO_SERVER_APP",
    show_default=True,
)
@click.option(
    "--with-prometheus-config",
    help="Run with custom config for prometheus",
    is_flag=True,
)
@click.option(
    "--batching/--no-batching",
    default=None,
    help="Coalesce concurrent single-model requests into fused fleet "
    "programs (gordo_tpu.serve). Overrides GORDO_TPU_BATCHING; the "
    "default leaves the env switch (default: off) in charge.",
)
@click.option(
    "--batch-max-size",
    type=click.IntRange(1, 4096),
    default=None,
    help="Requests per fused batch before an immediate flush "
    "[GORDO_TPU_BATCH_MAX_SIZE, default 32].",
)
@click.option(
    "--batch-max-delay-ms",
    type=click.FloatRange(0.0, 60000.0),
    default=None,
    help="Longest a request waits for co-batchable traffic "
    "[GORDO_TPU_BATCH_MAX_DELAY_MS, default 5].",
)
@click.option(
    "--batch-queue-depth",
    type=click.IntRange(1, 1 << 20),
    default=None,
    help="Queued requests before admission control answers 429 "
    "[GORDO_TPU_BATCH_QUEUE_DEPTH, default 512].",
)
@click.option(
    "--batch-deadline-ms",
    type=click.FloatRange(1.0, 600000.0),
    default=None,
    help="Per-request batching deadline before a 504 "
    "[GORDO_TPU_BATCH_DEADLINE_MS, default 2000].",
)
@click.option(
    "--batch-row-ladder",
    default=None,
    help="Comma-separated row-padding rungs bounding the jit cache "
    "[GORDO_TPU_BATCH_ROW_LADDER, default 32,128,512,2048,8192].",
)
@click.option(
    "--serve-warmup/--no-serve-warmup",
    default=None,
    help="Precompile each served bucket's ladder programs at startup "
    "[GORDO_TPU_SERVE_WARMUP, default on when batching is on].",
)
@click.option(
    "--serve-precision",
    type=click.Choice(["f32", "bf16", "int8"]),
    default=None,
    help="Default serving precision for the fused batch programs "
    "[GORDO_TPU_SERVE_PRECISION, default f32]. A spec's own "
    "`precision:` field overrides per model; reduced precision serves "
    "only behind a passed precision-parity gate and degrades to f32 "
    "on failure (see docs/serving.md, 'Serving precision').",
)
def run_server_cli(
    host,
    port,
    workers,
    worker_connections,
    threads,
    worker_class,
    log_level,
    server_app,
    with_prometheus_config,
    batching,
    batch_max_size,
    batch_max_delay_ms,
    batch_queue_depth,
    batch_deadline_ms,
    batch_row_ladder,
    serve_warmup,
    serve_precision,
):
    """Run the model server."""
    # Batching knobs travel as env vars — that is how they reach the
    # gunicorn worker processes (and the werkzeug fallback alike).
    for env_name, value in (
        ("GORDO_TPU_BATCHING", None if batching is None else int(batching)),
        ("GORDO_TPU_BATCH_MAX_SIZE", batch_max_size),
        ("GORDO_TPU_BATCH_MAX_DELAY_MS", batch_max_delay_ms),
        ("GORDO_TPU_BATCH_QUEUE_DEPTH", batch_queue_depth),
        ("GORDO_TPU_BATCH_DEADLINE_MS", batch_deadline_ms),
        ("GORDO_TPU_BATCH_ROW_LADDER", batch_row_ladder),
        ("GORDO_TPU_SERVE_WARMUP", None if serve_warmup is None else int(serve_warmup)),
        ("GORDO_TPU_SERVE_PRECISION", serve_precision),
    ):
        if value is not None:
            os.environ[env_name] = str(value)
    config_module = None
    if with_prometheus_config:
        config_module = "gordo_tpu.server.prometheus.gunicorn_config"
    run_server(
        host,
        port,
        workers,
        log_level.lower(),
        config_module=config_module,
        worker_connections=worker_connections,
        threads=threads,
        worker_class=worker_class,
        server_app=server_app,
    )


def _load_fleet_machines(machines_config: str) -> List[Machine]:
    """Machines from a path to (or literal YAML of) a ``machines:``
    document, project_name defaulted per machine — shared by
    ``build-fleet`` and ``plan``."""
    if os.path.isfile(machines_config):
        with open(machines_config) as f:
            config = yaml.safe_load(f)
    else:
        config = yaml.safe_load(machines_config)
    project = config.get("project_name", "fleet-build")
    machine_dicts = [dict(m) for m in config["machines"]]
    for m in machine_dicts:
        m.setdefault("project_name", project)
    return [Machine.from_dict(m) for m in machine_dicts]


def _load_planner_inputs(
    plan_from: Optional[str], cost_table_path: Optional[str]
):
    """(FleetPlan, CostTable) from their CLI paths (None where absent);
    unusable documents (stale version, torn JSON) become clean CLI
    errors, not tracebacks."""
    from ..planner import CostTable, FleetPlan

    try:
        fleet_plan = FleetPlan.load(plan_from) if plan_from else None
    except ValueError as exc:
        raise click.ClickException(f"--plan-from: {exc}") from exc
    try:
        cost_table = (
            CostTable.load(cost_table_path) if cost_table_path else None
        )
    except ValueError as exc:
        raise click.ClickException(f"--cost-table: {exc}") from exc
    return fleet_plan, cost_table


@click.command("plan")
@click.argument("machines-config", envvar="MACHINES_CONFIG")
@click.option(
    "--strategy",
    type=click.Choice(["naive", "packed"]),
    default=None,
    help="Bucket-construction strategy (default: GORDO_TPU_PLAN_STRATEGY "
    "or naive). `packed` is the cost-model bin packer: geometric shape "
    "ladders, per-bucket HBM caps, compile-budget rung merging.",
)
@click.option(
    "--output",
    "-o",
    "output_path",
    default=None,
    type=click.Path(dir_okay=False, writable=True),
    help="Write the FleetPlan JSON here (feed it to "
    "`build-fleet --plan-from`).",
)
@click.option(
    "--cost-table",
    "cost_table_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Calibrated cost_table.json to cost buckets with "
    "(default: the analytic table).",
)
@click.option(
    "--calibrate-from",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Fit a cost table from this build_trace.jsonl first (the "
    "telemetry trace of any previous build on the same backend) and "
    "plan with it; persisted as cost_table.json beside the trace "
    "unless --cost-table-out is given.",
)
@click.option(
    "--cost-table-out",
    default=None,
    type=click.Path(dir_okay=False, writable=True),
    help="Where --calibrate-from persists the fitted table.",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw plan document instead of the table",
)
def plan_fleet(
    machines_config: str,
    strategy: Optional[str],
    output_path: Optional[str],
    cost_table_path: Optional[str],
    calibrate_from: Optional[str],
    cost_table_out: Optional[str],
    as_json: bool,
):
    """
    Emit and explain the FleetPlan a ``build-fleet`` of MACHINES_CONFIG
    would run: every bucket with its member roster, padded shape,
    predicted compile/run seconds, HBM footprint and padding waste —
    deterministic (same config + cost table → byte-identical JSON, so
    the plan hash is a stable identity the build journal records).

    Data IS fetched and staged (bucket shapes depend on per-machine
    sample counts), but nothing trains and no artifacts are written.
    """
    from ..parallel.fleet_build import FleetBuilder
    from ..planner import COST_TABLE_FILE, calibrate, render_plan

    _, cost_table = _load_planner_inputs(None, cost_table_path)
    if calibrate_from:
        cost_table = calibrate(calibrate_from, cost_table)
        table_path = cost_table_out or os.path.join(
            os.path.dirname(os.path.abspath(calibrate_from)), COST_TABLE_FILE
        )
        cost_table.save(table_path)
        logger.info("Calibrated cost table written to %s", table_path)

    machines = _load_fleet_machines(machines_config)
    builder = FleetBuilder(
        machines, plan_strategy=strategy, cost_table=cost_table
    )
    plan = builder.plan_only()
    if builder.build_errors:
        name, exc = next(iter(builder.build_errors.items()))
        raise click.ClickException(
            f"{len(builder.build_errors)} machine(s) could not be planned "
            f"(first: {name}: {exc!r})"
        )
    if output_path:
        plan.save(output_path)
        logger.info("FleetPlan written to %s", output_path)
    if as_json:
        click.echo(plan.to_json(), nl=False)
    else:
        click.echo(render_plan(plan))


@click.command("build-fleet")
@click.argument("machines-config", envvar="MACHINES_CONFIG")
@click.argument("output-dir", default="/data", envvar="OUTPUT_DIR")
@click.option(
    "--model-register-dir",
    default=None,
    envvar="MODEL_REGISTER_DIR",
    type=click.Path(
        exists=False, file_okay=False, dir_okay=True, writable=True, readable=True
    ),
)
@click.option(
    "--exceptions-reporter-file",
    envvar="EXCEPTIONS_REPORTER_FILE",
    help="JSON output file for exception information",
)
@click.option(
    "--exceptions-report-level",
    type=click.Choice(ReportLevel.get_names(), case_sensitive=False),
    default=ReportLevel.MESSAGE.name,
    envvar="EXCEPTIONS_REPORT_LEVEL",
    help="Detail level for exception reporting",
)
@click.option(
    "--resume",
    is_flag=True,
    envvar="FLEET_RESUME",
    help="Resume a crashed build from OUTPUT_DIR's build journal: machines "
    "journaled complete (config-hash matched, artifact checksum-verified) "
    "are skipped; only the remainder is replanned and trained.",
)
@click.option(
    "--plan-strategy",
    type=click.Choice(["naive", "packed"]),
    default=None,
    help="Bucket-construction strategy (gordo_tpu.planner): naive = the "
    "historical exact-key grouping (default, also via "
    "GORDO_TPU_PLAN_STRATEGY), packed = cost-model bin packing with "
    "geometric shape ladders, HBM caps and a compile budget.",
)
@click.option(
    "--plan-from",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Replay a FleetPlan emitted by `gordo-tpu plan`: covered "
    "members train in their planned buckets with their planned pad "
    "targets (stable across --resume); uncovered members pack live.",
)
@click.option(
    "--cost-table",
    "cost_table_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Calibrated cost_table.json for the packed strategy's cost "
    "model.",
)
def build_fleet(
    machines_config: str,
    output_dir: str,
    model_register_dir: Optional[str],
    exceptions_reporter_file: str,
    exceptions_report_level: str,
    resume: bool,
    plan_strategy: Optional[str],
    plan_from: Optional[str],
    cost_table_path: Optional[str],
):
    """
    Train a whole machine shard as mesh-sharded model batches on this TPU
    slice — the entry point each fleet-builder Job pod runs (the TPU-native
    replacement for the reference's one-`build`-pod-per-machine fan-out).

    MACHINES_CONFIG is a path to (or literal YAML of) a document with a
    ``machines:`` list of fully-resolved machine dicts, as emitted into the
    workflow's ConfigMaps by ``workflow generate``.
    """
    import os

    try:
        _maybe_init_distributed()

        # ConfigMap dicts from `workflow generate` are fully resolved; a
        # hand-written document may instead carry project_name at the top
        # level (or omit it entirely for local runs).
        machines = _load_fleet_machines(machines_config)
        fleet_plan, cost_table = _load_planner_inputs(
            plan_from, cost_table_path
        )

        from ..parallel.fleet_build import FleetBuilder

        # On a multi-host slice every process runs the same SPMD training
        # program, but only the coordinator may write artifacts, touch the
        # shared build cache, or run reporters — otherwise N pods race on
        # the same files and duplicate every report.
        is_coordinator = int(os.getenv("JAX_PROCESS_INDEX", "0")) == 0
        if not is_coordinator:
            # The coordinator's machine filters must be mirrored here: all
            # processes run ONE SPMD program, so every process has to
            # train the same surviving machine set — a divergent list
            # desynchronizes the collective device programs. Both mirrors
            # read the shared volume without writing anything.
            if resume:
                from ..parallel.journal import resumable_names

                skip = set(resumable_names(output_dir, machines))
                machines = [m for m in machines if m.name not in skip]
            if model_register_dir:
                # read-only shadow of FleetBuilder.build's cache-hit
                # filter (load_cached runs on the coordinator only);
                # probe_cache shares check_cache's validity definition
                from ..builder.build_model import ModelBuilder

                machines = [
                    m
                    for m in machines
                    if ModelBuilder.probe_cache(m, model_register_dir) is None
                ]
        logger.info(
            "Fleet-building %d machines; output at %s%s",
            len(machines),
            output_dir,
            "" if is_coordinator else " (non-coordinator: side effects skipped)",
        )
        builder = FleetBuilder(
            machines,
            plan_strategy=plan_strategy,
            fleet_plan=fleet_plan,
            cost_table=cost_table,
        )
        results = builder.build(
            output_dir if is_coordinator else None,
            model_register_dir=model_register_dir if is_coordinator else None,
            resume=resume,
        )
        if is_coordinator:
            for _, machine_out in results:
                machine_out.report()
        logger.info(
            "Fleet build complete: %d built, %d resumed (skipped), %d failed",
            len(results),
            len(builder.resumed),
            len(builder.build_errors),
        )
        if builder.build_errors:
            # failFast:false — successes are saved/reported above; exit with
            # the first failure's mapped code like a reference builder pod.
            name, exc = next(iter(builder.build_errors.items()))
            raise exc
    except Exception:
        traceback.print_exc()
        exc_type, exc_value, exc_traceback = sys.exc_info()
        exit_code = _exceptions_reporter.exception_exit_code(exc_type)
        if exceptions_reporter_file:
            _exceptions_reporter.safe_report(
                cast(
                    ReportLevel,
                    ReportLevel.get_by_name(
                        exceptions_report_level, ReportLevel.EXIT_CODE
                    ),
                ),
                exc_type,
                exc_value,
                exc_traceback,
                exceptions_reporter_file,
                max_message_len=2024 - 500,
            )
        sys.exit(exit_code)


def _maybe_init_distributed():
    """
    Join the slice-wide jax.distributed mesh when launched as one pod of a
    multi-host fleet-builder Job (env injected by the workflow template).
    """
    import os

    process_count = int(os.getenv("JAX_PROCESS_COUNT", "1"))
    if process_count > 1:
        import jax

        jax.distributed.initialize(
            coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=process_count,
            process_id=int(os.environ["JAX_PROCESS_INDEX"]),
        )
        logger.info(
            "jax.distributed initialized: process %s of %s",
            os.environ["JAX_PROCESS_INDEX"],
            process_count,
        )


@click.command("build-status")
@click.argument("output-dir", envvar="OUTPUT_DIR")
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw build_status.json document instead of the table",
)
@click.option(
    "--watch",
    default=None,
    type=float,
    help="Re-render every N seconds until the build leaves 'running'",
)
def build_status(output_dir: str, as_json: bool, watch: Optional[float]):
    """
    Render the live progress of a fleet build from OUTPUT_DIR's
    ``build_status.json`` heartbeat — the chip-fan-out analog of
    ``argo get``: state, current phase, machine counts with an ETA from
    the completed-machine rate, and the per-phase wall-clock table.

    Works mid-build (the builder atomically replaces the document on
    every phase transition and machine completion), after a crash (the
    last heartbeat survives beside the journal for post-mortems), and
    on finished builds. The model server exposes the same document at
    ``/gordo/v0/<project>/build-status``.
    """
    import time as time_mod

    from ..telemetry import load_status, render_status

    while True:
        doc = load_status(output_dir)
        if doc is None:
            raise click.ClickException(
                f"No build status found in {output_dir} (no fleet build "
                "has written a heartbeat there, or telemetry is disabled)"
            )
        if as_json:
            click.echo(json.dumps(doc, indent=1, sort_keys=True))
        else:
            click.echo(render_status(doc))
        if watch is None or doc.get("state") != "running":
            break
        time_mod.sleep(max(0.1, watch))
        click.echo("")


@click.command("fleet-status")
@click.argument("directory", envvar="OUTPUT_DIR")
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw joined document instead of the table",
)
@click.option(
    "--watch",
    default=None,
    type=float,
    help="Re-render every N seconds (Ctrl-C to stop)",
)
@click.option(
    "--machines",
    "machines",
    default=None,
    help="Per-machine record selection: `all`, `none`, a state "
    "(`healthy`/`degraded`/`drifting`/`quarantined`/`unhealthy`) or a "
    "comma-separated name list. Default: inline while the fleet is "
    "small, summary + top-K offenders past "
    "GORDO_TPU_FLEET_STATUS_MAX_MACHINES.",
)
@click.option(
    "--limit",
    default=None,
    type=int,
    help="Page size for --machines selections (capped at "
    "GORDO_TPU_FLEET_STATUS_MAX_MACHINES)",
)
@click.option(
    "--offset",
    default=0,
    type=int,
    help="Page offset for --machines selections",
)
def fleet_status(
    directory: str,
    as_json: bool,
    watch: Optional[float],
    machines: Optional[str],
    limit: Optional[int],
    offset: int,
):
    """
    The fleet console: ONE joined operator view over DIRECTORY (a build
    output / served revision dir) — build progress
    (``build_status.json``), plan accuracy incl. the measured
    HBM/padding actuals (``fleet_plan.json`` + the health ledger),
    per-member health counts with the unhealthiest machines
    (``fleet_health.json``), lifecycle phase and quarantine records
    (``.lifecycle/state.json``), device memory occupancy and
    compile-cache hit rates.

    The model server answers the same document at
    ``/gordo/v0/<project>/fleet-health`` — point this CLI at the
    artifact volume, or curl the route for a live serving process's
    in-memory view (its device counters see the serving programs).
    """
    import time as time_mod

    from ..stream import stream_plane_section
    from ..telemetry import (
        fleet_status_document,
        render_fleet_status,
        utilization_snapshot,
    )

    if not os.path.isdir(directory):
        raise click.ClickException(f"No such directory: {directory}")
    while True:
        doc = fleet_status_document(
            directory,
            device=utilization_snapshot(),
            # None in a CLI process with no installed plane — the
            # section is injected, never imported by telemetry
            stream=stream_plane_section(),
            machines=machines,
            limit=limit,
            offset=offset,
        )
        if as_json:
            click.echo(json.dumps(doc, indent=1, sort_keys=True, default=str))
        else:
            click.echo(render_fleet_status(doc))
        if watch is None:
            break
        time_mod.sleep(max(0.1, watch))
        click.echo("")


def _parse_since(
    since: Optional[str], last: Optional[str]
) -> Optional[float]:
    """``--since`` (ISO timestamp or epoch seconds) / ``--last``
    (duration like ``90m``/``6h``/``7d``) -> an epoch cutoff."""
    from ..telemetry.aggregate import parse_span_time
    from ..telemetry.slo import parse_duration

    if since and last:
        raise click.ClickException("--since and --last are exclusive")
    if last:
        try:
            return time.time() - parse_duration(last)
        except ValueError as exc:
            raise click.ClickException(str(exc))
    if since:
        try:
            return float(since)
        except ValueError:
            pass
        ts = parse_span_time(since)
        if ts is None:
            raise click.ClickException(
                f"Unparseable --since {since!r} (ISO timestamp or epoch)"
            )
        return ts
    return None


@click.command("trace")
@click.argument("target", envvar="OUTPUT_DIR")
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw analysis document instead of the report",
)
@click.option(
    "--since",
    default=None,
    help="Only analyze spans ending at/after this ISO timestamp (or "
    "epoch seconds); rotated generations older than the cutoff are "
    "skipped without being parsed.",
)
@click.option(
    "--last",
    default=None,
    help="Only analyze the trailing window, e.g. `--last 1h`, `90m`, "
    "`7d` (exclusive with --since).",
)
def trace(target: str, as_json: bool, since: Optional[str], last: Optional[str]):
    """
    Analyze a span trace: per-span latency percentiles, the request
    per-stage breakdown with attribution coverage and the median
    request's critical path, and the top self-time frames the sampling
    profiler collected.

    TARGET is a trace file (``serve_trace.jsonl`` / ``build_trace.jsonl``,
    rotated generations are read automatically) or a directory holding
    one — a serving telemetry dir or a build output dir. Per-worker
    sink variants (``serve_trace-<pid>.jsonl``) are read-merged into
    one analysis per logical trace; with both serve and build traces
    present, each is analyzed in turn.
    """
    from ..telemetry import SERVE_TRACE_FILE
    from ..telemetry.aggregate import sink_window_index
    from ..telemetry.progress import BUILD_TRACE_FILE
    from ..telemetry.trace_analysis import (
        analyze_trace,
        render_analysis,
        trace_bases,
    )

    since_ts = _parse_since(since, last)
    window_index: dict = {}
    if os.path.isdir(target):
        # one analysis per LOGICAL trace: all worker variants of the
        # serve trace merge, ditto the build trace
        groups = [
            bases
            for bases in (
                trace_bases(target, SERVE_TRACE_FILE),
                trace_bases(target, BUILD_TRACE_FILE),
            )
            if bases
        ]
        if since_ts is not None:
            # the rollup manifest records each rotated generation's span
            # window — skip-by-window beats the mtime heuristic (a
            # late-touched old generation still gets skipped)
            window_index = sink_window_index(target)
        if not groups:
            raise click.ClickException(
                f"No {SERVE_TRACE_FILE} or {BUILD_TRACE_FILE} in {target} "
                "(is GORDO_TPU_TELEMETRY_DIR pointed elsewhere, or "
                "telemetry disabled?)"
            )
    elif os.path.exists(target):
        groups = [[target]]
    else:
        raise click.ClickException(f"No such trace file or directory: {target}")

    docs = [
        analyze_trace(group, since_ts=since_ts, window_index=window_index)
        for group in groups
    ]
    if as_json:
        click.echo(
            json.dumps(docs[0] if len(docs) == 1 else docs, indent=1)
        )
        return
    for i, doc in enumerate(docs):
        if i:
            click.echo("")
        click.echo(render_analysis(doc))


@click.group("slo")
def slo_cli():
    """Fleet SLO engine: cross-worker rollups, error budgets, and
    multi-window burn-rate alerts (gordo_tpu.telemetry.slo;
    docs/observability.md "SLOs & error budgets")."""


def _slo_evaluate(directory: str, config_path: Optional[str]):
    from ..telemetry import slo as slo_engine

    if not os.path.isdir(directory):
        raise click.ClickException(f"No such directory: {directory}")
    try:
        config = slo_engine.load_slo_config(directory, path=config_path)
    except (OSError, ValueError) as exc:
        raise click.ClickException(f"Bad SLO config: {exc}")
    try:
        return slo_engine.evaluate(directory, config=config)
    except OSError as exc:
        raise click.ClickException(f"SLO evaluation failed: {exc}")


@slo_cli.command("status")
@click.argument("directory", envvar="GORDO_TPU_TELEMETRY_DIR")
@click.option(
    "--config",
    "config_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="slos.toml to evaluate against (default: GORDO_TPU_SLO_CONFIG, "
    "then DIRECTORY/slos.toml, then the packaged defaults).",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw status document instead of the table",
)
@click.option(
    "--watch",
    default=None,
    type=float,
    help="Re-evaluate and re-render every N seconds (Ctrl-C to stop)",
)
def slo_status(
    directory: str,
    config_path: Optional[str],
    as_json: bool,
    watch: Optional[float],
):
    """
    Evaluate and render the SLO status of DIRECTORY (a telemetry dir or
    build output dir holding trace sinks): per-objective error-budget
    remaining, multi-window burn rates, and every alert's state in the
    pending -> firing -> resolved lifecycle.

    Evaluation is incremental — new spans fold into the persisted
    ``rollups/`` artifacts; re-running over an unchanged corpus reads
    zero span bytes. The model server answers the same document at
    ``/gordo/v0/<project>/slo``.
    """
    from ..telemetry import render_slo_status

    while True:
        doc = _slo_evaluate(directory, config_path)
        if as_json:
            click.echo(json.dumps(doc, indent=1, sort_keys=True, default=str))
        else:
            click.echo(render_slo_status(doc))
        if watch is None:
            break
        time.sleep(max(0.1, watch))
        click.echo("")


@slo_cli.command("check")
@click.argument("directory", envvar="GORDO_TPU_TELEMETRY_DIR")
@click.option(
    "--config",
    "config_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="slos.toml to evaluate against (default resolution as `status`).",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw status document instead of the table",
)
def slo_check(directory: str, config_path: Optional[str], as_json: bool):
    """
    The SLO gate: evaluate DIRECTORY and exit non-zero while any
    burn-rate alert is FIRING (pending and resolved alerts exit 0) —
    mirroring ``bench-check``, so deploy pipelines and cron monitors
    can gate on one command.
    """
    from ..telemetry import render_slo_status

    doc = _slo_evaluate(directory, config_path)
    if as_json:
        click.echo(json.dumps(doc, indent=1, sort_keys=True, default=str))
    else:
        click.echo(render_slo_status(doc))
    if doc.get("firing"):
        raise SystemExit(1)


@click.command("bench-check")
@click.argument("candidate", type=click.Path(exists=True, dir_okay=False))
@click.option(
    "--baseline",
    "baseline_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Baseline bench JSON (default: the committed BENCH_*.json for "
    "the candidate's bench kind, looked up beside the candidate and "
    "then in the current directory).",
)
@click.option(
    "--tolerance",
    "tolerance_scale",
    default=1.0,
    type=float,
    help="Scale every gate tolerance by this factor (2.0 = twice as "
    "lenient; noisy hosts).",
)
@click.option(
    "--report-only",
    is_flag=True,
    help="Always exit 0: print the comparison, never gate (CI visibility "
    "mode).",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw comparison document instead of the report",
)
def bench_check(
    candidate: str,
    baseline_path: Optional[str],
    tolerance_scale: float,
    report_only: bool,
    as_json: bool,
):
    """
    The performance-regression gate: compare a fresh bench run
    (CANDIDATE, a ``BENCH_*.json``-shaped document) against the
    committed baseline for the same bench kind, metric by metric under
    each metric's direction and tolerance, and exit non-zero on any
    regression (unless --report-only).

    Example: ``make bench-route BENCH_ROUTE_OUT=/tmp/fresh.json &&
    gordo-tpu bench-check /tmp/fresh.json``.
    """
    from ..telemetry.benchgate import (
        BASELINE_FILES,
        compare_files,
        render_report,
    )

    if baseline_path is None:
        try:
            with open(candidate) as handle:
                bench = json.load(handle).get("bench")
        except (OSError, ValueError) as exc:
            raise click.ClickException(f"Unreadable candidate: {exc}")
        default_name = BASELINE_FILES.get(str(bench))
        if default_name is None:
            raise click.ClickException(
                f"No default baseline known for bench {bench!r}; "
                "pass --baseline"
            )
        for directory in (
            os.path.dirname(os.path.abspath(candidate)),
            os.getcwd(),
        ):
            probe = os.path.join(directory, default_name)
            if os.path.exists(probe) and os.path.abspath(
                probe
            ) != os.path.abspath(candidate):
                baseline_path = probe
                break
        if baseline_path is None:
            raise click.ClickException(
                f"Committed baseline {default_name} not found beside the "
                "candidate or in the current directory; pass --baseline"
            )

    try:
        report = compare_files(
            baseline_path, candidate, tolerance_scale=tolerance_scale
        )
    except (OSError, ValueError) as exc:
        raise click.ClickException(str(exc))

    if as_json:
        click.echo(json.dumps(report, indent=1, sort_keys=True))
    else:
        click.echo(render_report(report))
    if not report["ok"] and not report_only:
        raise SystemExit(1)


@click.command("lint")
@click.argument("paths", nargs=-1)
@click.option(
    "--root",
    "root",
    default=None,
    type=click.Path(exists=True, file_okay=False),
    help="Repository root the paths (and the baseline) are relative to "
    "(default: the current directory).",
)
@click.option(
    "--baseline",
    "baseline_path",
    default=None,
    type=click.Path(dir_okay=False),
    help="Baseline file of grandfathered findings (default: "
    "<root>/lint_baseline.json; every entry must carry a justification).",
)
@click.option(
    "--update-baseline",
    is_flag=True,
    help="Rewrite the baseline to cover every current finding (each "
    "entry gets a FIXME justification to hand-edit), then exit 0.",
)
@click.option(
    "--report-only",
    is_flag=True,
    help="Always exit 0: print the findings, never gate (CI visibility "
    "mode).",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw lint document instead of the report",
)
@click.option(
    "--sarif",
    "sarif_path",
    default=None,
    type=click.Path(dir_okay=False),
    help="Also write a SARIF 2.1.0 document to this path (rule "
    "metadata, stable fingerprints, baseline entries as suppressions) "
    "— the artifact the CI lint job uploads for PR annotations.",
)
def lint(
    paths: Tuple[str, ...],
    root: Optional[str],
    baseline_path: Optional[str],
    update_baseline: bool,
    report_only: bool,
    as_json: bool,
    sarif_path: Optional[str],
):
    """
    The invariant gate: run the project's static-analysis rules
    (gordo_tpu.analysis — layering arrows, JAX dispatch hazards, the
    env-knob registry, atomic artifact writes, clock discipline,
    Prometheus label cardinality) over PATHS (default: ``gordo_tpu/``)
    and exit non-zero on any finding that is neither suppressed in-file
    (``# gt-lint: disable=<rule>``) nor grandfathered in the committed
    baseline. See ``docs/static-analysis.md`` for the rule catalog.

    Example: ``gordo-tpu lint`` at the repo root — the same invocation
    the CI ``lint`` job and ``make lint-gordo`` run.
    """
    from ..analysis import (
        BaselineError,
        default_baseline_path,
        default_rules,
        lint_document,
        load_baseline,
        render_report,
        run_lint,
        sarif_document,
        split_by_baseline,
        write_baseline,
    )

    root = os.path.abspath(root or os.getcwd())
    if baseline_path is None:
        baseline_path = default_baseline_path(root)
    rules = default_rules()
    result = run_lint(root, rules, paths=list(paths) or None)
    if update_baseline:
        # still-matching entries keep their hand-written justifications;
        # an unreadable existing baseline just means a fresh start
        try:
            existing = load_baseline(baseline_path)
        except BaselineError:
            existing = []
        write_baseline(
            baseline_path,
            result.findings,
            "FIXME: justify this grandfathered finding (lint refuses "
            "unjustified baselines)",
            existing=existing,
        )
        click.echo(
            f"Baseline rewritten with {len(result.findings)} entr"
            f"{'y' if len(result.findings) == 1 else 'ies'} -> "
            f"{baseline_path}; edit the justifications before committing."
        )
        return
    try:
        entries = load_baseline(baseline_path)
    except BaselineError as exc:
        raise click.ClickException(str(exc))
    new, baselined, stale = split_by_baseline(result.findings, entries)
    if sarif_path:
        import gordo_tpu

        doc = sarif_document(
            result,
            new,
            baselined,
            entries=entries,
            rules=rules,
            version=gordo_tpu.__version__,
        )
        tmp = f"{sarif_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as handle:
            json.dump(doc, handle, indent=1, sort_keys=True)
            handle.write("\n")
        os.replace(tmp, sarif_path)
    if as_json:
        click.echo(
            json.dumps(
                lint_document(result, new, baselined, stale),
                indent=1,
                sort_keys=True,
            )
        )
    else:
        click.echo(render_report(result, new, baselined, stale))
    if (new or result.parse_errors) and not report_only:
        raise SystemExit(1)


@click.command("lockgraph")
@click.argument("sinks", nargs=-1, required=True)
@click.option(
    "--top",
    default=10,
    type=int,
    help="Held-while-blocking hotspot rows to report.",
)
@click.option(
    "--report-only",
    is_flag=True,
    help="Always exit 0: print the report, never gate.",
)
@click.option(
    "--as-json",
    "as_json",
    is_flag=True,
    help="Print the raw analysis document instead of the report.",
)
def lockgraph(sinks: Tuple[str, ...], top: int, report_only: bool, as_json: bool):
    """
    Analyze lock-order trace sinks for deadlock potential: build the
    acquisition-ordering graph recorded by ``GORDO_TPU_LOCK_TRACE``
    (``gordo_tpu.analysis.lockgraph``), fail on any ordering cycle —
    two threads taking the same locks in opposite orders — and report
    the max-held-while-blocking hotspots.

    SINKS are edge files (``lock_trace-<pid>.jsonl``) or glob patterns;
    a traced multi-process run merges into one graph.

    Example: ``GORDO_TPU_LOCK_TRACE=1 pytest -m "serve or slo" &&
    gordo-tpu lockgraph 'lock_trace-*.jsonl'``
    """
    import glob as _glob

    from ..analysis.lockgraph import analyze, render_report as render_lock_report

    paths: list = []
    for pattern in sinks:
        matched = sorted(_glob.glob(pattern))
        paths.extend(matched if matched else [pattern])
    missing = [p for p in paths if not os.path.exists(p)]
    if missing or not paths:
        raise click.ClickException(
            "no trace sinks found: "
            + (", ".join(missing) or "(empty sink list)")
            + " — run the suites with GORDO_TPU_LOCK_TRACE set first"
        )
    report = analyze(paths, top=top)
    if as_json:
        click.echo(json.dumps(report, indent=1, sort_keys=True))
    else:
        click.echo(render_lock_report(report))
    if not report["ok"] and not report_only:
        raise SystemExit(1)


@click.command("wait-for-models")
@click.argument("models-dir", envvar="MODELS_DIR")
@click.option(
    "--name",
    "names",
    multiple=True,
    help="Model names to wait for; repeatable. Default: EXPECTED_MODELS env",
)
@click.option("--timeout", default=3600, type=int, envvar="WAIT_TIMEOUT")
@click.option("--poll-interval", default=10, type=int)
def wait_for_models(
    models_dir: str, names: Tuple[str, ...], timeout: int, poll_interval: int
):
    """
    Block until every named model's artifacts exist under MODELS_DIR.

    The plain-k8s stand-in for the reference DAG's step ordering (its
    client/cleanup steps depend on builder steps): replay and
    revision-cleanup Jobs run this in an initContainer so they start only
    after the fleet builders have written the revision.
    """
    import os
    import time as time_mod

    if not names:
        names = tuple(yaml.safe_load(os.getenv("EXPECTED_MODELS", "[]")) or ())
    if not names:
        raise click.ClickException("No model names given (--name / EXPECTED_MODELS)")

    deadline = time_mod.monotonic() + timeout
    missing = set(names)
    while missing:
        missing = {
            name
            for name in missing
            if not os.path.isfile(os.path.join(models_dir, name, "metadata.json"))
        }
        if not missing:
            break
        if time_mod.monotonic() > deadline:
            raise click.ClickException(
                f"Timed out after {timeout}s waiting for models: "
                f"{', '.join(sorted(missing)[:10])}"
            )
        logger.info("Waiting for %d model(s)...", len(missing))
        time_mod.sleep(poll_interval)
    click.echo(f"All {len(names)} models present in {models_dir}")


@click.command("score")
@click.argument("model-dir", type=click.Path(exists=True, file_okay=False))
@click.argument("output", type=click.Path(dir_okay=False, writable=True))
@click.option("--input", "input_path", default=None, type=click.Path(exists=True),
              help="Parquet/CSV of sensor columns to score (overrides --start/--end)")
@click.option("--start", default=None, help="Score window start (ISO timestamp)")
@click.option("--end", default=None, help="Score window end (ISO timestamp)")
@click.option(
    "--anomaly/--predict-only",
    "with_anomaly",
    default=True,
    help="Emit the full anomaly frame (detector models) or raw predictions",
)
def score(
    model_dir: str,
    output: str,
    input_path: Optional[str],
    start: Optional[str],
    end: Optional[str],
    with_anomaly: bool,
):
    """
    Batch-score a data window against a built model, no server needed —
    backfills, migrations, ad-hoc investigations. Data comes from a
    parquet/CSV file (``--input``) or from the machine's own dataset
    config re-pointed at ``--start``/``--end`` (as the replay client
    does). Output is one parquet of the anomaly frame (or raw
    predictions) with pipe-flattened columns, the replay sink's format.

    Long series on a multi-device host score through the ring
    (time-sharded) path automatically: windowed models shard the time
    axis over the mesh past ``GORDO_TPU_RING_PREDICT_ROWS`` rows
    (parallel/sequence.py) — the host never materializes the lookback×
    window blowup of a year-scale backfill.
    """
    import jax
    import pandas as pd

    from .. import serializer
    from ..client.forwarders import flatten_columns
    from ..dataset import GordoBaseDataset

    model = serializer.load(model_dir)
    metadata = serializer.load_metadata(model_dir)

    if input_path:
        if input_path.endswith(".csv"):
            X = pd.read_csv(input_path, index_col=0, parse_dates=True)
        else:
            X = pd.read_parquet(input_path)
        y = X  # file mode carries inputs only; autoencoder semantics
    else:
        if not (start and end):
            raise click.ClickException("Provide --input or both --start/--end")
        dataset_config = dict(metadata.get("dataset") or {})
        if not dataset_config:
            raise click.ClickException(
                "Model metadata carries no dataset config; use --input"
            )
        dataset_config["train_start_date"] = start
        dataset_config["train_end_date"] = end
        # the dataset yields the machine's own targets, so machines with a
        # distinct target_tag_list score against the right columns
        X, y = GordoBaseDataset.from_dict(dataset_config).get_data()

    logger.info("Scoring %d rows on %d device(s)", len(X), len(jax.devices()))
    if with_anomaly and hasattr(model, "anomaly"):
        frame = model.anomaly(X, y)
    else:
        values = model.predict(X)
        index = X.index[len(X) - len(values):]
        frame = pd.DataFrame(
            values, index=index, columns=[str(i) for i in range(values.shape[1])]
        )
    flatten_columns(frame).to_parquet(output)
    click.echo(f"Scored {len(frame)} rows -> {output}")


@click.command("ensure-single-workflow")
@click.argument("models-root", envvar="MODELS_ROOT")
@click.argument("revision", envvar="PROJECT_REVISION")
@click.option(
    "--check-only", is_flag=True, help="Verify the lock without acquiring it"
)
def ensure_single_workflow(models_root: str, revision: str, check_only: bool):
    """
    Single-deployer guard on the shared model volume.

    The reference's ensure-single-workflow Argo step kills OLDER concurrent
    workflows of the same project before deploying
    (argo-workflow.yml.template:47-104). This plane has no k8s API access
    (by design — no kubectl, no RBAC), so the semantics invert: the STALE
    deploy aborts itself. The lock file ``MODELS_ROOT/deploy.lock`` records
    the newest deploying revision (atomic rename); any Job belonging to an
    older revision fails this guard fast instead of interleaving its
    writes with the newer deploy's. Same-revision acquires are idempotent,
    so every shard Job of one deploy guards independently with no
    ordering requirement between them.
    """
    import datetime as datetime_mod
    import os
    import tempfile
    import time as time_mod

    if not str(revision).isdigit():
        raise click.ClickException(f"Revision must be numeric, got {revision!r}")
    os.makedirs(models_root, exist_ok=True)
    lock_path = os.path.join(models_root, "deploy.lock")

    def read_lock() -> str:
        try:
            with open(lock_path) as f:
                lock = json.load(f)
        except FileNotFoundError:
            return ""
        except ValueError:
            logger.warning("Corrupt deploy.lock at %s; overwriting", lock_path)
            return ""
        return str(lock.get("revision", "")) if isinstance(lock, dict) else ""

    def fail_stale(held: str) -> None:
        raise click.ClickException(
            f"A newer deploy (revision {held}) owns {models_root}; "
            f"this deploy (revision {revision}) is stale and must not write"
        )

    if check_only:
        held = read_lock()
        if held.isdigit() and int(held) > int(revision):
            fail_stale(held)
        click.echo(f"Lock check ok for revision {revision} (held: {held or 'none'})")
        return

    # The read-check-replace must not race a concurrent deploy (both could
    # pass the check, then the OLDER one could land its lock last). The
    # guard is a directory that is NEVER empty — acquirers stage
    # ``<unique>/held`` and atomically rename it onto the mutex path —
    # because POSIX rename replaces an EMPTY directory target silently
    # but fails (ENOTEMPTY) on a non-empty one. That one property makes
    # both acquisition (can't steal a live guard) and stale-break
    # restoration (can't clobber a successor's guard) atomic; a crashed
    # holder's stale guard is broken after a timeout (the critical
    # section below is milliseconds long).
    mutex = os.path.join(models_root, ".deploy.guard")

    def _unique(suffix: str) -> str:
        return f"{mutex}.{suffix}-{os.getpid()}-{time_mod.monotonic_ns()}"

    def _remove_guard(path: str) -> None:
        for entry in ("held", ""):
            try:
                os.rmdir(os.path.join(path, entry) if entry else path)
            except OSError:
                pass

    def _try_acquire() -> bool:
        staging = _unique("acquire")
        os.mkdir(staging)
        os.mkdir(os.path.join(staging, "held"))
        try:
            # Fails while ANY guard (always non-empty) sits at the path.
            os.rename(staging, mutex)
            return True
        except OSError:
            _remove_guard(staging)
            return False

    deadline = time_mod.monotonic() + 60
    while not _try_acquire():
        if time_mod.monotonic() > deadline:
            raise click.ClickException(
                f"Could not acquire {mutex} within 60s; if no other "
                "deploy is running, remove the stale directory"
            )
        try:
            age = time_mod.time() - os.stat(mutex).st_mtime
            if age > 300:
                # Break the stale guard via an atomic rename to a unique
                # name: exactly one waiter's rename succeeds, and only
                # that winner may dispose of the condemned dir. The
                # rename may still have caught a guard that was
                # broken-and-reacquired between our stat and our rename
                # (a sub-millisecond window), so the winner re-checks the
                # age of what it actually took: a young guard is handed
                # straight back — and because guards are non-empty, that
                # restore can never overwrite a successor's live guard
                # (rename fails ENOTEMPTY and we release ours instead;
                # a guard stands at the path either way).
                condemned = _unique("stale")
                try:
                    os.rename(mutex, condemned)
                except OSError:
                    pass  # another waiter already broke it
                else:
                    try:
                        renamed_age = (
                            time_mod.time() - os.stat(condemned).st_mtime
                        )
                    except OSError:
                        renamed_age = None
                    if renamed_age is not None and renamed_age <= 300:
                        try:
                            os.rename(condemned, mutex)
                        except OSError:
                            _remove_guard(condemned)
                    else:
                        logger.warning("Broke stale deploy mutex %s", mutex)
                        _remove_guard(condemned)
                continue
        except OSError:
            pass
        time_mod.sleep(0.5)
    try:
        held = read_lock()
        if held.isdigit() and int(held) > int(revision):
            fail_stale(held)
        fd, tmp = tempfile.mkstemp(dir=models_root, prefix=".deploy.lock.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {
                        "revision": str(revision),
                        "acquired_at": datetime_mod.datetime.now(
                            datetime_mod.timezone.utc
                        ).isoformat(),
                    },
                    f,
                )
            os.replace(tmp, lock_path)  # atomic on the shared volume
        except OSError:
            try:
                os.unlink(tmp)
            finally:
                raise
    finally:
        _remove_guard(mutex)
    click.echo(f"Acquired deploy lock for revision {revision}")


@click.command("cleanup-revisions")
@click.argument("models-root", envvar="MODELS_ROOT")
@click.argument("current-revision", envvar="PROJECT_REVISION")
@click.option(
    "--keep",
    default=3,
    type=int,
    help="How many newest revisions to retain (the current one always is)",
)
@click.option("--dry-run", is_flag=True)
def cleanup_revisions(models_root: str, current_revision: str, keep: int, dry_run: bool):
    """
    Delete old model revisions under MODELS_ROOT, keeping the newest
    ``--keep`` plus always the current one.

    The reference cleans stale revisions in its workflow's onExit handler
    by deleting per-revision k8s resources (argo-workflow.yml.template
    onExit section); here revisions are directories on the shared model
    volume, so lifecycle is a filesystem sweep — no k8s API, no RBAC.
    """
    import os
    import shutil

    try:
        entries = sorted(
            (
                entry
                for entry in os.listdir(models_root)
                if os.path.isdir(os.path.join(models_root, entry)) and entry.isdigit()
            ),
            key=int,  # numeric, not lexicographic: '1000' is newer than '999'
        )
    except FileNotFoundError:
        raise click.ClickException(f"No such models root: {models_root}")

    retained = set(entries[-keep:] if keep > 0 else [])
    retained.add(current_revision)
    doomed = [entry for entry in entries if entry not in retained]
    failed = []
    for revision in doomed:
        path = os.path.join(models_root, revision)
        if dry_run:
            click.echo(f"Would delete {path}")
            continue
        logger.info("Deleting old revision %s", path)
        try:
            shutil.rmtree(path)
        except OSError as exc:
            # Surface it: a cleanup Job that silently leaves revisions
            # behind lets the shared volume fill — fail so k8s retries/alerts.
            logger.error("Could not delete %s: %s", path, exc)
            failed.append(revision)
    click.echo(
        f"Revisions: {len(entries) - len(doomed)} kept, "
        f"{len(doomed) - len(failed)} deleted"
        f"{' (dry run)' if dry_run else ''}"
    )
    if failed:
        raise click.ClickException(
            f"Failed to delete {len(failed)} revision(s): {', '.join(failed)}"
        )


@click.group("lifecycle")
def lifecycle_cli():
    """Self-healing fleet lifecycle: drift-triggered incremental
    rebuilds, canary promotion with auto-rollback, zero-downtime
    hot-swap (gordo_tpu.lifecycle; docs/lifecycle.md)."""


def _lifecycle_supervisor(
    collection_dir: str,
    machines_config: Optional[str],
    canary_fraction: Optional[float],
    auto_promote: Optional[bool] = None,
):
    from ..lifecycle import LifecycleConfig, LifecycleSupervisor

    machines = (
        _load_fleet_machines(machines_config) if machines_config else []
    )
    config = LifecycleConfig.from_env()
    if canary_fraction is not None:
        config.canary_fraction = canary_fraction
    if auto_promote is not None:
        config.auto_promote = auto_promote
    return LifecycleSupervisor(machines, collection_dir, config=config)


def _lifecycle_frames(machines) -> dict:
    """One probe window per machine: the machine's own dataset fetch
    (the scoring loop's data plane). Per-machine isolation — a machine
    whose provider is down simply contributes no probe rows this
    cycle."""
    from ..dataset import GordoBaseDataset

    frames = {}
    for machine in machines:
        try:
            dataset = (
                machine.dataset
                if isinstance(machine.dataset, GordoBaseDataset)
                else GordoBaseDataset.from_dict(machine.dataset)
            )
            X, _y = dataset.get_data()
            frames[machine.name] = X
        except Exception as exc:  # noqa: BLE001 - per-machine isolation
            logger.warning("lifecycle probe fetch failed for %s: %r",
                           machine.name, exc)
    return frames


def _echo_cycle(report) -> None:
    click.echo(f"phase: {report.phase}")
    if report.drifted:
        for name, reasons in sorted(report.drifted.items()):
            click.echo(f"  drifted {name}: {'; '.join(reasons)}")
    if report.canary_revision:
        click.echo(f"  canary revision: {report.canary_revision}")
    if report.gate is not None:
        verdict = "PASSED" if report.gate["passed"] else "FAILED"
        click.echo(f"  gates: {verdict}")
        for failure in report.gate["failures"]:
            click.echo(f"    {failure}")
    if report.promoted:
        click.echo(
            f"  promoted (swap {report.details.get('swap_seconds', 0)}s)"
        )
    if report.rolled_back:
        click.echo("  rolled back; serving stays on the last-good revision")


@lifecycle_cli.command("run")
@click.argument("machines-config", envvar="MACHINES_CONFIG")
@click.argument("collection-dir", envvar="MODEL_COLLECTION_DIR")
@click.option(
    "--once", is_flag=True, help="Run a single cycle and exit (cron mode)."
)
@click.option(
    "--interval",
    default=300.0,
    type=click.FloatRange(min=0.0),
    show_default=True,
    help="Seconds between cycles in loop mode.",
)
@click.option(
    "--cycles",
    default=None,
    type=click.IntRange(min=1),
    help="Stop after this many cycles (default: run forever).",
)
@click.option(
    "--canary-fraction",
    default=None,
    type=click.FloatRange(0.0, 1.0, min_open=True),
    help="Traffic slice routed to a canary under evaluation "
    "[GORDO_TPU_CANARY_FRACTION, default 0.25].",
)
@click.option(
    "--auto-promote/--no-auto-promote",
    default=True,
    show_default=True,
    help="Promote automatically when the gates pass; off leaves the "
    "canary serving its slice until `lifecycle promote`.",
)
@click.option(
    "--dry-run",
    is_flag=True,
    help="Observe and report drift only; never rebuild or route.",
)
def lifecycle_run(
    machines_config: str,
    collection_dir: str,
    once: bool,
    interval: float,
    cycles: Optional[int],
    canary_fraction: Optional[float],
    auto_promote: bool,
    dry_run: bool,
):
    """
    Supervise COLLECTION_DIR (a served revision directory): each cycle
    scores every machine's current data through the serving fleet,
    updates per-machine drift statistics, incrementally rebuilds
    members that tripped, canaries the result and promotes (or rolls
    back) through the gates. Crash-safe: state and build journals
    under ``<models root>/.lifecycle`` make every phase resumable.

    Canary/hot-swap ROUTING is per-process (the store is process
    memory): embed the supervisor in the serving process for live
    traffic splitting; a separately-running server picks promotions
    up at its next boot. See docs/lifecycle.md "Deployment model".
    """
    import time as time_mod

    supervisor = _lifecycle_supervisor(
        collection_dir, machines_config, canary_fraction, auto_promote
    )
    try:
        ran = 0
        while True:
            frames = _lifecycle_frames(supervisor.machines)
            if dry_run:
                supervisor.observe(frames)
                verdicts = supervisor.evaluate_drift()
                for name, verdict in sorted(verdicts.items()):
                    status = "DRIFTED" if verdict.drifted else "ok"
                    click.echo(
                        f"{name}: {status} {'; '.join(verdict.reasons)}"
                    )
            else:
                _echo_cycle(supervisor.run_cycle(frames))
            ran += 1
            if once or (cycles is not None and ran >= cycles):
                break
            time_mod.sleep(interval)
    finally:
        supervisor.close()


@lifecycle_cli.command("status")
@click.argument("models-root", envvar="MODELS_ROOT")
@click.option("--as-json", is_flag=True, help="Machine-readable output.")
def lifecycle_status(models_root: str, as_json: bool):
    """The lifecycle state and quarantine record for MODELS_ROOT (the
    directory holding the numbered revision dirs)."""
    from ..lifecycle import LifecycleState

    state = LifecycleState.load(models_root)
    quarantined = state.quarantined()
    if as_json:
        click.echo(
            json.dumps(
                {"state": state.doc, "quarantined": quarantined},
                indent=1,
                sort_keys=True,
                default=str,
            )
        )
        return
    click.echo(f"phase:    {state.phase}")
    click.echo(f"anchor:   {state.anchor_revision}")
    click.echo(f"serving:  {state.serving_revision}")
    click.echo(f"canary:   {state.canary_revision or '-'}")
    if state.stale:
        click.echo(f"stale:    {', '.join(state.stale)}")
    for entry in (state.doc.get("history") or [])[-5:]:
        click.echo(
            f"  {entry.get('event')}: serving={entry.get('serving_revision')}"
            f" canary={entry.get('canary_revision')}"
        )
    click.echo(f"quarantined canaries: {len(quarantined)}")
    for record in quarantined[-3:]:
        click.echo(
            f"  revision {record.get('canary_revision')}: "
            f"{'; '.join(record.get('reasons', [])[:2])}"
        )


@lifecycle_cli.command("promote")
@click.argument("collection-dir", envvar="MODEL_COLLECTION_DIR")
@click.option(
    "--machines-config",
    envvar="MACHINES_CONFIG",
    default=None,
    help="Machine YAML for fetching a probe window (gates need scored "
    "data; without it only --force can promote).",
)
@click.option(
    "--force",
    is_flag=True,
    help="Skip the gates (operator has verified the canary externally).",
)
def lifecycle_promote(
    collection_dir: str, machines_config: Optional[str], force: bool
):
    """Promote the current canary revision into serving."""
    supervisor = _lifecycle_supervisor(collection_dir, machines_config, None)
    try:
        if machines_config and not force:
            supervisor.observe(_lifecycle_frames(supervisor.machines))
        report = supervisor.promote(force=force)
    except RuntimeError as exc:
        raise click.ClickException(str(exc)) from exc
    finally:
        supervisor.close()
    _echo_cycle(report)
    if report.rolled_back:
        raise click.ClickException("gates failed; canary rolled back")


@lifecycle_cli.command("rollback")
@click.argument("collection-dir", envvar="MODEL_COLLECTION_DIR")
@click.option(
    "--reason",
    default="operator rollback",
    show_default=True,
    help="Recorded in the quarantine entry.",
)
def lifecycle_rollback(collection_dir: str, reason: str):
    """Roll back the current canary: drop its traffic slice, quarantine
    it, and keep serving the last-good revision."""
    supervisor = _lifecycle_supervisor(collection_dir, None, None)
    try:
        report = supervisor.rollback(reason)
    except RuntimeError as exc:
        raise click.ClickException(str(exc)) from exc
    finally:
        supervisor.close()
    _echo_cycle(report)


@click.group("perfmodel")
def perfmodel_cli():
    """The learned performance model: fit device-cost regressors from
    telemetry traces, inspect the promoted table, and evaluate learned
    vs analytic accuracy on a corpus."""


@perfmodel_cli.command("fit")
@click.argument("corpus-dir", type=click.Path(exists=True, file_okay=False))
@click.option(
    "--table",
    "table_path",
    default=None,
    type=click.Path(dir_okay=False, writable=True),
    help="The cost_table.json to promote into (default: "
    "GORDO_TPU_PERFMODEL_TABLE, else cost_table.json beside the corpus).",
)
@click.option(
    "--min-samples",
    default=None,
    type=int,
    help="Smallest (target, program) population to fit (default: "
    "GORDO_TPU_PERFMODEL_MIN_SAMPLES).",
)
@click.option(
    "--force",
    is_flag=True,
    help="Install the fit even when it loses the holdout accuracy gate "
    "(the sample floor still applies).",
)
@click.option("--as-json", "as_json", is_flag=True, help="Raw report JSON")
def perfmodel_fit(
    corpus_dir: str,
    table_path: Optional[str],
    min_samples: Optional[int],
    force: bool,
    as_json: bool,
):
    """Harvest CORPUS_DIR's traces (build + serve, rotated generations
    and worker variants merged), fit the per-program regressors, and
    promote them into the cost table IF each beats the analytic model
    and the incumbent on its holdout."""
    from ..perfmodel import fit_and_promote

    report = fit_and_promote(
        corpus_dir,
        table_path=table_path,
        min_samples=min_samples,
        force=force,
    )
    if as_json:
        click.echo(json.dumps(report, indent=1, sort_keys=True))
        return
    corpus = report.get("corpus") or {}
    click.echo(
        f"corpus: {corpus.get('rows', 0)} training row(s) from "
        f"{corpus.get('spans', 0)} span(s) in {corpus_dir}"
    )
    for entry in report.get("models") or []:
        inc = entry.get("incumbent_mae_log")
        click.echo(
            f"  {entry['target']}/{entry['program']}: n={entry['n']} "
            f"holdout={entry['holdout_mae_log']:.4f} "
            f"analytic={entry.get('analytic_mae_log')} "
            f"incumbent={inc if inc is not None else '-'} "
            f"-> {entry['reason']}"
        )
    click.echo(
        f"{'PROMOTED' if report.get('promoted') else 'not promoted'}: "
        f"{report.get('reason')}"
        + (f" ({report.get('table')})" if report.get("promoted") else "")
    )
    if not report.get("promoted") and not (report.get("models") or []):
        # an empty/thin corpus is normal at cold start — say so plainly
        click.echo("the analytic model remains the active fallback")


@perfmodel_cli.command("status")
@click.option(
    "--table",
    "table_path",
    default=None,
    type=click.Path(dir_okay=False),
    help="The cost table to inspect (default: GORDO_TPU_PERFMODEL_TABLE).",
)
@click.option("--as-json", "as_json", is_flag=True, help="Raw status JSON")
def perfmodel_status(table_path: Optional[str], as_json: bool):
    """What the cost table currently carries: calibration factors,
    fitted learned models and their holdout accuracy, corpus identity."""
    from ..perfmodel import default_table_path, section_status

    path = table_path or default_table_path()
    doc = section_status(path)
    if as_json:
        click.echo(json.dumps(doc, indent=1, sort_keys=True))
        return
    click.echo(f"table: {path or '(none; analytic defaults)'}")
    click.echo(
        f"calibrated: {doc['calibrated']}  learned: {doc['learned']}"
    )
    corpus = doc.get("corpus")
    if corpus:
        click.echo(
            f"corpus: {corpus.get('rows')} row(s), "
            f"fingerprint {corpus.get('fingerprint')}"
        )
    for entry in doc["models"]:
        click.echo(
            f"  {entry['target']}/{entry['program']}: n={entry['n']} "
            f"holdout_mae_log={entry['holdout_mae_log']}"
        )
    if not doc["models"]:
        click.echo("no learned models; predictions are analytic")


@perfmodel_cli.command("eval")
@click.argument("corpus-dir", type=click.Path(exists=True, file_okay=False))
@click.option(
    "--table",
    "table_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Evaluate THIS table's learned models (default: "
    "GORDO_TPU_PERFMODEL_TABLE, else cost_table.json beside the corpus).",
)
@click.option("--as-json", "as_json", is_flag=True, help="Raw report JSON")
def perfmodel_eval(
    corpus_dir: str, table_path: Optional[str], as_json: bool
):
    """Score a table's learned models against CORPUS_DIR's measured
    spans — learned vs analytic mean absolute log error per (target,
    program), without fitting or writing anything."""
    from ..perfmodel import default_table_path, harvest_corpus
    from ..perfmodel.model import analytic_prediction, evaluate_rows
    from ..planner.costmodel import load_table_safe

    path = table_path or default_table_path(corpus_dir)
    table = load_table_safe(path)
    rows, stats = harvest_corpus(corpus_dir)
    populations: dict = {}
    for row in rows:
        populations.setdefault((row.target, row.program), []).append(row)
    report = {
        "table": path,
        "corpus": stats,
        "models": [],
    }
    for (target, program), population in sorted(populations.items()):
        learned_mae, learned_n = evaluate_rows(
            population,
            lambda r: table.learned_predict(target, program, r.features),
        )
        analytic_mae, analytic_n = evaluate_rows(
            population,
            lambda r: analytic_prediction(table, target, program, r.features),
        )
        report["models"].append(
            {
                "target": target,
                "program": program,
                "rows": len(population),
                "learned_mae_log": round(learned_mae, 6)
                if learned_n
                else None,
                "learned_scored": learned_n,
                "analytic_mae_log": round(analytic_mae, 6)
                if analytic_n
                else None,
            }
        )
    if as_json:
        click.echo(json.dumps(report, indent=1, sort_keys=True))
        return
    click.echo(
        f"corpus: {len(rows)} row(s); table: "
        f"{path or '(analytic defaults)'}"
    )
    for entry in report["models"]:
        learned = entry["learned_mae_log"]
        click.echo(
            f"  {entry['target']}/{entry['program']}: rows={entry['rows']} "
            f"learned={learned if learned is not None else '-'} "
            f"(scored {entry['learned_scored']}) "
            f"analytic={entry['analytic_mae_log']}"
        )
    if not report["models"]:
        click.echo("no training rows in the corpus")


gordo_tpu_cli.add_command(workflow_cli)
gordo_tpu_cli.add_command(client_cli)
gordo_tpu_cli.add_command(build)
gordo_tpu_cli.add_command(build_fleet)
gordo_tpu_cli.add_command(plan_fleet)
gordo_tpu_cli.add_command(build_status)
gordo_tpu_cli.add_command(fleet_status)
gordo_tpu_cli.add_command(trace)
gordo_tpu_cli.add_command(slo_cli)
gordo_tpu_cli.add_command(bench_check)
gordo_tpu_cli.add_command(lint)
gordo_tpu_cli.add_command(lockgraph)
gordo_tpu_cli.add_command(run_server_cli)
gordo_tpu_cli.add_command(wait_for_models)
gordo_tpu_cli.add_command(score)
gordo_tpu_cli.add_command(ensure_single_workflow)
gordo_tpu_cli.add_command(cleanup_revisions)
gordo_tpu_cli.add_command(lifecycle_cli)
gordo_tpu_cli.add_command(perfmodel_cli)


if __name__ == "__main__":
    gordo_tpu_cli()
