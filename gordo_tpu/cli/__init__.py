from .cli import gordo_tpu_cli

__all__ = ["gordo_tpu_cli"]
