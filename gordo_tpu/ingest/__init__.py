"""
Device-resident ingest: compiled preprocessing plans and raw-column
device transfer.

The subsystem has two halves. :mod:`gordo_tpu.ingest.plan` turns each
served artifact's sklearn scaler pipeline into a composed affine plan
and stacks a spec bucket's plans into device-resident
``[members, features]`` arrays, so preprocessing runs as a fused
prologue inside the gather program instead of as per-request host numpy.
:mod:`gordo_tpu.ingest.transfer` carries decoded wire columns
(:class:`~gordo_tpu.ingest.transfer.RawColumns`) to the device over
dlpack without the legacy ``column_stack`` staging copy, falling back to
the host path whenever the columns or backend refuse.

Layering: this package sits beside ``planner``/``parallel`` — it may be
imported by ``server``/``serve``/``stream`` but never imports them (the
``gordo_tpu/ingest`` arrow in ``analysis/contracts.toml``).

Both halves are independently switchable:

- ``GORDO_TPU_INGEST_COMPILED`` (default on) — compiled plans + fused
  preprocessing prologue; off = every request takes the host sklearn
  walk, exactly the pre-ingest serving path.
- ``GORDO_TPU_INGEST_DLPACK`` (default on) — per-column dlpack device
  transfer; off = host ``column_stack`` staging (the transfer fallback
  rung) while compiled plans stay active. The dlpack rung only engages
  on accelerator backends: on CPU both rungs stage through host memory,
  so the per-column device dispatch is pure overhead and host staging
  IS the fast rung.
"""

from typing import Optional

from gordo_tpu.ingest.plan import (  # noqa: F401
    FleetIngestPlan,
    MemberPlan,
    build_fleet_plan,
    extract_member_plan,
)
from gordo_tpu.ingest.transfer import (  # noqa: F401
    RawColumns,
    ingest_stats,
    reset_ingest_stats,
    to_device,
)
from gordo_tpu.utils.env import env_bool

INGEST_COMPILED_ENV = "GORDO_TPU_INGEST_COMPILED"
INGEST_DLPACK_ENV = "GORDO_TPU_INGEST_DLPACK"


def compiled_enabled() -> bool:
    """Whether serving should compile preprocessing into the fused
    gather program (re-read per request so operators can flip it live)."""
    return env_bool(INGEST_COMPILED_ENV, True)


#: cached once per process — the default backend cannot change after
#: the first device op, so one probe answers every request
_ACCELERATOR: Optional[bool] = None


def _accelerator_backend() -> bool:
    global _ACCELERATOR
    if _ACCELERATOR is None:
        try:
            import jax

            _ACCELERATOR = jax.default_backend() != "cpu"
        except Exception:  # noqa: BLE001 - no backend = host staging
            _ACCELERATOR = False
    return _ACCELERATOR


def dlpack_enabled() -> bool:
    """Whether serving's device transfer should try the per-column
    dlpack rung before the host staging fallback: the env knob is the
    operator kill-switch, and on the CPU backend the rung never engages
    (both rungs stage through host memory there — per-column device
    dispatch is measurably pure overhead, ~10x on the ingest bench).
    Explicit ``to_device(..., dlpack=True)`` callers still get the rung
    on any backend."""
    return env_bool(INGEST_DLPACK_ENV, True) and _accelerator_backend()
