"""
Wire-column → device transfer without the host staging copy.

The legacy decode path materializes a request as ``np.column_stack`` of
the Arrow wire columns — a full host copy of the payload — and only then
hands the matrix to the device program, which copies it AGAIN across the
transfer boundary. :class:`RawColumns` instead carries the decoded wire
columns as-is (zero-copy views straight out of the Arrow buffers) and
:func:`to_device` moves them per-column over the dlpack protocol, so the
first full-matrix materialization happens device-side inside the fused
program's ``stack``. On backends whose dlpack import aliases host
memory (TPU DMA path) that removes the staging copy entirely; the CPU
backend copies on import, so the win there is skipping ``column_stack``
— either way no intermediate host matrix is built.

The fallback ladder is deliberately boring: ANY dlpack failure
(non-contiguous column, unsupported dtype, backend refusal) drops the
whole request to the host path — ``host_matrix()`` + ``jnp.asarray`` —
which is the exact legacy staging behaviour, so parity is structural.
Outcomes are counted module-wide (:func:`ingest_stats`) so benches and
``/fleet-health`` can see which rung actually served traffic.
"""

import threading
from typing import Any, List, Optional, Sequence

import numpy as np

_stats_lock = threading.Lock()
_STATS = {
    "dlpack_transfers": 0,
    "host_transfers": 0,
    "dlpack_columns": 0,
    "fallback_reasons": {},
}


def _note_transfer(dlpack: bool, columns: int = 0, reason: str = "") -> None:
    with _stats_lock:
        if dlpack:
            _STATS["dlpack_transfers"] += 1
            _STATS["dlpack_columns"] += columns
        else:
            _STATS["host_transfers"] += 1
            if reason:
                reasons = _STATS["fallback_reasons"]
                reasons[reason] = reasons.get(reason, 0) + 1


def ingest_stats() -> dict:
    """Process-wide transfer counters: how many requests went over
    dlpack vs the host staging path, and why the host path was taken."""
    with _stats_lock:
        return {
            "dlpack_transfers": _STATS["dlpack_transfers"],
            "host_transfers": _STATS["host_transfers"],
            "dlpack_columns": _STATS["dlpack_columns"],
            "fallback_reasons": dict(_STATS["fallback_reasons"]),
        }


def reset_ingest_stats() -> None:
    with _stats_lock:
        _STATS["dlpack_transfers"] = 0
        _STATS["host_transfers"] = 0
        _STATS["dlpack_columns"] = 0
        _STATS["fallback_reasons"] = {}


class RawColumns:
    """A request payload still in wire form: per-feature columns in
    model-tag order, not yet stacked into a matrix.

    Built from decoded Arrow columns (zero-copy buffer views) or, for
    JSON/fallback requests, from an existing matrix (``matrix`` mode —
    already staged, nothing to save, but it lets every caller speak one
    payload type). ``host_matrix()`` is the escape hatch back to the
    legacy staged ``float32`` matrix and is lazy: the raw-column fast
    path never pays for it.

    >>> raw = RawColumns.from_columns(
    ...     [np.array([1.0, 2.0]), np.array([3.0, 4.0])])
    >>> raw.rows, raw.width
    (2, 2)
    >>> raw.host_matrix().shape
    (2, 2)
    """

    __slots__ = ("columns", "matrix", "rows", "width", "_host")

    def __init__(
        self,
        columns: Optional[Sequence[np.ndarray]],
        matrix: Optional[np.ndarray],
        rows: int,
        width: int,
    ):
        self.columns = tuple(columns) if columns is not None else None
        self.matrix = matrix
        self.rows = int(rows)
        self.width = int(width)
        self._host: Optional[np.ndarray] = None

    @classmethod
    def from_columns(cls, columns: Sequence[np.ndarray]) -> "RawColumns":
        cols = [np.asarray(col) for col in columns]
        rows = len(cols[0]) if cols else 0
        return cls(cols, None, rows, len(cols))

    @classmethod
    def from_matrix(cls, matrix: Any) -> "RawColumns":
        mat = np.asarray(matrix)
        return cls(None, mat, mat.shape[0], mat.shape[1] if mat.ndim > 1 else 1)

    def host_matrix(self) -> np.ndarray:
        """The legacy staged matrix (``float32``, C-order), built at most
        once."""
        if self._host is None:
            if self.matrix is not None:
                self._host = np.ascontiguousarray(self.matrix, np.float32)
            else:
                self._host = np.column_stack(
                    [np.asarray(col, np.float32) for col in self.columns]
                )
        return self._host

    @property
    def nbytes(self) -> int:
        if self.columns is not None:
            return int(sum(col.nbytes for col in self.columns))
        return int(self.matrix.nbytes)


def _dlpack_column(col: np.ndarray) -> Any:
    """One wire column onto the device via dlpack, as float32. Raises on
    anything the protocol can't take (caller falls back)."""
    import jax
    import jax.numpy as jnp

    arr = np.asarray(col)
    if arr.dtype != np.float32:
        # dlpack moves bytes, not values: cast (a copy) first. Arrow f64
        # wires land here; the compiled path computes f32 regardless.
        arr = np.ascontiguousarray(arr, np.float32)
    elif not arr.flags["C_CONTIGUOUS"]:
        raise ValueError("non-contiguous wire column")
    out = jax.dlpack.from_dlpack(arr)
    if out.dtype != jnp.float32:  # pragma: no cover - cast path above
        out = out.astype(jnp.float32)
    return out


def to_device(
    raw: RawColumns,
    padded_rows: Optional[int] = None,
    dlpack: bool = True,
) -> Any:
    """``raw`` as a ``[rows, width]`` (or ``[padded_rows, width]``)
    float32 device array.

    Fast rung: each wire column crosses via dlpack and the matrix is
    first assembled device-side (``jnp.stack(axis=1)``); row padding, if
    any, happens on device too. Fallback rung (``dlpack=False``, a
    padding-incompatible shape, or any dlpack refusal): the legacy host
    staging — ``host_matrix()`` zero-padded on host, one ``jnp.asarray``
    transfer. Both rungs return the same values; only the copy count
    differs.
    """
    import jax.numpy as jnp

    rows = raw.rows
    target = padded_rows if padded_rows is not None else rows
    if dlpack and raw.columns is not None and raw.width > 0 and rows > 0:
        try:
            device_cols: List[Any] = [
                _dlpack_column(col) for col in raw.columns
            ]
            X = jnp.stack(device_cols, axis=1)
            if target != rows:
                X = jnp.zeros((target, raw.width), jnp.float32).at[:rows].set(X)
            _note_transfer(True, columns=raw.width)
            return X
        except Exception as exc:  # noqa: BLE001 - any refusal = host rung
            _note_transfer(False, reason=type(exc).__name__)
    else:
        reason = "disabled" if not dlpack else "no_columns"
        _note_transfer(False, reason=reason)
    host = raw.host_matrix()
    if target != rows:
        padded = np.zeros((target, raw.width), np.float32)
        padded[:rows] = host
        host = padded
    return jnp.asarray(host)
