"""
Compiled preprocessing plans: the host pipeline's scaler math as device
arrays.

The serving artifacts wrap their estimator in (optionally) an sklearn
``Pipeline`` whose leading steps are fitted scalers. The host serving
path replays those steps per request (``fleet_store._host_transform``):
an object-graph walk plus one float64 numpy pass per transformer per
member — pure host work sitting between the wire decode and the fused
device program. Every stock scaler is an *affine* map, and a chain of
affine maps composes into ONE ``X * scale + offset``; this module
extracts that composition per member and stacks it across a spec bucket
into device-resident ``[members, features]`` arrays, so the whole
preprocessing pipeline runs as a fused prologue INSIDE the gather
program (``fleet_store.fleet_forward_gather``).

Anything that is not provably affine — a custom transformer, a
row-count-changing step, ``MinMaxScaler(clip=True)`` — answers ``None``
and the caller keeps the host path (the fallback ladder in
``docs/serving.md``); supported scalers are matched by EXACT type so a
subclass with an overridden ``transform`` can never be silently
mis-compiled.

Numerics: the compiled prologue computes in float32 on device while the
host pipeline runs float64 then casts — results agree to float32
round-off (the parity tests pin this at tolerance), except for the
**identity** plan (no transformer steps — the common bare-estimator
artifact), which skips the multiply-add entirely and is bit-identical
to the host path by construction.
"""

import logging
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class MemberPlan:
    """One member's composed affine pipeline: ``f(X) = X * scale + offset``
    (both ``[n_features]`` float32). ``identity`` marks the no-op plan
    (no transformer steps), which callers must apply by NOT applying it —
    skipping the multiply-add keeps the compiled path bit-identical to
    the host path for bare-estimator artifacts.

    >>> plan = MemberPlan(np.ones(2, np.float32), np.zeros(2, np.float32), True)
    >>> plan.identity
    True
    """

    __slots__ = ("scale", "offset", "identity")

    def __init__(self, scale: np.ndarray, offset: np.ndarray, identity: bool):
        self.scale = scale
        self.offset = offset
        self.identity = identity


def _affine_of(transformer: Any) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """``(scale, offset)`` such that ``transform(X) == X * scale + offset``,
    or None when this transformer is not provably affine. Exact-type
    dispatch only — a subclass may override ``transform``."""
    try:
        from sklearn.preprocessing import (
            MaxAbsScaler,
            MinMaxScaler,
            RobustScaler,
            StandardScaler,
        )
    except ImportError:  # pragma: no cover - sklearn is a hard dep today
        return None

    kind = type(transformer)
    try:
        if kind is MinMaxScaler:
            if getattr(transformer, "clip", False):
                return None  # clip is not affine
            return (
                np.asarray(transformer.scale_, dtype=np.float64),
                np.asarray(transformer.min_, dtype=np.float64),
            )
        if kind is StandardScaler:
            scale = (
                np.asarray(transformer.scale_, dtype=np.float64)
                if getattr(transformer, "with_std", True)
                and transformer.scale_ is not None
                else None
            )
            mean = (
                np.asarray(transformer.mean_, dtype=np.float64)
                if getattr(transformer, "with_mean", True)
                and transformer.mean_ is not None
                else None
            )
            s = 1.0 / scale if scale is not None else np.asarray(1.0)
            o = -(mean * s) if mean is not None else np.asarray(0.0)
            return np.asarray(s), np.asarray(o)
        if kind is MaxAbsScaler:
            return (
                1.0 / np.asarray(transformer.scale_, dtype=np.float64),
                np.asarray(0.0),
            )
        if kind is RobustScaler:
            scale = (
                np.asarray(transformer.scale_, dtype=np.float64)
                if getattr(transformer, "with_scaling", True)
                and transformer.scale_ is not None
                else None
            )
            center = (
                np.asarray(transformer.center_, dtype=np.float64)
                if getattr(transformer, "with_centering", True)
                and transformer.center_ is not None
                else None
            )
            s = 1.0 / scale if scale is not None else np.asarray(1.0)
            o = -(center * s) if center is not None else np.asarray(0.0)
            return np.asarray(s), np.asarray(o)
    except AttributeError:
        return None  # unfitted scaler: nothing to compile
    return None


def _pipeline_steps(model: Any) -> List[Any]:
    """The transformer steps ahead of the estimator, through the same
    unwrapping ``fleet_store._host_transform`` does (detector →
    ``base_estimator`` → ``Pipeline.steps[:-1]``)."""
    obj = model
    base = getattr(obj, "base_estimator", None)
    if base is not None:
        obj = base
    steps = getattr(obj, "steps", None)
    if steps:
        return [transformer for _, transformer in steps[:-1]]
    return []


def extract_member_plan(model: Any, n_features: int) -> Optional[MemberPlan]:
    """The composed affine plan for one served model, or None when any
    pipeline step is not provably affine (the host-fallback cue).

    Composition order matches the pipeline's sequential transform: with
    accumulated ``X*s1+o1`` followed by step ``(s2, o2)``, the result is
    ``X*(s1*s2) + (o1*s2 + o2)``.
    """
    transformers = _pipeline_steps(model)
    if not transformers:
        return MemberPlan(
            np.ones(n_features, np.float32),
            np.zeros(n_features, np.float32),
            identity=True,
        )
    scale = np.ones(n_features, np.float64)
    offset = np.zeros(n_features, np.float64)
    for transformer in transformers:
        affine = _affine_of(transformer)
        if affine is None:
            return None
        s, o = affine
        try:
            s = np.broadcast_to(s, (n_features,))
            o = np.broadcast_to(o, (n_features,))
        except ValueError:
            # a width-changing step (feature selection) is not a plan
            return None
        scale = scale * s
        offset = offset * s + o
    return MemberPlan(
        np.asarray(scale, np.float32), np.asarray(offset, np.float32),
        identity=False,
    )


class FleetIngestPlan:
    """A spec bucket's stacked preprocessing plan, device-resident.

    ``scale``/``offset`` are ``[members, features]`` float32 device
    arrays aligned row-for-row with the bucket's stacked parameters
    (same sorted-name order), so the fused gather program indexes them
    with the SAME ``indices`` it gathers member params with;
    ``host_scale``/``host_offset`` keep the numpy originals for callers
    that apply the plan host-side (the fleet route's vectorized staging)
    without a device→host sync. For the all-identity bucket all four are
    None (``identity`` True, zero resident bytes): callers run the
    existing un-prologued program, keeping the compiled path
    bit-identical to the host path for bare-estimator fleets.
    """

    __slots__ = (
        "names",
        "scale",
        "offset",
        "host_scale",
        "host_offset",
        "identity",
        "nbytes",
    )

    def __init__(
        self,
        names: Sequence[str],
        scale: Optional[Any],
        offset: Optional[Any],
        identity: bool,
        host_scale: Optional[np.ndarray] = None,
        host_offset: Optional[np.ndarray] = None,
    ):
        self.names = list(names)
        self.scale = scale
        self.offset = offset
        self.host_scale = host_scale
        self.host_offset = host_offset
        self.identity = identity
        self.nbytes = (
            0
            if identity
            else int(scale.size + offset.size) * 4  # float32 leaves
        )


def build_fleet_plan(
    members: Sequence[Tuple[str, Any]], n_features: int
) -> Optional[FleetIngestPlan]:
    """The stacked :class:`FleetIngestPlan` for one spec bucket
    (``members`` in bucket order), or None when ANY member's pipeline is
    not compilable — plans are all-or-nothing per bucket, so a fused
    batch never mixes compiled and host-transformed riders."""
    import jax

    plans: List[MemberPlan] = []
    for name, model in members:
        plan = extract_member_plan(model, n_features)
        if plan is None:
            logger.debug(
                "ingest plan: %s has a non-affine pipeline; bucket keeps "
                "the host transform path",
                name,
            )
            return None
        plans.append(plan)
    if not plans:
        return None
    names = [name for name, _ in members]
    if all(plan.identity for plan in plans):
        return FleetIngestPlan(names, None, None, identity=True)
    host_scale = np.stack([plan.scale for plan in plans])
    host_offset = np.stack([plan.offset for plan in plans])
    return FleetIngestPlan(
        names,
        jax.device_put(host_scale),
        jax.device_put(host_offset),
        identity=False,
        host_scale=host_scale,
        host_offset=host_offset,
    )
