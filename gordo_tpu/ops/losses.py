"""Loss functions over batches with sample weights (pure JAX)."""

from typing import Callable

import jax.numpy as jnp


def _per_sample_mse(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.square(pred - target), axis=-1)


def _per_sample_mae(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.abs(pred - target), axis=-1)


_LOSSES = {
    "mse": _per_sample_mse,
    "mean_squared_error": _per_sample_mse,
    "mae": _per_sample_mae,
    "mean_absolute_error": _per_sample_mae,
}


def resolve_loss(name: str) -> Callable:
    """
    Per-sample loss fn for a Keras-style loss name.

    >>> import jax.numpy as jnp
    >>> fn = resolve_loss("mse")
    >>> float(fn(jnp.array([[1.0, 1.0]]), jnp.array([[0.0, 0.0]]))[0])
    1.0
    """
    try:
        return _LOSSES[name]
    except KeyError:
        raise ValueError(f"Unknown loss {name!r}; known: {sorted(_LOSSES)}")


def weighted_mean_loss(
    per_sample: jnp.ndarray, weights: jnp.ndarray
) -> jnp.ndarray:
    """
    Weighted mean of per-sample losses; weights zero out padding rows.
    An all-zero weight vector yields NaN — "no data" must be
    distinguishable from "zero loss" (a fleet member without validation
    rows would otherwise report a perfect val_loss of 0.0).
    """
    total = jnp.sum(weights)
    mean = jnp.sum(per_sample * weights) / jnp.maximum(total, 1.0)
    return jnp.where(total > 0, mean, jnp.nan)
