from .activations import resolve_activation
from .windows import model_offset, num_windows, sliding_windows, window_targets

__all__ = [
    "resolve_activation",
    "sliding_windows",
    "window_targets",
    "num_windows",
    "model_offset",
]
