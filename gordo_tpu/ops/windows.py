"""
Sliding-window construction for sequence models — the windowing contract the
whole framework's "model offset" rides on.

Semantics parity with the reference's ``create_keras_timeseriesgenerator``
(gordo/machine/model/models.py:713-793), which pads/shifts so that for
lookback L and lookahead ``la``:

- sample ``k`` sees window ``X[k : k+L]`` and targets ``y[k + L + la - 1]``
- sample count is ``n - L - la + 1``
- model output is shorter than input by ``L + la - 1`` (the *model offset*
  threaded through builder metadata, scoring, and server alignment)

These are pure functions over arrays: under ``jit`` the gather lowers to one
XLA gather; the fleet trainer vmaps them over the model axis.
"""

from typing import Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

Array = Union[np.ndarray, jnp.ndarray]


def num_windows(n_samples: int, lookback: int, lookahead: int) -> int:
    """
    Number of (window, target) samples a series of length ``n_samples``
    yields.

    >>> num_windows(100, 20, 0)
    81
    >>> num_windows(100, 20, 1)
    80
    """
    return n_samples - lookback - lookahead + 1


def model_offset(lookback: int, lookahead: int) -> int:
    """
    How many rows shorter than its input the model output is.

    >>> model_offset(20, 0), model_offset(20, 1)
    (19, 20)
    """
    return lookback + lookahead - 1


def sliding_windows(X: Array, lookback: int, lookahead: int = 0) -> Array:
    """
    All length-``lookback`` windows of ``X`` usable with the given lookahead:
    shape ``[num_windows, lookback, n_features]``.

    >>> import numpy as np
    >>> X = np.arange(10).reshape(5, 2)
    >>> w = sliding_windows(X, lookback=2, lookahead=0)
    >>> w.shape
    (4, 2, 2)
    >>> w[0].tolist()
    [[0, 1], [2, 3]]
    """
    n = X.shape[0]
    count = num_windows(n, lookback, lookahead)
    if count <= 0:
        raise ValueError(
            f"Series of length {n} too short for lookback={lookback}, "
            f"lookahead={lookahead}"
        )
    xp = jnp if isinstance(X, jnp.ndarray) else np
    idx = xp.arange(count)[:, None] + xp.arange(lookback)[None, :]
    return X[idx]


def window_targets(y: Array, lookback: int, lookahead: int = 0) -> Array:
    """
    Targets aligned with :func:`sliding_windows`: ``y[k + lookback +
    lookahead - 1]`` for each window ``k``.

    >>> import numpy as np
    >>> y = np.arange(5)
    >>> window_targets(y, lookback=2, lookahead=0).tolist()
    [1, 2, 3, 4]
    >>> window_targets(y, lookback=2, lookahead=1).tolist()
    [2, 3, 4]
    """
    n = y.shape[0]
    count = num_windows(n, lookback, lookahead)
    start = lookback + lookahead - 1
    return y[start : start + count]


def windowed_dataset(
    X: Array, y: Optional[Array], lookback: int, lookahead: int = 0
) -> Tuple[Array, Optional[Array]]:
    """Convenience: (windows, aligned targets)."""
    windows = sliding_windows(X, lookback, lookahead)
    targets = window_targets(y, lookback, lookahead) if y is not None else None
    return windows, targets
