"""
Pallas TPU kernel: the fleet feedforward-AE batch as ONE fused kernel.

The serving hot loop (reference call stack §3.3: ``model.anomaly`` →
``self.predict(X)``, gordo/machine/model/anomaly/diff.py:310-458) for a
feedforward AE is a stack of small dense layers. Model dims are tiny
(hourglass of a ~20-tag asset), so when a fleet of M models scores a batch
at once, XLA's batched-matmul path emits one kernel per layer and streams
the [M, B, hidden] activations through HBM between them. This kernel
instead walks the whole stack for one model per grid step with every
activation resident in VMEM: grid = (M,), each step loads the model's
weights + its row block, applies all L layers and the output head, and
writes only the final reconstruction back to HBM.

The layer walk is unrolled at trace time from the spec (static), so the
kernel is recompiled per architecture — exactly like the XLA path, which
is cached per (spec, shape) too.

CPU tests run with ``interpret=True`` (no TPU needed); numerical parity
with :func:`gordo_tpu.models.nn.forward_feedforward` is asserted in
tests/ops/test_pallas_dense.py.
"""

from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU-only installs too, but guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

from ..models.spec import FeedForwardSpec
from .activations import resolve_activation

Params = Dict[str, Dict[str, jnp.ndarray]]


def _layer_names(spec: FeedForwardSpec) -> List[Tuple[str, str]]:
    """[(param key, activation name), ...] in forward order."""
    names = [(f"dense_{i}", spec.activations[i]) for i in range(len(spec.dims))]
    names.append(("out", spec.out_activation))
    return names


# Row-block size of the batch grid axis. Bounds VMEM residency per grid
# step to ~BLOCK_B × max(width) activations regardless of request size —
# without it a large B (e.g. a year of 10-min rows ≈ 52k) would try to
# hold the whole [B, F] block in VMEM and fail to compile.
BLOCK_B = 512


def fleet_feedforward_pallas(
    spec: FeedForwardSpec,
    stacked_params: Params,
    X: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """
    Fused forward for a stacked fleet: ``X[M, B, F] -> [M, B, F_out]``.

    ``stacked_params`` is the fleet pytree (leading model axis on every
    leaf), as produced by ``parallel.fleet.stack_member_params``.

    Semantically identical to ``vmap(forward_feedforward)`` without the
    activity-penalty output (inference only). The grid is (models,
    row-blocks): each step walks the whole layer stack for one model's
    ``BLOCK_B`` rows with activations resident in VMEM.
    """
    names = _layer_names(spec)
    M, B, F = X.shape
    f_out = spec.n_features_out

    block_b = min(B, BLOCK_B)
    b_pad = -(-B // block_b) * block_b
    if b_pad != B:
        X = jnp.pad(X, ((0, 0), (0, b_pad - B), (0, 0)))

    # Flatten params into the pallas_call argument list, layer order.
    # Biases ride as [M, 1, d_out]: a (1, d_out) block of an [M, d_out]
    # array violates the TPU tiling rule (second-to-last block dim must
    # divide 8 or equal the array dim); a trailing-(1, d_out) block of an
    # [M, 1, d_out] array satisfies it exactly.
    flat: List[jnp.ndarray] = []
    for key, _ in names:
        flat.append(stacked_params[key]["W"])
        flat.append(stacked_params[key]["b"][:, None, :])

    def kernel(x_ref, *refs):
        out_ref = refs[-1]
        param_refs = refs[:-1]
        h = x_ref[0]  # [block_b, F] this model's row block, in VMEM
        for li, (_, act_name) in enumerate(names):
            w = param_refs[2 * li][0]  # [d_in, d_out]
            b = param_refs[2 * li + 1][0, 0]  # [d_out]
            h = jnp.dot(h, w, preferred_element_type=jnp.float32) + b
            h = resolve_activation(act_name)(h)
        out_ref[0] = h

    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    in_specs = [pl.BlockSpec((1, block_b, F), lambda m, bi: (m, bi, 0), **mem)]
    for key, _ in names:
        w = stacked_params[key]["W"]
        d_in, d_out = w.shape[-2], w.shape[-1]
        in_specs.append(pl.BlockSpec((1, d_in, d_out), lambda m, bi: (m, 0, 0), **mem))
        in_specs.append(pl.BlockSpec((1, 1, d_out), lambda m, bi: (m, 0, 0), **mem))

    out = pl.pallas_call(
        kernel,
        grid=(M, b_pad // block_b),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_b, f_out), lambda m, bi: (m, bi, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((M, b_pad, f_out), jnp.float32),
        interpret=interpret,
    )(X.astype(jnp.float32), *flat)
    return out[:, :B]


@partial(jax.jit, static_argnums=(0,), static_argnames=("interpret",))
def fleet_anomaly_scores_pallas(
    spec: FeedForwardSpec,
    stacked_params: Params,
    X: jnp.ndarray,
    y: jnp.ndarray,
    *,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """
    Fused fleet scoring: ``(reconstruction[M, B, F_out], mse[M, B])``.

    The per-row mean-squared error is the ``total-anomaly-unscaled``
    column of the anomaly response (diff.py:387-415 semantics); the
    reconstruction feeds the ``model-output`` columns.
    """
    out = fleet_feedforward_pallas(spec, stacked_params, X, interpret=interpret)
    err = ((out - y.astype(jnp.float32)) ** 2).mean(axis=-1)
    return out, err
