"""Activation-name resolution (Keras-style names → jax.nn functions)."""

from typing import Callable, Union

import jax
import jax.numpy as jnp


def _linear(x):
    return x


_ACTIVATIONS = {
    "linear": _linear,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "leaky_relu": jax.nn.leaky_relu,
    "relu6": jax.nn.relu6,
    "exponential": jnp.exp,
    "softmax": jax.nn.softmax,
}


def resolve_activation(activation: Union[str, Callable]) -> Callable:
    """
    Map a Keras-style activation name to its jax.nn function.

    >>> resolve_activation("tanh") is jnp.tanh
    True
    >>> resolve_activation("linear")(2.0)
    2.0
    """
    if callable(activation):
        return activation
    try:
        return _ACTIVATIONS[activation]
    except KeyError:
        raise ValueError(
            f"Unknown activation {activation!r}; known: {sorted(_ACTIVATIONS)}"
        )
