"""
Live object graph → config definition (inverse of ``from_definition``).

Behavior parity with gordo/serializer/into_definition.py:12-190: walk
``get_params(deep=False)``, honor an object's ``into_definition`` hook,
unwrap (name, step) tuples from Pipeline/FeatureUnion params, and turn bare
functions into their dotted import path. The round trip
``into_definition(from_definition(d))`` freezes an estimator's defaults into
the definition (used by the CLI before building — cli/cli.py:142-144).
"""

import logging
from inspect import isclass, isfunction
from typing import Any, Dict

logger = logging.getLogger(__name__)


def _location_of(obj_type: type) -> str:
    return f"{obj_type.__module__}.{obj_type.__name__}"


def into_definition(pipeline, prune_default_params: bool = False) -> Dict[str, Any]:
    """
    Convert an estimator / pipeline into its YAML-able definition.

    Example
    -------
    >>> from sklearn.pipeline import Pipeline
    >>> from sklearn.decomposition import PCA
    >>> definition = into_definition(Pipeline([("pca", PCA(n_components=2))]))
    >>> list(definition)
    ['sklearn.pipeline.Pipeline']
    """
    return _decompose_node(pipeline, prune_default_params)


def _decompose_node(obj: Any, prune_default_params: bool = False) -> Any:
    if hasattr(obj, "into_definition"):
        return {_location_of(type(obj)): obj.into_definition()}

    if isfunction(obj):
        return f"{obj.__module__}.{obj.__name__}"

    if isclass(obj):
        return _location_of(obj)

    if isinstance(obj, (list, tuple)):
        # A (name, step) tuple from Pipeline.steps keeps only the step; plain
        # sequences decompose element-wise.
        if (
            isinstance(obj, tuple)
            and len(obj) == 2
            and isinstance(obj[0], str)
            and hasattr(obj[1], "get_params")
        ):
            return _decompose_node(obj[1], prune_default_params)
        return [_decompose_node(item, prune_default_params) for item in obj]

    if hasattr(obj, "get_params"):
        params = obj.get_params(deep=False)
        if prune_default_params:
            params = _prune_default_params(obj, params)
        definition = {
            name: _decompose_node(value, prune_default_params)
            if _needs_decomposition(value)
            else value
            for name, value in params.items()
        }
        return {_location_of(type(obj)): definition}

    return obj


def _needs_decomposition(value: Any) -> bool:
    if hasattr(value, "get_params") or hasattr(value, "into_definition"):
        return True
    if isfunction(value) or isclass(value):
        return True
    if isinstance(value, (list, tuple)):
        return any(_needs_decomposition(item) for item in value)
    return False


def _prune_default_params(obj: Any, params: Dict[str, Any]) -> Dict[str, Any]:
    """Drop params whose value equals the constructor default."""
    import inspect

    try:
        sig = inspect.signature(type(obj).__init__)
    except (TypeError, ValueError):
        return params
    pruned = {}
    for name, value in params.items():
        param = sig.parameters.get(name)
        if param is not None and param.default is not inspect.Parameter.empty:
            try:
                if param.default == value:
                    continue
            except Exception:
                pass
        pruned[name] = value
    return pruned
