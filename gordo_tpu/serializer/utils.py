"""Typing helpers for the serializer (reference: gordo/serializer/utils.py)."""

import typing
from typing import Any


def _unpack_optional(annotation: Any):
    origin = typing.get_origin(annotation)
    if origin is typing.Union:
        return [arg for arg in typing.get_args(annotation) if arg is not type(None)]
    return [annotation]


def is_tuple_type(annotation: Any) -> bool:
    """
    True when ``annotation`` is a tuple type, including ``Optional[Tuple]``
    and ``Union[..., Tuple, ...]`` forms.

    >>> from typing import Tuple, Optional, Union
    >>> is_tuple_type(Tuple[int, ...])
    True
    >>> is_tuple_type(Optional[Tuple[int, int]])
    True
    >>> is_tuple_type(Union[str, tuple])
    True
    >>> is_tuple_type(int)
    False
    """
    if annotation is tuple:
        return True
    for candidate in _unpack_optional(annotation):
        if candidate is tuple:
            return True
        origin = typing.get_origin(candidate)
        if origin is tuple:
            return True
    return False
