"""
Dotted-path → object resolution: the primitive under the whole config
language.

Reference parity: gordo-core's ``import_utils.import_location`` (consumed at
gordo/serializer/from_definition.py:16 and throughout); not vendored in the
reference snapshot, so re-derived from its call sites: accepts
``package.module.Attribute`` (and ``package.module:Attribute``), imports the
module, returns the attribute.
"""

import importlib
from typing import Any


def import_location(import_path: str) -> Any:
    """
    Import and return the object at ``import_path``.

    Both ``a.b.Class`` and ``a.b:Class`` forms are accepted. Raises
    ``ImportError`` when the module can't be imported and ``ValueError`` when
    the path is malformed or the attribute is missing.

    Examples
    --------
    >>> import_location("collections.OrderedDict").__name__
    'OrderedDict'
    """
    if not isinstance(import_path, str) or not import_path:
        raise ValueError(f"Invalid import path: {import_path!r}")

    if ":" in import_path:
        module_path, _, attr_path = import_path.partition(":")
        if not module_path or not attr_path:
            raise ValueError(f"Invalid import path: {import_path!r}")
        module = importlib.import_module(module_path)
    else:
        parts = import_path.split(".")
        if len(parts) < 2:
            raise ValueError(
                f"Import path must contain a module and attribute: {import_path!r}"
            )
        module_path, attr_path = ".".join(parts[:-1]), parts[-1]
        try:
            module = importlib.import_module(module_path)
        except ImportError:
            # The penultimate element may itself be an attribute (e.g. a class
            # with a nested attribute); fall back one level.
            if len(parts) < 3:
                raise
            module = importlib.import_module(".".join(parts[:-2]))
            attr_path = ".".join(parts[-2:])

    obj = module
    for attr in attr_path.split("."):
        try:
            obj = getattr(obj, attr)
        except AttributeError as e:
            raise ValueError(f"Could not resolve {import_path!r}: {e}")
    return obj


def prepare_back_compatible_locations(location: str, aliases: dict) -> str:
    """Map a legacy/reference import path onto its gordo-tpu equivalent."""
    return aliases.get(location, location)
