"""
Config-definition → live object graph.

This is gordo's "serializer as config language": any sklearn-style object
graph can be expressed in YAML as nested single-key dicts
``{dotted.import.path: kwargs}``. Behavior parity with the reference
(gordo/serializer/from_definition.py:23-373):

- single-key dicts resolve the key as an import path, the value as kwargs
- a bare string resolves to a class instantiated with defaults
- classes exposing a ``from_definition`` classmethod get the raw kwargs dict
- ``sklearn.pipeline.Pipeline`` / ``FeatureUnion`` steps / transformer_list
  entries are built recursively (named ``step_N``)
- layer-container classes (our Flax ``Sequential`` spec analog of Keras
  ``Sequential``) get their ``layers`` built recursively
- string params resolving to callables are replaced by the callable
- list values are coerced to tuples for tuple-annotated constructor params
- ``callbacks`` lists are built into callback objects

The engine difference vs the reference: resolved model classes are JAX/Flax
estimators; nothing here touches TF/Keras.
"""

import copy
import logging
from inspect import Parameter, signature
from typing import Any, Dict, Iterable, Union

from sklearn.base import BaseEstimator
from sklearn.pipeline import FeatureUnion, Pipeline

from .import_utils import import_location
from .utils import is_tuple_type

logger = logging.getLogger(__name__)

# Reference-config compatibility: a user migrating from equinor/gordo can keep
# their YAML as-is; these dotted paths are rewritten onto the gordo-tpu
# equivalents before import.
COMPAT_LOCATIONS: Dict[str, str] = {
    "gordo.machine.model.models.KerasAutoEncoder": "gordo_tpu.models.JaxAutoEncoder",
    "gordo.machine.model.models.KerasLSTMAutoEncoder": "gordo_tpu.models.JaxLSTMAutoEncoder",
    "gordo.machine.model.models.KerasLSTMForecast": "gordo_tpu.models.JaxLSTMForecast",
    "gordo.machine.model.models.KerasRawModelRegressor": "gordo_tpu.models.JaxRawModelRegressor",
    "gordo.machine.model.anomaly.diff.DiffBasedAnomalyDetector": (
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
    ),
    "gordo.machine.model.anomaly.diff.DiffBasedKFCVAnomalyDetector": (
        "gordo_tpu.models.anomaly.diff.DiffBasedKFCVAnomalyDetector"
    ),
    "gordo.machine.model.transformers.imputer.InfImputer": (
        "gordo_tpu.models.transformers.imputer.InfImputer"
    ),
    "gordo.machine.model.transformer_funcs.general.multiply_by": (
        "gordo_tpu.models.transformer_funcs.general.multiply_by"
    ),
    "tensorflow.keras.callbacks.EarlyStopping": (
        "gordo_tpu.models.callbacks.EarlyStopping"
    ),
    "keras.callbacks.EarlyStopping": "gordo_tpu.models.callbacks.EarlyStopping",
    "tensorflow.keras.callbacks.ReduceLROnPlateau": (
        "gordo_tpu.models.callbacks.ReduceLROnPlateau"
    ),
    "keras.callbacks.ReduceLROnPlateau": (
        "gordo_tpu.models.callbacks.ReduceLROnPlateau"
    ),
    "tensorflow.keras.callbacks.TerminateOnNaN": (
        "gordo_tpu.models.callbacks.TerminateOnNaN"
    ),
    "keras.callbacks.TerminateOnNaN": (
        "gordo_tpu.models.callbacks.TerminateOnNaN"
    ),
    "tensorflow.keras.models.Sequential": "gordo_tpu.models.spec.Sequential",
    "keras.models.Sequential": "gordo_tpu.models.spec.Sequential",
    "tensorflow.keras.layers.Dense": "gordo_tpu.models.spec.Dense",
    "keras.layers.Dense": "gordo_tpu.models.spec.Dense",
    "gordo_dataset.datasets.TimeSeriesDataset": (
        "gordo_tpu.dataset.datasets.TimeSeriesDataset"
    ),
    "gordo_dataset.datasets.RandomDataset": "gordo_tpu.dataset.datasets.RandomDataset",
}


def _import(import_path: str):
    return import_location(COMPAT_LOCATIONS.get(import_path, import_path))


def from_definition(
    pipe_definition: Union[str, Dict[str, Any]]
) -> Union[FeatureUnion, Pipeline, BaseEstimator]:
    """
    Construct an estimator / Pipeline / FeatureUnion from a config definition.

    Example
    -------
    >>> import yaml
    >>> definition = yaml.safe_load('''
    ... sklearn.pipeline.Pipeline:
    ...     steps:
    ...         - sklearn.preprocessing.MinMaxScaler
    ...         - sklearn.decomposition.PCA:
    ...             n_components: 2
    ... ''')
    >>> pipe = from_definition(definition)
    >>> [type(s).__name__ for _, s in pipe.steps]
    ['MinMaxScaler', 'PCA']
    """
    return _build_step(copy.deepcopy(pipe_definition))


def _is_tuple_param(param: Parameter) -> bool:
    if param.default is not param.empty and isinstance(param.default, tuple):
        return True
    if param.annotation is not param.empty and is_tuple_type(param.annotation):
        return True
    return False


def create_instance(fn, **kwargs):
    """
    Instantiate ``fn(**kwargs)``, coercing list values to tuples for any
    parameter whose default or annotation is tuple-typed (YAML has no tuple
    literal).

    >>> from sklearn.preprocessing import MinMaxScaler
    >>> create_instance(MinMaxScaler, feature_range=[-1, 1])
    MinMaxScaler(feature_range=(-1, 1))
    """
    kwargs = copy.copy(kwargs)
    try:
        params = signature(fn).parameters
    except (TypeError, ValueError):
        params = {}
    for name, param in params.items():
        if name not in kwargs:
            continue
        if param.kind in (Parameter.KEYWORD_ONLY, Parameter.POSITIONAL_OR_KEYWORD):
            if _is_tuple_param(param) and isinstance(kwargs[name], list):
                kwargs[name] = tuple(kwargs[name])
    return fn(**kwargs)


def _is_layers_container(cls) -> bool:
    """Classes marked as taking a recursively-built ``layers`` list."""
    return getattr(cls, "_serializer_layers_container", False)


def _build_branch(definition: Iterable[Union[str, dict]]):
    return [_build_step(step) for step in definition]


def _build_scikit_branch(definition: Iterable[Union[str, dict]]):
    """Steps as (name, obj) tuples, the Pipeline/FeatureUnion convention."""
    return [(f"step_{i}", _build_step(step)) for i, step in enumerate(definition)]


def _build_step(step: Union[str, Dict[str, Any]]):
    logger.debug("Building step: %s", step)

    if isinstance(step, dict):
        if len(step) != 1:
            # Plain dict of params, each of which may itself be a definition
            return _load_param_classes(step)

        import_str = next(iter(step))
        try:
            StepClass = _import(import_str)
        except (ImportError, ValueError):
            StepClass = None
        if StepClass is None:
            raise ImportError(f'Could not locate path: "{import_str}"')

        params = step[import_str]
        if params is None:
            params = {}

        if hasattr(StepClass, "from_definition"):
            return StepClass.from_definition(params)

        if isinstance(params, dict):
            params = _load_param_classes(params)
            for name, value in list(params.items()):
                if isinstance(value, str):
                    try:
                        maybe_func = _import(value)
                    except (ImportError, ValueError):
                        maybe_func = None
                    if callable(maybe_func) and not isinstance(maybe_func, type):
                        params[name] = maybe_func

        if StepClass in (Pipeline, FeatureUnion) or _is_layers_container(StepClass):
            if isinstance(params, dict) and "transformer_list" in params:
                params["transformer_list"] = _build_scikit_branch(
                    params["transformer_list"]
                )
            elif isinstance(params, dict) and "steps" in params:
                params["steps"] = _build_scikit_branch(params["steps"])
            elif isinstance(params, (tuple, list)):
                return StepClass(_build_scikit_branch(params))
            elif isinstance(params, dict) and "layers" in params:
                params["layers"] = _build_branch(params["layers"])
            else:
                raise ValueError(
                    f"Got {StepClass} but the supplied parameters seem invalid: "
                    f"{params}"
                )
        return create_instance(StepClass, **params)

    if isinstance(step, str):
        try:
            Step = _import(step)
        except (ImportError, ValueError):
            Step = None
        if hasattr(Step, "from_definition"):
            return Step.from_definition({})
        return Step() if Step is not None else step

    raise ValueError(f"Expected step to be str or dict, found: {type(step)}")


def _load_param_classes(params: dict) -> dict:
    """
    Resolve any param values that are themselves definitions:

    - string values importable as ``BaseEstimator`` subclasses → instance
    - single-key dicts ``{path: {kwargs}}`` → instance (recursively)
    - ``callbacks`` lists → callback objects

    >>> _load_param_classes({"k": "v"})
    {'k': 'v'}
    >>> out = _load_param_classes(
    ...     {"base_estimator": "sklearn.ensemble.RandomForestRegressor"})
    >>> type(out["base_estimator"]).__name__
    'RandomForestRegressor'
    """
    params = copy.copy(params)
    for key, value in params.items():
        if isinstance(value, str):
            try:
                Model = _import(value)
            except (ImportError, ValueError):
                Model = None
            if Model is not None:
                if hasattr(Model, "from_definition"):
                    params[key] = Model.from_definition({})
                elif isinstance(Model, type) and issubclass(Model, BaseEstimator):
                    params[key] = Model()
        elif (
            isinstance(value, dict)
            and len(value) == 1
            and isinstance(value[next(iter(value))], dict)
        ):
            import_path = next(iter(value))
            try:
                Model = _import(import_path)
            except (ImportError, ValueError):
                Model = None
            sub_params = value[import_path]
            if hasattr(Model, "from_definition"):
                params[key] = Model.from_definition(sub_params)
            elif Model is not None and isinstance(Model, type):
                if issubclass(Model, Pipeline) or _is_layers_container(Model):
                    params[key] = from_definition(value)
                else:
                    params[key] = create_instance(
                        Model, **_load_param_classes(sub_params)
                    )
        elif key == "callbacks" and isinstance(value, list):
            params[key] = build_callbacks(value)
    return params


def load_params_from_definition(definition: dict) -> dict:
    """Deserialize each value of a kwargs dict (used for fit-arg expansion)."""
    if not isinstance(definition, dict):
        raise ValueError(f"Expected definition to be a dict, found {type(definition)}")
    return _load_param_classes(definition)


def build_callbacks(definitions: list) -> list:
    """
    Build training-callback objects from their definitions.

    >>> cbs = build_callbacks(
    ...     [{"gordo_tpu.models.callbacks.EarlyStopping":
    ...       {"monitor": "val_loss", "patience": 10}}])
    >>> type(cbs[0]).__name__
    'EarlyStopping'
    """
    from gordo_tpu.models.callbacks import Callback

    return [
        cb if isinstance(cb, Callback) else _build_step(cb) for cb in definitions
    ]
