"""
Disk / bytes serialization of trained models.

Artifact layout parity with gordo/serializer/serializer.py:149-196: a model
directory holds ``model.pkl`` (the pickled estimator/pipeline),
``metadata.json`` and ``info.json`` (with the model file's checksum). The
pickle-bytes form (``dumps``/``loads``) is the wire format of the server's
``/download-model`` route.

JAX estimators make this work by storing their params as host numpy arrays in
``__getstate__`` (see gordo_tpu/models/estimators.py), so a pickled model is
device-independent and loads on any backend.
"""

import hashlib
import logging
import os
import pickle
import re
import shutil
import uuid
from os import path
from typing import Any, Optional

from ..telemetry.aggregate import ROLLUP_DIR, is_worker_variant
from ..telemetry.fleet_health import FLEET_HEALTH_FILE, FLEET_HEALTH_SHARD_DIR
from ..telemetry.progress import BUILD_STATUS_FILE, BUILD_TRACE_FILE
from ..telemetry.serving import SERVE_TRACE_FILE
from ..telemetry.slo import SLO_CONFIG_FILE, SLO_STATE_FILE
from ..utils import json_compat as simplejson
from ..utils.faults import fault_point

logger = logging.getLogger(__name__)

MODEL_FILE = "model.pkl"
METADATA_FILE = "metadata.json"
INFO_FILE = "info.json"


def dumps(model) -> bytes:
    """
    Serialize a model into bytes.

    >>> from sklearn.preprocessing import MinMaxScaler
    >>> restored = loads(dumps(MinMaxScaler(feature_range=(0, 2))))
    >>> restored.feature_range
    (0, 2)
    """
    return pickle.dumps(model)


def loads(bytes_object: bytes):
    """Restore a model serialized with ``dumps``."""
    return pickle.loads(bytes_object)


def _file_checksum(file_path: str) -> str:
    digest = hashlib.md5()
    with open(file_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def dump(obj, dest_dir: str, metadata: Optional[dict] = None, info: Optional[dict] = None):
    """
    Serialize ``obj`` into ``dest_dir`` as ``model.pkl`` (+ optional
    ``metadata.json`` / ``info.json``; info always records the model
    checksum).
    """
    os.makedirs(dest_dir, exist_ok=True)
    model_path = path.join(dest_dir, MODEL_FILE)
    with open(model_path, "wb") as f:
        pickle.dump(obj, f)
    if metadata is not None:
        with open(path.join(dest_dir, METADATA_FILE), "w") as f:
            simplejson.dump(metadata, f, default=str, ignore_nan=True)
    full_info = {"checksum": _file_checksum(model_path)}
    if info:
        full_info.update(info)
    with open(path.join(dest_dir, INFO_FILE), "w") as f:
        simplejson.dump(full_info, f, default=str)


TMP_DIR_MARKER = ".tmp-"

#: the fleet builder's crash-safe journal, written beside the artifacts
#: (parallel/journal.py owns its format; the names live here so every
#: artifact-discovery path shares one notion of "not a model")
BUILD_JOURNAL_FILE = "build_state.json"
#: append-only per-machine event overlay (one JSON line per status
#: event), compacted into the base journal at phase boundaries
BUILD_JOURNAL_EVENTS_FILE = "." + BUILD_JOURNAL_FILE + ".events"
#: BUILD_STATUS_FILE / BUILD_TRACE_FILE — the build-progress heartbeat
#: and JSONL span trace written beside the artifacts — are re-exported
#: in the imports above: telemetry/progress.py owns the names and
#: formats (that package must stay stdlib-only importable from the
#: training hot path, so the dependency arrow points this way)


def is_staging_dir(name: str) -> bool:
    """True for atomic-write staging entries (``.<name>.tmp-*`` dirs and
    the journal's ``.build_state.json.tmp-*`` flush files): every
    artifact-discovery path (serving store, model listings, resume) must
    skip them — they are by construction possibly half-written."""
    return name.startswith(".") and TMP_DIR_MARKER in name


def _is_worker_sink(name: str, base: str) -> bool:
    """Per-worker variants of one telemetry sink, rotated generations
    included (``serve_trace-<pid>.jsonl[.N]``, ``fleet_health-<pid>
    .json``); the suffix grammar itself lives in ONE place
    (``telemetry.aggregate.is_worker_variant``)."""
    return is_worker_variant(re.sub(r"\.\d+$", "", name), base)


def is_builder_dropping(name: str) -> bool:
    """True for any non-model entry the fleet builder (or a serving /
    SLO process pointed at the artifact volume) may leave in an
    artifact directory: the build journal, its event overlay, the
    telemetry heartbeat/trace/health-ledger files — including their
    size-rotated generations (``build_trace.jsonl.1`` ...) and the
    per-worker ``-<pid>`` sink variants — the SLO engine's ``rollups/``
    directory, alert-state file and a deployment's ``slos.toml``, and
    atomic-write staging leftovers. Revision cleanup treats a directory
    holding only these as empty; model listings never surface them."""
    return (
        name == BUILD_JOURNAL_FILE
        or name == BUILD_JOURNAL_EVENTS_FILE
        or name == BUILD_STATUS_FILE
        or name == BUILD_TRACE_FILE
        or name == SERVE_TRACE_FILE
        or name == FLEET_HEALTH_FILE
        or name == FLEET_HEALTH_SHARD_DIR
        or name == ROLLUP_DIR
        or name == SLO_STATE_FILE
        or name == SLO_CONFIG_FILE
        or name.startswith(BUILD_TRACE_FILE + ".")
        or name.startswith(SERVE_TRACE_FILE + ".")
        or _is_worker_sink(name, SERVE_TRACE_FILE)
        or _is_worker_sink(name, FLEET_HEALTH_FILE)
        # the sharded health-ledger layout (`fleet_health.d/`,
        # per-worker `fleet_health-<pid>.d/`) is a dropping DIRECTORY
        or _is_worker_sink(name, FLEET_HEALTH_SHARD_DIR)
        or is_staging_dir(name)
    )


def list_model_dirs(directory: str) -> list:
    """Names of the artifact (model) directories under ``directory`` —
    the one shared definition of "what counts as a model entry" for the
    serving store, the model-list route, and resume: directories only,
    builder droppings and dot-entries excluded. Missing directory → []."""
    try:
        entries = os.listdir(directory)
    except FileNotFoundError:
        return []
    return sorted(
        entry
        for entry in entries
        if not entry.startswith(".")
        and not is_builder_dropping(entry)
        and path.isdir(path.join(directory, entry))
    )


#: files an artifact dir may contain; a dest dir holding ONLY these (or
#: nothing) is a prior artifact and safe to swap wholesale
_ARTIFACT_FILES = frozenset({MODEL_FILE, METADATA_FILE, INFO_FILE})


def dump_atomic(
    obj,
    dest_dir: str,
    metadata: Optional[dict] = None,
    info: Optional[dict] = None,
):
    """
    Crash-safe :func:`dump`: artifacts are written into a
    ``.<name>.tmp-*`` sibling staging dir and ``os.replace``-renamed
    into place, so ``dest_dir`` either holds a complete artifact set or
    does not exist — a crash mid-write can never leave a half-written
    ``model.pkl`` where the server's fleet store (or a ``--resume``
    pass) would load it.

    A pre-existing ``dest_dir`` that is empty or a prior artifact is
    replaced whole. A dest dir holding OTHER content (e.g. ``gordo
    build config.yaml .`` — the legacy dump merged into it) is never
    deleted: the three artifact files are moved in individually, each
    with its own atomic ``os.replace``.
    """
    dest_dir = path.normpath(dest_dir)
    parent, name = path.dirname(dest_dir), path.basename(dest_dir)
    os.makedirs(parent or ".", exist_ok=True)
    # Plain os.mkdir (NOT tempfile.mkdtemp): mkdtemp forces mode 0700,
    # which the rename would carry onto the artifact dir and lock out a
    # model server running as a different UID; mkdir honors the umask
    # like os.makedirs always did, with no process-global umask probing
    # (os.umask() round trips race across the dump thread pool).
    while True:
        staging = path.join(
            parent or ".", f".{name}{TMP_DIR_MARKER}{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        try:
            os.mkdir(staging)
            break
        except FileExistsError:  # pragma: no cover - 2^32 collision
            continue
    try:
        dump(obj, staging, metadata=metadata, info=info)
        fault_point("dump_artifact", name)
        if path.isdir(dest_dir) and not set(os.listdir(dest_dir)) <= _ARTIFACT_FILES:
            # Mixed-content dest: move each artifact file in (file-level
            # atomic), leave everything else untouched.
            for entry in os.listdir(staging):
                os.replace(path.join(staging, entry), path.join(dest_dir, entry))
            os.rmdir(staging)
            return
        if path.isdir(dest_dir):
            # rename(2) cannot replace a non-empty dir; a complete prior
            # artifact (e.g. a re-build into the same output dir) is
            # swapped out the pre-rename instant before the new one lands.
            shutil.rmtree(dest_dir)
        os.replace(staging, dest_dir)
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise


def load(source_dir: str) -> Any:
    """Load the model saved in ``source_dir`` by ``dump``."""
    model_path = path.join(source_dir, MODEL_FILE)
    with open(model_path, "rb") as f:
        return pickle.load(f)


def _load_json_file(source_dir: str, filename: str) -> dict:
    """
    Load a JSON artifact, falling back to the parent directory — the
    reference stores metadata either beside or one level above the model dir
    (gordo/serializer/serializer.py:77-84).
    """
    for candidate_dir in (source_dir, path.dirname(path.normpath(source_dir))):
        candidate = path.join(candidate_dir, filename)
        if path.isfile(candidate):
            with open(candidate) as f:
                return simplejson.load(f)
    raise FileNotFoundError(
        f"{filename} not found in {source_dir} or its parent directory"
    )


def load_metadata(source_dir: str) -> dict:
    """Load ``metadata.json`` for a model directory."""
    return _load_json_file(source_dir, METADATA_FILE)


def load_info(source_dir: str) -> dict:
    """Load ``info.json`` for a model directory."""
    return _load_json_file(source_dir, INFO_FILE)
