"""
Disk / bytes serialization of trained models.

Artifact layout parity with gordo/serializer/serializer.py:149-196: a model
directory holds ``model.pkl`` (the pickled estimator/pipeline),
``metadata.json`` and ``info.json`` (with the model file's checksum). The
pickle-bytes form (``dumps``/``loads``) is the wire format of the server's
``/download-model`` route.

JAX estimators make this work by storing their params as host numpy arrays in
``__getstate__`` (see gordo_tpu/models/estimators.py), so a pickled model is
device-independent and loads on any backend.
"""

import hashlib
import logging
import os
import pickle
from os import path
from typing import Any, Optional

import simplejson

logger = logging.getLogger(__name__)

MODEL_FILE = "model.pkl"
METADATA_FILE = "metadata.json"
INFO_FILE = "info.json"


def dumps(model) -> bytes:
    """
    Serialize a model into bytes.

    >>> from sklearn.preprocessing import MinMaxScaler
    >>> restored = loads(dumps(MinMaxScaler(feature_range=(0, 2))))
    >>> restored.feature_range
    (0, 2)
    """
    return pickle.dumps(model)


def loads(bytes_object: bytes):
    """Restore a model serialized with ``dumps``."""
    return pickle.loads(bytes_object)


def _file_checksum(file_path: str) -> str:
    digest = hashlib.md5()
    with open(file_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def dump(obj, dest_dir: str, metadata: Optional[dict] = None, info: Optional[dict] = None):
    """
    Serialize ``obj`` into ``dest_dir`` as ``model.pkl`` (+ optional
    ``metadata.json`` / ``info.json``; info always records the model
    checksum).
    """
    os.makedirs(dest_dir, exist_ok=True)
    model_path = path.join(dest_dir, MODEL_FILE)
    with open(model_path, "wb") as f:
        pickle.dump(obj, f)
    if metadata is not None:
        with open(path.join(dest_dir, METADATA_FILE), "w") as f:
            simplejson.dump(metadata, f, default=str, ignore_nan=True)
    full_info = {"checksum": _file_checksum(model_path)}
    if info:
        full_info.update(info)
    with open(path.join(dest_dir, INFO_FILE), "w") as f:
        simplejson.dump(full_info, f, default=str)


def load(source_dir: str) -> Any:
    """Load the model saved in ``source_dir`` by ``dump``."""
    model_path = path.join(source_dir, MODEL_FILE)
    with open(model_path, "rb") as f:
        return pickle.load(f)


def _load_json_file(source_dir: str, filename: str) -> dict:
    """
    Load a JSON artifact, falling back to the parent directory — the
    reference stores metadata either beside or one level above the model dir
    (gordo/serializer/serializer.py:77-84).
    """
    for candidate_dir in (source_dir, path.dirname(path.normpath(source_dir))):
        candidate = path.join(candidate_dir, filename)
        if path.isfile(candidate):
            with open(candidate) as f:
                return simplejson.load(f)
    raise FileNotFoundError(
        f"{filename} not found in {source_dir} or its parent directory"
    )


def load_metadata(source_dir: str) -> dict:
    """Load ``metadata.json`` for a model directory."""
    return _load_json_file(source_dir, METADATA_FILE)


def load_info(source_dir: str) -> dict:
    """Load ``info.json`` for a model directory."""
    return _load_json_file(source_dir, INFO_FILE)
