from .from_definition import (
    build_callbacks,
    from_definition,
    load_params_from_definition,
)
from .into_definition import into_definition
from .serializer import (
    INFO_FILE,
    METADATA_FILE,
    MODEL_FILE,
    dump,
    dumps,
    load,
    load_info,
    load_metadata,
    loads,
)

__all__ = [
    "MODEL_FILE",
    "METADATA_FILE",
    "INFO_FILE",
    "from_definition",
    "into_definition",
    "load_params_from_definition",
    "build_callbacks",
    "dump",
    "dumps",
    "load",
    "loads",
    "load_metadata",
    "load_info",
]
