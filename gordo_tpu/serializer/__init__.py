from .from_definition import (
    build_callbacks,
    from_definition,
    load_params_from_definition,
)
from .into_definition import into_definition
from .serializer import (
    BUILD_JOURNAL_FILE,
    INFO_FILE,
    METADATA_FILE,
    MODEL_FILE,
    dump,
    dump_atomic,
    dumps,
    is_builder_dropping,
    is_staging_dir,
    list_model_dirs,
    load,
    load_info,
    load_metadata,
    loads,
)

__all__ = [
    "MODEL_FILE",
    "METADATA_FILE",
    "INFO_FILE",
    "BUILD_JOURNAL_FILE",
    "is_builder_dropping",
    "list_model_dirs",
    "from_definition",
    "into_definition",
    "load_params_from_definition",
    "build_callbacks",
    "dump",
    "dump_atomic",
    "dumps",
    "is_staging_dir",
    "load",
    "loads",
    "load_metadata",
    "load_info",
]
