"""
The ``FleetPlan`` artifact: explainable, deterministic, replayable.

A plan is the full answer to "what will this build run, and why": every
bucket with its member roster, pad targets, predicted wall-clock /
compile / HBM / padding-waste numbers, plus the knobs that produced it.
Properties the rest of the system leans on:

- **deterministic**: the same machine configs and cost table always
  serialize to byte-identical JSON (sorted keys, rounded floats, no
  timestamps) — so ``plan_hash`` is a stable identity the build journal
  records and ``--resume`` can trust;
- **self-describing**: specs serialize via ``ModelSpec.to_dict`` and the
  fit config inline, so a plan explains itself without the machine YAML
  in hand;
- **replayable**: ``build-fleet --plan-from plan.json`` re-binds bucket
  rosters to live members by NAME (:meth:`FleetPlan.materialize_buckets`).
  A member keeps its planned pad targets even when neighbors were
  resumed away, so its padded shape — and therefore its shuffle stream
  and trained parameters — never depends on which other members are
  still building.
"""

import hashlib
import json
import os
from typing import Any, Dict, List, Sequence, Tuple

from .costmodel import perfmodel_enabled
from .packing import PlannedBucket, member_is_windowed, member_samples

PLAN_VERSION = 1

#: canonical plan filename a build drops beside its artifacts
PLAN_FILE = "fleet_plan.json"


class PlanError(ValueError):
    """A plan document that cannot be used (version/shape mismatch)."""


class FleetPlan:
    """In-memory plan: the serialized document plus name→bucket maps."""

    def __init__(self, doc: Dict[str, Any]):
        if int(doc.get("version", 0)) != PLAN_VERSION:
            raise PlanError(
                f"fleet plan version {doc.get('version')!r} != supported "
                f"{PLAN_VERSION}; re-run `gordo-tpu plan`"
            )
        self.doc = doc
        self._assignment: Dict[str, dict] = {}
        for bucket in self.buckets:
            for name in bucket["members"]:
                self._assignment[name] = bucket

    # -- document accessors -------------------------------------------------

    @property
    def strategy(self) -> str:
        return str(self.doc.get("strategy", ""))

    @property
    def buckets(self) -> List[dict]:
        return list(self.doc.get("buckets") or [])

    @property
    def totals(self) -> Dict[str, Any]:
        return dict(self.doc.get("totals") or {})

    @property
    def member_names(self) -> List[str]:
        return sorted(self._assignment)

    def covers(self, names: Sequence[str]) -> bool:
        return all(name in self._assignment for name in names)

    # -- identity -----------------------------------------------------------

    def to_json(self) -> str:
        """The canonical byte form: sorted keys, indent 1, trailing
        newline. Everything (including :attr:`plan_hash`) derives from
        this, so two plans are the same iff their files are."""
        return json.dumps(self.doc, indent=1, sort_keys=True) + "\n"

    @property
    def plan_hash(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]

    def save(self, path: str) -> None:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "FleetPlan":
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as exc:
            raise PlanError(f"unreadable fleet plan {path}: {exc}") from exc
        if not isinstance(doc, dict):
            raise PlanError(f"fleet plan {path} is not a JSON object")
        return cls(doc)

    # -- replay -------------------------------------------------------------

    def materialize_buckets(
        self, members: Sequence[Any]
    ) -> Tuple[List[PlannedBucket], List[Any]]:
        """
        Re-bind this plan's bucket rosters to live ``members`` by name.

        Returns ``(buckets, uncovered)``: one :class:`PlannedBucket` per
        plan bucket that has at least one live member (keeping the
        planned pad targets — composition may be a subset after
        ``--resume``), plus the members the plan does not know (CV fold
        members, machines added since planning) for live packing.
        """
        by_bucket: Dict[str, List[Any]] = {}
        uncovered: List[Any] = []
        for member in members:
            entry = self._assignment.get(member.name)
            # A member the plan is stale for cannot use the planned
            # bucket: data that outgrew the pad target would be
            # truncated by stacking, and a spec that drifted since
            # planning (the machine's architecture was edited) would
            # train under the wrong program — both repack live instead.
            if (
                entry is None
                or member_samples(member) > int(entry["n_padded"])
                or _jsonable(member.spec.to_dict()) != entry.get("spec")
            ):
                uncovered.append(member)
                continue
            by_bucket.setdefault(entry["id"], []).append(member)
        buckets: List[PlannedBucket] = []
        for entry in self.buckets:
            live = by_bucket.get(entry["id"])
            if not live:
                continue
            windowed = bool(entry.get("windowed"))
            if any(member_is_windowed(m) != windowed for m in live):
                raise PlanError(
                    f"plan bucket {entry['id']} mixes windowed and dense "
                    "members with the live fleet — the plan does not match "
                    "this config; re-run `gordo-tpu plan`"
                )
            buckets.append(
                PlannedBucket(
                    bucket_id=str(entry["id"]),
                    program=str(entry["program"]),
                    spec=live[0].spec,
                    members=live,
                    n_padded=int(entry["n_padded"]),
                    m_padded=(
                        int(entry["m_padded"])
                        if entry.get("m_padded") is not None
                        else None
                    ),
                    offset=int(entry.get("offset", 0)),
                    windowed=windowed,
                )
            )
        return buckets, uncovered


def build_plan_doc(
    buckets_by_config: Sequence[Tuple[Any, Sequence[PlannedBucket]]],
    strategy: str,
    mesh_shape: Tuple[int, int],
    cost_table: Any,
    config_fingerprint: str,
) -> FleetPlan:
    """
    Assemble the serializable plan document from per-fit-config bucket
    lists (``annotate_predictions`` must already have run on them).

    ``config_fingerprint`` ties the plan to the machine configs it was
    computed from (the builder hashes the per-machine cache keys); the
    journal records :attr:`FleetPlan.plan_hash` so a resume can tell a
    replan from a replay.
    """
    bucket_docs: List[dict] = []
    totals = {
        "buckets": 0,
        "members": 0,
        "compiles": 0,
        "predicted_compile_s": 0.0,
        "predicted_run_s": 0.0,
        "flops_true": 0.0,
        "flops_padded": 0.0,
        "hbm_peak_bytes": 0,
    }
    for config, buckets in buckets_by_config:
        config_doc = {
            "epochs": config.epochs,
            "batch_size": config.batch_size,
            "validation_split": config.validation_split,
            "shuffle": config.shuffle,
            "early_stopping": list(config.early_stopping)
            if config.early_stopping
            else None,
        }
        for bucket in buckets:
            predicted = dict(bucket.predicted)
            bucket_docs.append(
                {
                    "id": bucket.bucket_id,
                    "program": bucket.program,
                    "windowed": bucket.windowed,
                    "spec": _jsonable(bucket.spec.to_dict()),
                    "fit_config": config_doc,
                    "members": list(bucket.member_names),
                    "n_padded": bucket.n_padded,
                    "m_padded": bucket.m_padded,
                    "offset": bucket.offset,
                    "predicted": predicted,
                }
            )
            totals["buckets"] += 1
            totals["members"] += len(bucket.members)
            totals["compiles"] += int(predicted.get("compiles", 1))
            totals["predicted_compile_s"] += float(predicted.get("compile_s", 0.0))
            totals["predicted_run_s"] += float(predicted.get("run_s", 0.0))
            totals["flops_true"] += float(predicted.get("flops_true", 0.0))
            totals["flops_padded"] += float(predicted.get("flops_padded", 0.0))
            totals["hbm_peak_bytes"] = max(
                totals["hbm_peak_bytes"], int(predicted.get("hbm_bytes", 0))
            )
    bucket_docs.sort(key=lambda b: b["id"])
    totals["predicted_wall_s"] = round(
        totals["predicted_compile_s"] + totals["predicted_run_s"], 6
    )
    totals["predicted_compile_s"] = round(totals["predicted_compile_s"], 6)
    totals["predicted_run_s"] = round(totals["predicted_run_s"], 6)
    totals["padding_waste"] = round(
        1.0 - totals["flops_true"] / totals["flops_padded"]
        if totals["flops_padded"]
        else 0.0,
        6,
    )
    totals["flops_true"] = float(f"{totals['flops_true']:.6g}")
    totals["flops_padded"] = float(f"{totals['flops_padded']:.6g}")
    doc = {
        "version": PLAN_VERSION,
        "strategy": strategy,
        "mesh_shape": [int(mesh_shape[0]), int(mesh_shape[1] or 1)],
        "config_fingerprint": config_fingerprint,
        "cost_table": {
            "version": getattr(cost_table, "version", None),
            "calibrated": bool(getattr(cost_table, "calibrated", False)),
            # per-program calibration sample counts: thin calibration
            # (3 spans backing a factor) is visible in `plan --as-json`
            # instead of hiding behind a confident-looking number
            "samples": {
                str(k): int(v)
                for k, v in sorted(
                    (getattr(cost_table, "samples", None) or {}).items()
                )
            },
            # True only when the learned performance model actually
            # participated in costing (section fitted AND knob on) —
            # the plan records which ruler ranked its buckets
            "learned": bool(getattr(cost_table, "has_learned", False))
            and perfmodel_enabled(),
        },
        "buckets": bucket_docs,
        "totals": totals,
    }
    return FleetPlan(doc)


def _jsonable(value: Any) -> Any:
    """Tuples → lists (json round-trip stability for spec dicts)."""
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


def config_fingerprint(cache_keys: Sequence[str]) -> str:
    """One stable hash over the fleet's per-machine config hashes."""
    digest = hashlib.sha256()
    for key in sorted(cache_keys):
        digest.update(str(key).encode())
        digest.update(b"\0")
    return digest.hexdigest()[:16]
