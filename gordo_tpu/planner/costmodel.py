"""
Analytic bucket cost model with trace-fitted correction factors.

Per-program TPU cost is predictable from static features plus a small
calibration set (the learned-performance-model line of work, PAPERS.md).
This module is the smallest useful instance of that recipe:

- **static features**: parameter count and padded training FLOPs derived
  from the spec geometry alone (:func:`spec_param_count`,
  :func:`spec_flops_per_sample`) — the planner never traces or compiles
  anything to cost a candidate bucket;
- **calibration**: :func:`calibrate` fits per-program correction factors
  from the ``device_program`` spans PR 3's telemetry already records in
  ``build_trace.jsonl`` (first-call-per-signature spans are compiles,
  the rest steady-state runs), and persists them as a versioned
  ``cost_table.json``.

Absolute accuracy is NOT the point — bucket *ranking* is. The packer
only ever compares candidate buckets of the same fleet against each
other, so a constant-factor error cancels; the calibration exists to
keep the compile-vs-run trade (the compile-budget knob) honest on the
actual backend.
"""

import json
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

from ..models.spec import FeedForwardSpec, LSTMSpec, ModelSpec

logger = logging.getLogger(__name__)

#: canonical calibrated-table filename (beside the trace it was fit from)
COST_TABLE_FILE = "cost_table.json"

#: cost_table.json schema version — bump on shape changes so stale
#: tables are rejected instead of silently misread
COST_TABLE_VERSION = 1

#: Adam keeps params + grads + two moment vectors resident per member
_OPTIMIZER_COPIES = 4

#: backward pass ≈ 2x the forward FLOPs (grad wrt inputs + weights)
_TRAIN_FLOP_FACTOR = 3.0

#: resident WEIGHT bytes per element at each serving precision (the
#: serve engine's precision ladder): int8 weight-only quantization
#: additionally keeps a per-channel f32 scale, accounted separately in
#: :meth:`CostModel.serve_weight_bytes`
PRECISION_WEIGHT_BYTES: Dict[str, int] = {"f32": 4, "bf16": 2, "int8": 1}

#: activation/compute bytes per element: int8 serving runs its
#: activations in bf16 (weight-only quantization), so its compute width
#: is bf16's
PRECISION_COMPUTE_BYTES: Dict[str, int] = {"f32": 4, "bf16": 2, "int8": 2}

#: THE canonical precision-alias table. It lives HERE (not in
#: gordo_tpu.serve.precision, which re-imports it) because the layering
#: contract forbids planner→serve imports even lazily — the cost model
#: is the lowest layer that speaks precision, so it owns the vocabulary
#: and the serve package reads it from below.
PRECISION_ALIASES: Dict[str, str] = {
    "f32": "f32", "fp32": "f32", "float32": "f32",
    "bf16": "bf16", "bfloat16": "bf16",
    "int8": "int8", "i8": "int8", "w8": "int8",
}

#: analytic default per-precision step-time factors (shared by the
#: CostTable field default and the legacy-table load path)
DEFAULT_PRECISION_FACTORS: Dict[str, float] = {"bf16": 0.6, "int8": 0.55}


def normalize_precision(precision: Optional[str]) -> str:
    """Canonical precision key (``float32``→``f32``, ``bfloat16``→
    ``bf16``); unknown/empty values cost as f32 — the conservative
    (widest) estimate."""
    if not precision:
        return "f32"
    return PRECISION_ALIASES.get(str(precision).strip().lower(), "f32")


def compute_precision(spec: ModelSpec) -> str:
    """The precision feature of a spec's TRAINING programs, derived from
    its ``compute_dtype`` (bf16 compute halves activation traffic even
    though master params stay f32 — models/nn.py dtype contract)."""
    return normalize_precision(getattr(spec, "compute_dtype", "float32"))


def spec_param_count(spec: ModelSpec) -> int:
    """Trainable parameter count from the spec geometry alone."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return sum(
            d_in * d_out + d_out for d_in, d_out in zip(dims[:-1], dims[1:])
        )
    if isinstance(spec, LSTMSpec):
        total = 0
        d_in = spec.n_features
        for d_h in spec.dims:
            # 4 gates, each [d_in + d_h, d_h] + bias
            total += 4 * (d_in * d_h + d_h * d_h + d_h)
            d_in = d_h
        total += d_in * spec.n_features_out + spec.n_features_out
        return total
    # Unknown spec types (future architectures): no geometry knowledge —
    # callers treat 0 as "cost unknown, keep the member in its own group".
    return 0


def spec_flops_per_sample(spec: ModelSpec) -> float:
    """Forward-pass FLOPs for ONE sample (one window for LSTM specs —
    the recurrence runs ``lookback_window`` steps per window)."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return float(
            sum(2 * d_in * d_out for d_in, d_out in zip(dims[:-1], dims[1:]))
        )
    if isinstance(spec, LSTMSpec):
        per_step = 0.0
        d_in = spec.n_features
        for d_h in spec.dims:
            per_step += 2.0 * 4 * (d_in + d_h) * d_h
            d_in = d_h
        head = 2.0 * d_in * spec.n_features_out
        return per_step * spec.lookback_window + head
    # ~2 FLOPs per parameter per sample is the dense-layer identity;
    # use it as the generic fallback.
    return 2.0 * spec_param_count(spec)


@dataclass
class CostTable:
    """Versioned correction factors fit by :func:`calibrate`.

    ``run_factors``/``compile_factors`` map program name (``fleet_fit``,
    ``fleet_windowed_fit``, ...) to a multiplicative correction on the
    analytic estimate; unseen programs fall back to 1.0. ``throughput``
    and ``compile_per_flop`` are the analytic baseline constants the
    factors correct — persisted so a table is self-contained.
    """

    #: sustained training throughput (FLOP/s) the analytic model divides
    #: by; deliberately conservative-CPU-ish so an UNcalibrated model
    #: still ranks buckets sanely on the test backend
    throughput: float = 2.0e9
    #: seconds of XLA compile per traced FLOP-per-sample unit, plus a
    #: fixed per-program floor — compiles scale with program complexity
    #: (op count ~ layer count ~ flops/sample), not with data volume
    compile_per_flop: float = 2.0e-7
    compile_floor_s: float = 0.35
    #: per-program-dispatch fixed overhead (host dispatch + fetch)
    dispatch_s: float = 0.01
    run_factors: Dict[str, float] = field(default_factory=dict)
    compile_factors: Dict[str, float] = field(default_factory=dict)
    #: per-precision multiplicative correction on predicted step time —
    #: the precision FEATURE of the cost model. Defaults assume the
    #: HBM-bound tiny-model regime (bf16 halves re-read bytes but not
    #: to 0.5x — dispatch and host shares don't scale; int8's dequant
    #: claws some back). Unlisted precisions (and f32) cost 1.0;
    #: recalibrate per backend like every other factor.
    precision_factors: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_PRECISION_FACTORS)
    )
    #: calibration provenance: sample counts per program
    samples: Dict[str, int] = field(default_factory=dict)
    version: int = COST_TABLE_VERSION

    def precision_factor(self, precision: Optional[str]) -> float:
        return float(
            self.precision_factors.get(normalize_precision(precision), 1.0)
        )

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "throughput": self.throughput,
            "compile_per_flop": self.compile_per_flop,
            "compile_floor_s": self.compile_floor_s,
            "dispatch_s": self.dispatch_s,
            "run_factors": dict(sorted(self.run_factors.items())),
            "compile_factors": dict(sorted(self.compile_factors.items())),
            "precision_factors": dict(sorted(self.precision_factors.items())),
            "samples": dict(sorted(self.samples.items())),
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "CostTable":
        version = int(doc.get("version", 0))
        if version != COST_TABLE_VERSION:
            raise ValueError(
                f"cost table version {version} != supported "
                f"{COST_TABLE_VERSION}; re-run calibration"
            )
        return cls(
            throughput=float(doc.get("throughput", cls.throughput)),
            compile_per_flop=float(
                doc.get("compile_per_flop", cls.compile_per_flop)
            ),
            compile_floor_s=float(doc.get("compile_floor_s", cls.compile_floor_s)),
            dispatch_s=float(doc.get("dispatch_s", cls.dispatch_s)),
            run_factors={
                str(k): float(v) for k, v in (doc.get("run_factors") or {}).items()
            },
            compile_factors={
                str(k): float(v)
                for k, v in (doc.get("compile_factors") or {}).items()
            },
            # pre-precision tables (PR ≤13) carry no factor map: they
            # load with the analytic defaults rather than being rejected
            precision_factors={
                str(k): float(v)
                for k, v in (
                    doc.get("precision_factors") or DEFAULT_PRECISION_FACTORS
                ).items()
            },
            samples={
                str(k): int(v) for k, v in (doc.get("samples") or {}).items()
            },
            version=version,
        )

    def save(self, path: str) -> None:
        payload = json.dumps(self.to_dict(), indent=1, sort_keys=True) + "\n"
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "CostTable":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @property
    def calibrated(self) -> bool:
        return bool(self.run_factors or self.compile_factors)


class CostModel:
    """Bucket-shape cost estimates against a :class:`CostTable`.

    ``mesh_shape`` is the trainer mesh's ``(model_axis, data_axis)`` —
    the estimator replicates the trainer's shape rounding so predicted
    program signatures (and therefore compile counts) match what XLA
    will actually see.
    """

    def __init__(
        self,
        table: Optional[CostTable] = None,
        mesh_shape: Tuple[int, int] = (1, 1),
    ):
        self.table = table or CostTable()
        self.mesh_shape = (int(mesh_shape[0]), int(mesh_shape[1] or 1))

    # -- shape replication --------------------------------------------------

    def stacked_shape(
        self, m: int, n_padded: int, batch_size: int
    ) -> Tuple[int, int]:
        """``(m_total, n_total)`` after the trainer's mesh rounding
        (mirrors ``FleetTrainer._stack_bucket``): the model axis pads to
        a multiple of the mesh's model axis, the sample axis to a whole
        number of batches that also divides across the data axis."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        n_total = -(-n_padded // step) * step
        return m_total, n_total

    def stacked_windowed_shape(
        self, m: int, n_padded: int, offset: int, batch_size: int
    ) -> Tuple[int, int, int]:
        """``(m_total, series_rows, windows_total)`` after the trainer's
        windowed-stacker rounding (mirrors
        ``FleetTrainer._stack_windowed_bucket``): the series axis stays
        at ``n_padded`` exactly; only the virtual window axis mesh-rounds."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        nv_total = -(-(n_padded - offset) // step) * step
        return m_total, n_padded, nv_total

    # -- analytic estimates -------------------------------------------------

    def train_flops(
        self, spec: ModelSpec, m: int, n: int, epochs: int
    ) -> float:
        """Training FLOPs for ``m`` members × ``n`` (virtual) samples ×
        ``epochs`` epochs at this spec."""
        return (
            _TRAIN_FLOP_FACTOR
            * spec_flops_per_sample(spec)
            * float(m)
            * float(n)
            * float(max(epochs, 1))
        )

    def predict_run_s(
        self,
        program: str,
        spec: ModelSpec,
        m_total: int,
        n_total: int,
        epochs: int,
        precision: Optional[str] = None,
    ) -> float:
        """``precision`` is the program's compute precision (defaults to
        the spec's own ``compute_dtype``) — a feature of predicted step
        cost, corrected by the table's per-precision factor."""
        if precision is None:
            precision = compute_precision(spec)
        flops = self.train_flops(spec, m_total, n_total, epochs)
        factor = self.table.run_factors.get(program, 1.0)
        factor *= self.table.precision_factor(precision)
        return factor * (flops / self.table.throughput) + self.table.dispatch_s

    def predict_compile_s(self, program: str, spec: ModelSpec) -> float:
        factor = self.table.compile_factors.get(program, 1.0)
        return factor * (
            self.table.compile_floor_s
            + self.table.compile_per_flop * spec_flops_per_sample(spec)
        )

    def predict_hbm_bytes(
        self,
        spec: ModelSpec,
        m_total: int,
        n_total: int,
        batch_size: int,
        y_aliased: bool = True,
        series_rows: Optional[int] = None,
        precision: Optional[str] = None,
    ) -> int:
        """Resident device bytes of one bucket's training program:
        staged data + per-member params × optimizer copies + one batch
        of activations. ``series_rows`` switches to the windowed layout
        (series resident instead of materialized windows).

        ``precision`` (default: the spec's ``compute_dtype``) scales the
        ACTIVATION bytes — bf16 compute halves them, which changes how
        many members fit under the packer's HBM cap. Master params and
        staged f32 data keep full width during training (the models/nn
        mixed-precision contract: params never store reduced)."""
        if precision is None:
            precision = compute_precision(spec)
        f_in = getattr(spec, "n_features", 1)
        f_out = getattr(spec, "n_features_out", f_in)
        if series_rows is not None:
            data = m_total * series_rows * f_in + m_total * n_total * f_out
        else:
            data = m_total * n_total * f_in
            if not y_aliased:
                data += m_total * n_total * f_out
        data += 3 * m_total * n_total  # train/val weights + epoch bookkeeping
        params = spec_param_count(spec) * m_total * _OPTIMIZER_COPIES
        width = max(
            [f_in, f_out, *getattr(spec, "dims", ())] or [1]
        )
        lookback = getattr(spec, "lookback_window", 1)
        activations = m_total * batch_size * width * (
            len(getattr(spec, "dims", ())) + 2
        ) * lookback
        compute_bytes = PRECISION_COMPUTE_BYTES.get(
            normalize_precision(precision), 4
        )
        return int(4 * (data + params) + compute_bytes * activations)

    # -- serve-side estimates (the engine's precision ladder) ---------------

    def serve_weight_bytes(
        self, spec: ModelSpec, members: int, precision: str = "f32"
    ) -> int:
        """Resident weight bytes of one revision bucket at a serving
        precision: bf16 halves them, int8 quarters them (plus the
        per-channel f32 scales — one scale per output unit per member).
        This is the number the precision ladder exists to shrink: the
        HBM traffic every fused batch re-reads."""
        precision = normalize_precision(precision)
        weight_bytes = PRECISION_WEIGHT_BYTES.get(precision, 4)
        params = spec_param_count(spec) * members
        scales = 0
        if precision == "int8":
            dims = tuple(getattr(spec, "dims", ())) + (
                getattr(spec, "n_features_out", 1),
            )
            scales = 4 * members * sum(dims)  # f32 scale per out channel
        return int(weight_bytes * params + scales)

    def predict_serve_hbm_bytes(
        self, spec: ModelSpec, members: int, rows: int, precision: str = "f32"
    ) -> int:
        """Resident bytes of one fused serving batch: the precision's
        weight bucket + the staged payload at the compute width + the
        f32 output."""
        precision = normalize_precision(precision)
        f_in = getattr(spec, "n_features", 1)
        f_out = getattr(spec, "n_features_out", f_in)
        compute_bytes = PRECISION_COMPUTE_BYTES.get(precision, 4)
        payload = compute_bytes * members * rows * f_in
        output = 4 * members * rows * f_out  # always float32 out
        return self.serve_weight_bytes(spec, members, precision) + payload + output

    def predict_serve_step_s(
        self, spec: ModelSpec, members: int, rows: int, precision: str = "f32"
    ) -> float:
        """Predicted wall seconds of one fused serving batch (forward
        only — no train factor), with precision as a feature: the
        engine stamps this next to the measured device time on every
        batch span (predicted-vs-actual on the new axis)."""
        flops = spec_flops_per_sample(spec) * float(members) * float(rows)
        factor = self.table.run_factors.get("fleet_forward", 1.0)
        factor *= self.table.precision_factor(precision)
        return factor * (flops / self.table.throughput) + self.table.dispatch_s


def calibrate(
    trace_path: str, table: Optional[CostTable] = None
) -> CostTable:
    """
    Fit per-program correction factors from a ``build_trace.jsonl``.

    Reads every ``device_program`` span carrying the planner's static
    features (``params``/``flops_per_sample``/``members``/``epochs``,
    recorded by the trainer's program spans), splits them into compile
    (first call per signature) and run samples, and sets each program's
    factor to the MEDIAN of actual/analytic ratios — median, not mean,
    because a shared host's neighbor stalls put multi-second one-sided
    outliers into any wall-clock sample set.

    Returns a new :class:`CostTable`; the input ``table`` (default: the
    analytic defaults) provides the baseline constants the factors
    correct. Spans missing the static features (older traces) are
    skipped.
    """
    base = table or CostTable()
    model = CostModel(CostTable(  # factor-free baseline for the ratios
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
    ))
    run_ratios: Dict[str, list] = {}
    compile_ratios: Dict[str, list] = {}
    counts: Dict[str, int] = {}
    for span in _iter_spans(trace_path):
        if span.get("name") != "device_program":
            continue
        attrs = span.get("attributes") or {}
        program = str(attrs.get("program", ""))
        flops_per_sample = attrs.get("flops_per_sample")
        if not program or flops_per_sample is None:
            continue
        try:
            m = int(attrs.get("stacked_members") or attrs.get("members") or 0)
            n = int(attrs.get("stacked_samples") or 0)
            epochs = int(attrs.get("epochs") or 1)
            seconds = float(span.get("duration_ms") or 0.0) / 1000.0
            flops_per_sample = float(flops_per_sample)
        except (TypeError, ValueError):
            continue
        if m <= 0 or n <= 0 or seconds <= 0.0:
            continue
        counts[program] = counts.get(program, 0) + 1
        flops = _TRAIN_FLOP_FACTOR * flops_per_sample * m * n * max(epochs, 1)
        analytic_run = flops / base.throughput + base.dispatch_s
        if attrs.get("compile"):
            analytic_compile = (
                base.compile_floor_s + base.compile_per_flop * flops_per_sample
            )
            # the first call is trace+compile+first run; subtract the
            # analytic run share so the factor corrects the compile part
            compile_ratios.setdefault(program, []).append(
                max(seconds - analytic_run, 1e-3) / analytic_compile
            )
        else:
            run_ratios.setdefault(program, []).append(seconds / analytic_run)

    def medians(ratios: Dict[str, list]) -> Dict[str, float]:
        out = {}
        for program, values in ratios.items():
            values = sorted(values)
            out[program] = round(values[len(values) // 2], 6)
        return out

    calibrated = CostTable(
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
        run_factors=medians(run_ratios),
        compile_factors=medians(compile_ratios),
        samples=counts,
    )
    logger.info(
        "Calibrated cost table from %s: %d program kind(s), %d span(s)",
        trace_path,
        len(counts),
        sum(counts.values()),
    )
    return calibrated


def _iter_spans(trace_path: str) -> Iterable[dict]:
    with open(trace_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn tail from a killed build
            if isinstance(doc, dict):
                yield doc
