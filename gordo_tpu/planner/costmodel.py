"""
Analytic bucket cost model with trace-fitted correction factors.

Per-program TPU cost is predictable from static features plus a small
calibration set (the learned-performance-model line of work, PAPERS.md).
This module is the smallest useful instance of that recipe:

- **static features**: parameter count and padded training FLOPs derived
  from the spec geometry alone (:func:`spec_param_count`,
  :func:`spec_flops_per_sample`) — the planner never traces or compiles
  anything to cost a candidate bucket;
- **calibration**: :func:`calibrate` fits per-program correction factors
  from the ``device_program`` spans PR 3's telemetry already records in
  ``build_trace.jsonl`` (first-call-per-signature spans are compiles,
  the rest steady-state runs), and persists them as a versioned
  ``cost_table.json``.

Absolute accuracy is NOT the point — bucket *ranking* is. The packer
only ever compares candidate buckets of the same fleet against each
other, so a constant-factor error cancels; the calibration exists to
keep the compile-vs-run trade (the compile-budget knob) honest on the
actual backend.
"""

import json
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

from ..models.spec import FeedForwardSpec, LSTMSpec, ModelSpec

logger = logging.getLogger(__name__)

#: canonical calibrated-table filename (beside the trace it was fit from)
COST_TABLE_FILE = "cost_table.json"

#: cost_table.json schema version — bump on shape changes so stale
#: tables are rejected instead of silently misread
COST_TABLE_VERSION = 1

#: Adam keeps params + grads + two moment vectors resident per member
_OPTIMIZER_COPIES = 4

#: backward pass ≈ 2x the forward FLOPs (grad wrt inputs + weights)
_TRAIN_FLOP_FACTOR = 3.0


def spec_param_count(spec: ModelSpec) -> int:
    """Trainable parameter count from the spec geometry alone."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return sum(
            d_in * d_out + d_out for d_in, d_out in zip(dims[:-1], dims[1:])
        )
    if isinstance(spec, LSTMSpec):
        total = 0
        d_in = spec.n_features
        for d_h in spec.dims:
            # 4 gates, each [d_in + d_h, d_h] + bias
            total += 4 * (d_in * d_h + d_h * d_h + d_h)
            d_in = d_h
        total += d_in * spec.n_features_out + spec.n_features_out
        return total
    # Unknown spec types (future architectures): no geometry knowledge —
    # callers treat 0 as "cost unknown, keep the member in its own group".
    return 0


def spec_flops_per_sample(spec: ModelSpec) -> float:
    """Forward-pass FLOPs for ONE sample (one window for LSTM specs —
    the recurrence runs ``lookback_window`` steps per window)."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return float(
            sum(2 * d_in * d_out for d_in, d_out in zip(dims[:-1], dims[1:]))
        )
    if isinstance(spec, LSTMSpec):
        per_step = 0.0
        d_in = spec.n_features
        for d_h in spec.dims:
            per_step += 2.0 * 4 * (d_in + d_h) * d_h
            d_in = d_h
        head = 2.0 * d_in * spec.n_features_out
        return per_step * spec.lookback_window + head
    # ~2 FLOPs per parameter per sample is the dense-layer identity;
    # use it as the generic fallback.
    return 2.0 * spec_param_count(spec)


@dataclass
class CostTable:
    """Versioned correction factors fit by :func:`calibrate`.

    ``run_factors``/``compile_factors`` map program name (``fleet_fit``,
    ``fleet_windowed_fit``, ...) to a multiplicative correction on the
    analytic estimate; unseen programs fall back to 1.0. ``throughput``
    and ``compile_per_flop`` are the analytic baseline constants the
    factors correct — persisted so a table is self-contained.
    """

    #: sustained training throughput (FLOP/s) the analytic model divides
    #: by; deliberately conservative-CPU-ish so an UNcalibrated model
    #: still ranks buckets sanely on the test backend
    throughput: float = 2.0e9
    #: seconds of XLA compile per traced FLOP-per-sample unit, plus a
    #: fixed per-program floor — compiles scale with program complexity
    #: (op count ~ layer count ~ flops/sample), not with data volume
    compile_per_flop: float = 2.0e-7
    compile_floor_s: float = 0.35
    #: per-program-dispatch fixed overhead (host dispatch + fetch)
    dispatch_s: float = 0.01
    run_factors: Dict[str, float] = field(default_factory=dict)
    compile_factors: Dict[str, float] = field(default_factory=dict)
    #: calibration provenance: sample counts per program
    samples: Dict[str, int] = field(default_factory=dict)
    version: int = COST_TABLE_VERSION

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "throughput": self.throughput,
            "compile_per_flop": self.compile_per_flop,
            "compile_floor_s": self.compile_floor_s,
            "dispatch_s": self.dispatch_s,
            "run_factors": dict(sorted(self.run_factors.items())),
            "compile_factors": dict(sorted(self.compile_factors.items())),
            "samples": dict(sorted(self.samples.items())),
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "CostTable":
        version = int(doc.get("version", 0))
        if version != COST_TABLE_VERSION:
            raise ValueError(
                f"cost table version {version} != supported "
                f"{COST_TABLE_VERSION}; re-run calibration"
            )
        return cls(
            throughput=float(doc.get("throughput", cls.throughput)),
            compile_per_flop=float(
                doc.get("compile_per_flop", cls.compile_per_flop)
            ),
            compile_floor_s=float(doc.get("compile_floor_s", cls.compile_floor_s)),
            dispatch_s=float(doc.get("dispatch_s", cls.dispatch_s)),
            run_factors={
                str(k): float(v) for k, v in (doc.get("run_factors") or {}).items()
            },
            compile_factors={
                str(k): float(v)
                for k, v in (doc.get("compile_factors") or {}).items()
            },
            samples={
                str(k): int(v) for k, v in (doc.get("samples") or {}).items()
            },
            version=version,
        )

    def save(self, path: str) -> None:
        payload = json.dumps(self.to_dict(), indent=1, sort_keys=True) + "\n"
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "CostTable":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @property
    def calibrated(self) -> bool:
        return bool(self.run_factors or self.compile_factors)


class CostModel:
    """Bucket-shape cost estimates against a :class:`CostTable`.

    ``mesh_shape`` is the trainer mesh's ``(model_axis, data_axis)`` —
    the estimator replicates the trainer's shape rounding so predicted
    program signatures (and therefore compile counts) match what XLA
    will actually see.
    """

    def __init__(
        self,
        table: Optional[CostTable] = None,
        mesh_shape: Tuple[int, int] = (1, 1),
    ):
        self.table = table or CostTable()
        self.mesh_shape = (int(mesh_shape[0]), int(mesh_shape[1] or 1))

    # -- shape replication --------------------------------------------------

    def stacked_shape(
        self, m: int, n_padded: int, batch_size: int
    ) -> Tuple[int, int]:
        """``(m_total, n_total)`` after the trainer's mesh rounding
        (mirrors ``FleetTrainer._stack_bucket``): the model axis pads to
        a multiple of the mesh's model axis, the sample axis to a whole
        number of batches that also divides across the data axis."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        n_total = -(-n_padded // step) * step
        return m_total, n_total

    def stacked_windowed_shape(
        self, m: int, n_padded: int, offset: int, batch_size: int
    ) -> Tuple[int, int, int]:
        """``(m_total, series_rows, windows_total)`` after the trainer's
        windowed-stacker rounding (mirrors
        ``FleetTrainer._stack_windowed_bucket``): the series axis stays
        at ``n_padded`` exactly; only the virtual window axis mesh-rounds."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        nv_total = -(-(n_padded - offset) // step) * step
        return m_total, n_padded, nv_total

    # -- analytic estimates -------------------------------------------------

    def train_flops(
        self, spec: ModelSpec, m: int, n: int, epochs: int
    ) -> float:
        """Training FLOPs for ``m`` members × ``n`` (virtual) samples ×
        ``epochs`` epochs at this spec."""
        return (
            _TRAIN_FLOP_FACTOR
            * spec_flops_per_sample(spec)
            * float(m)
            * float(n)
            * float(max(epochs, 1))
        )

    def predict_run_s(
        self, program: str, spec: ModelSpec, m_total: int, n_total: int, epochs: int
    ) -> float:
        flops = self.train_flops(spec, m_total, n_total, epochs)
        factor = self.table.run_factors.get(program, 1.0)
        return factor * (flops / self.table.throughput) + self.table.dispatch_s

    def predict_compile_s(self, program: str, spec: ModelSpec) -> float:
        factor = self.table.compile_factors.get(program, 1.0)
        return factor * (
            self.table.compile_floor_s
            + self.table.compile_per_flop * spec_flops_per_sample(spec)
        )

    def predict_hbm_bytes(
        self,
        spec: ModelSpec,
        m_total: int,
        n_total: int,
        batch_size: int,
        y_aliased: bool = True,
        series_rows: Optional[int] = None,
    ) -> int:
        """Resident device bytes of one bucket's training program:
        staged data + per-member params × optimizer copies + one batch
        of activations. ``series_rows`` switches to the windowed layout
        (series resident instead of materialized windows)."""
        f_in = getattr(spec, "n_features", 1)
        f_out = getattr(spec, "n_features_out", f_in)
        if series_rows is not None:
            data = m_total * series_rows * f_in + m_total * n_total * f_out
        else:
            data = m_total * n_total * f_in
            if not y_aliased:
                data += m_total * n_total * f_out
        data += 3 * m_total * n_total  # train/val weights + epoch bookkeeping
        params = spec_param_count(spec) * m_total * _OPTIMIZER_COPIES
        width = max(
            [f_in, f_out, *getattr(spec, "dims", ())] or [1]
        )
        lookback = getattr(spec, "lookback_window", 1)
        activations = m_total * batch_size * width * (
            len(getattr(spec, "dims", ())) + 2
        ) * lookback
        return 4 * int(data + params + activations)  # float32


def calibrate(
    trace_path: str, table: Optional[CostTable] = None
) -> CostTable:
    """
    Fit per-program correction factors from a ``build_trace.jsonl``.

    Reads every ``device_program`` span carrying the planner's static
    features (``params``/``flops_per_sample``/``members``/``epochs``,
    recorded by the trainer's program spans), splits them into compile
    (first call per signature) and run samples, and sets each program's
    factor to the MEDIAN of actual/analytic ratios — median, not mean,
    because a shared host's neighbor stalls put multi-second one-sided
    outliers into any wall-clock sample set.

    Returns a new :class:`CostTable`; the input ``table`` (default: the
    analytic defaults) provides the baseline constants the factors
    correct. Spans missing the static features (older traces) are
    skipped.
    """
    base = table or CostTable()
    model = CostModel(CostTable(  # factor-free baseline for the ratios
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
    ))
    run_ratios: Dict[str, list] = {}
    compile_ratios: Dict[str, list] = {}
    counts: Dict[str, int] = {}
    for span in _iter_spans(trace_path):
        if span.get("name") != "device_program":
            continue
        attrs = span.get("attributes") or {}
        program = str(attrs.get("program", ""))
        flops_per_sample = attrs.get("flops_per_sample")
        if not program or flops_per_sample is None:
            continue
        try:
            m = int(attrs.get("stacked_members") or attrs.get("members") or 0)
            n = int(attrs.get("stacked_samples") or 0)
            epochs = int(attrs.get("epochs") or 1)
            seconds = float(span.get("duration_ms") or 0.0) / 1000.0
            flops_per_sample = float(flops_per_sample)
        except (TypeError, ValueError):
            continue
        if m <= 0 or n <= 0 or seconds <= 0.0:
            continue
        counts[program] = counts.get(program, 0) + 1
        flops = _TRAIN_FLOP_FACTOR * flops_per_sample * m * n * max(epochs, 1)
        analytic_run = flops / base.throughput + base.dispatch_s
        if attrs.get("compile"):
            analytic_compile = (
                base.compile_floor_s + base.compile_per_flop * flops_per_sample
            )
            # the first call is trace+compile+first run; subtract the
            # analytic run share so the factor corrects the compile part
            compile_ratios.setdefault(program, []).append(
                max(seconds - analytic_run, 1e-3) / analytic_compile
            )
        else:
            run_ratios.setdefault(program, []).append(seconds / analytic_run)

    def medians(ratios: Dict[str, list]) -> Dict[str, float]:
        out = {}
        for program, values in ratios.items():
            values = sorted(values)
            out[program] = round(values[len(values) // 2], 6)
        return out

    calibrated = CostTable(
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
        run_factors=medians(run_ratios),
        compile_factors=medians(compile_ratios),
        samples=counts,
    )
    logger.info(
        "Calibrated cost table from %s: %d program kind(s), %d span(s)",
        trace_path,
        len(counts),
        sum(counts.values()),
    )
    return calibrated


def _iter_spans(trace_path: str) -> Iterable[dict]:
    with open(trace_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn tail from a killed build
            if isinstance(doc, dict):
                yield doc
