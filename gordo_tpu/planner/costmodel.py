"""
Analytic bucket cost model with trace-fitted correction factors.

Per-program TPU cost is predictable from static features plus a small
calibration set (the learned-performance-model line of work, PAPERS.md).
This module is the smallest useful instance of that recipe:

- **static features**: parameter count and padded training FLOPs derived
  from the spec geometry alone (:func:`spec_param_count`,
  :func:`spec_flops_per_sample`) — the planner never traces or compiles
  anything to cost a candidate bucket;
- **calibration**: :func:`calibrate` fits per-program correction factors
  from the ``device_program`` spans PR 3's telemetry already records in
  ``build_trace.jsonl`` (first-call-per-signature spans are compiles,
  the rest steady-state runs), and persists them as a versioned
  ``cost_table.json``.

Absolute accuracy is NOT the point — bucket *ranking* is. The packer
only ever compares candidate buckets of the same fleet against each
other, so a constant-factor error cancels; the calibration exists to
keep the compile-vs-run trade (the compile-budget knob) honest on the
actual backend.
"""

import json
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..models.spec import FeedForwardSpec, LSTMSpec, ModelSpec
from ..utils.env import env_bool

logger = logging.getLogger(__name__)

#: canonical calibrated-table filename (beside the trace it was fit from)
COST_TABLE_FILE = "cost_table.json"

#: cost_table.json schema version — bump on shape changes so stale
#: tables are rejected instead of silently misread
COST_TABLE_VERSION = 1

#: master switch for the LEARNED performance model (PR 20): when on, a
#: cost table carrying a fitted ``learned`` section answers predictions
#: from its log-linear regressors (in-domain) instead of the analytic
#: formula. Off (the default) the learned section is inert — plans and
#: ladder choices are byte-identical to the analytic model's.
PERFMODEL_ENV = "GORDO_TPU_PERFMODEL"

#: ``learned`` section schema version inside cost_table.json — the
#: section versions independently of the table (an old table with no
#: section stays loadable; a future section shape downgrades to the
#: analytic fallback with a warning instead of rejecting the table)
LEARNED_VERSION = 1

#: the shared feature vocabulary: the FIT side (gordo_tpu.perfmodel)
#: and the EVAL side (this module) must agree on the vector, and the
#: layering contract forbids planner->perfmodel imports — so the
#: vocabulary lives here, at the bottom, and perfmodel reads it from
#: below exactly like serve reads PRECISION_ALIASES
LEARNED_FEATURES: Tuple[str, ...] = (
    "log_flops_per_sample",
    "log_members",
    "log_rows",
    "log_epochs",
    "bf16",
    "int8",
)

#: prediction targets a learned section may carry, with their units
LEARNED_TARGETS: Tuple[str, ...] = ("device_ms", "compile_ms", "hbm_bytes")

#: extrapolation slack in log space around the training corpus's
#: per-feature [lo, hi] box: ~5x beyond the largest trained shape still
#: answers learned, further falls back analytic (a regressor fit on
#: 8-member buckets has no business costing a 4096-member one)
LEARNED_DOMAIN_SLACK = 1.6

#: Adam keeps params + grads + two moment vectors resident per member
_OPTIMIZER_COPIES = 4

#: backward pass ≈ 2x the forward FLOPs (grad wrt inputs + weights)
_TRAIN_FLOP_FACTOR = 3.0

#: resident WEIGHT bytes per element at each serving precision (the
#: serve engine's precision ladder): int8 weight-only quantization
#: additionally keeps a per-channel f32 scale, accounted separately in
#: :meth:`CostModel.serve_weight_bytes`
PRECISION_WEIGHT_BYTES: Dict[str, int] = {"f32": 4, "bf16": 2, "int8": 1}

#: activation/compute bytes per element: int8 serving runs its
#: activations in bf16 (weight-only quantization), so its compute width
#: is bf16's
PRECISION_COMPUTE_BYTES: Dict[str, int] = {"f32": 4, "bf16": 2, "int8": 2}

#: THE canonical precision-alias table. It lives HERE (not in
#: gordo_tpu.serve.precision, which re-imports it) because the layering
#: contract forbids planner→serve imports even lazily — the cost model
#: is the lowest layer that speaks precision, so it owns the vocabulary
#: and the serve package reads it from below.
PRECISION_ALIASES: Dict[str, str] = {
    "f32": "f32", "fp32": "f32", "float32": "f32",
    "bf16": "bf16", "bfloat16": "bf16",
    "int8": "int8", "i8": "int8", "w8": "int8",
}

#: analytic default per-precision step-time factors (shared by the
#: CostTable field default and the legacy-table load path)
DEFAULT_PRECISION_FACTORS: Dict[str, float] = {"bf16": 0.6, "int8": 0.55}


def perfmodel_enabled() -> bool:
    """The ``GORDO_TPU_PERFMODEL`` master switch (default off)."""
    return env_bool(PERFMODEL_ENV, False)


def learned_feature_vector(
    flops_per_sample: float,
    members: int,
    rows: int,
    epochs: int = 1,
    precision: Optional[str] = None,
) -> List[float]:
    """The :data:`LEARNED_FEATURES` vector for one program shape — the
    log-linear regressor's input, shared verbatim by the fit side
    (``gordo_tpu.perfmodel``) and this module's evaluation.

    >>> [round(v, 3) for v in learned_feature_vector(100.0, 8, 512)]
    [4.615, 2.079, 6.238, 0.0, 0.0, 0.0]
    """
    prec = normalize_precision(precision)
    return [
        math.log(max(float(flops_per_sample), 0.0) + 1.0),
        math.log(max(int(members), 1)),
        math.log(max(int(rows), 1)),
        math.log(max(int(epochs), 1)),
        1.0 if prec == "bf16" else 0.0,
        1.0 if prec == "int8" else 0.0,
    ]


def validate_learned_section(doc: object) -> Optional[dict]:
    """A usable ``learned`` section dict, or None (with ONE warning) for
    anything malformed — a truncated/mis-versioned/hand-edited section
    must downgrade to the analytic fallback, never traceback in the
    planner, the serve engine, or the lifecycle supervisor."""
    if doc is None:
        return None
    try:
        if not isinstance(doc, dict):
            raise ValueError(f"learned section is {type(doc).__name__}, not dict")
        version = int(doc.get("version", 0))
        if version != LEARNED_VERSION:
            raise ValueError(
                f"learned section version {version} != supported "
                f"{LEARNED_VERSION}"
            )
        features = tuple(str(f) for f in (doc.get("features") or ()))
        if features != LEARNED_FEATURES:
            raise ValueError(
                f"learned feature vocabulary {features!r} != "
                f"{LEARNED_FEATURES!r}"
            )
        width = len(LEARNED_FEATURES)
        targets = doc.get("targets")
        if not isinstance(targets, dict):
            raise ValueError("learned section carries no targets map")
        for target, programs in targets.items():
            if target not in LEARNED_TARGETS:
                raise ValueError(f"unknown learned target {target!r}")
            if not isinstance(programs, dict):
                raise ValueError(f"target {target!r} is not a program map")
            for program, entry in programs.items():
                coef = [float(c) for c in entry["coef"]]
                lo = [float(v) for v in entry["lo"]]
                hi = [float(v) for v in entry["hi"]]
                if len(coef) != width + 1 or len(lo) != width or len(hi) != width:
                    raise ValueError(
                        f"model {target}/{program} has wrong arity"
                    )
                if not all(math.isfinite(c) for c in coef):
                    raise ValueError(
                        f"model {target}/{program} has non-finite coefficients"
                    )
        return doc
    except (TypeError, ValueError, KeyError) as exc:
        logger.warning(
            "Ignoring unusable learned section in cost table (%s); "
            "falling back to the analytic model",
            exc,
        )
        return None


def normalize_precision(precision: Optional[str]) -> str:
    """Canonical precision key (``float32``→``f32``, ``bfloat16``→
    ``bf16``); unknown/empty values cost as f32 — the conservative
    (widest) estimate."""
    if not precision:
        return "f32"
    return PRECISION_ALIASES.get(str(precision).strip().lower(), "f32")


def compute_precision(spec: ModelSpec) -> str:
    """The precision feature of a spec's TRAINING programs, derived from
    its ``compute_dtype`` (bf16 compute halves activation traffic even
    though master params stay f32 — models/nn.py dtype contract)."""
    return normalize_precision(getattr(spec, "compute_dtype", "float32"))


def spec_param_count(spec: ModelSpec) -> int:
    """Trainable parameter count from the spec geometry alone."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return sum(
            d_in * d_out + d_out for d_in, d_out in zip(dims[:-1], dims[1:])
        )
    if isinstance(spec, LSTMSpec):
        total = 0
        d_in = spec.n_features
        for d_h in spec.dims:
            # 4 gates, each [d_in + d_h, d_h] + bias
            total += 4 * (d_in * d_h + d_h * d_h + d_h)
            d_in = d_h
        total += d_in * spec.n_features_out + spec.n_features_out
        return total
    # Unknown spec types (future architectures): no geometry knowledge —
    # callers treat 0 as "cost unknown, keep the member in its own group".
    return 0


def spec_flops_per_sample(spec: ModelSpec) -> float:
    """Forward-pass FLOPs for ONE sample (one window for LSTM specs —
    the recurrence runs ``lookback_window`` steps per window)."""
    if isinstance(spec, FeedForwardSpec):
        dims = (spec.n_features,) + tuple(spec.dims) + (spec.n_features_out,)
        return float(
            sum(2 * d_in * d_out for d_in, d_out in zip(dims[:-1], dims[1:]))
        )
    if isinstance(spec, LSTMSpec):
        per_step = 0.0
        d_in = spec.n_features
        for d_h in spec.dims:
            per_step += 2.0 * 4 * (d_in + d_h) * d_h
            d_in = d_h
        head = 2.0 * d_in * spec.n_features_out
        return per_step * spec.lookback_window + head
    # ~2 FLOPs per parameter per sample is the dense-layer identity;
    # use it as the generic fallback.
    return 2.0 * spec_param_count(spec)


@dataclass
class CostTable:
    """Versioned correction factors fit by :func:`calibrate`.

    ``run_factors``/``compile_factors`` map program name (``fleet_fit``,
    ``fleet_windowed_fit``, ...) to a multiplicative correction on the
    analytic estimate; unseen programs fall back to 1.0. ``throughput``
    and ``compile_per_flop`` are the analytic baseline constants the
    factors correct — persisted so a table is self-contained.
    """

    #: sustained training throughput (FLOP/s) the analytic model divides
    #: by; deliberately conservative-CPU-ish so an UNcalibrated model
    #: still ranks buckets sanely on the test backend
    throughput: float = 2.0e9
    #: seconds of XLA compile per traced FLOP-per-sample unit, plus a
    #: fixed per-program floor — compiles scale with program complexity
    #: (op count ~ layer count ~ flops/sample), not with data volume
    compile_per_flop: float = 2.0e-7
    compile_floor_s: float = 0.35
    #: per-program-dispatch fixed overhead (host dispatch + fetch)
    dispatch_s: float = 0.01
    run_factors: Dict[str, float] = field(default_factory=dict)
    compile_factors: Dict[str, float] = field(default_factory=dict)
    #: per-precision multiplicative correction on predicted step time —
    #: the precision FEATURE of the cost model. Defaults assume the
    #: HBM-bound tiny-model regime (bf16 halves re-read bytes but not
    #: to 0.5x — dispatch and host shares don't scale; int8's dequant
    #: claws some back). Unlisted precisions (and f32) cost 1.0;
    #: recalibrate per backend like every other factor.
    precision_factors: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_PRECISION_FACTORS)
    )
    #: calibration provenance: sample counts per program
    samples: Dict[str, int] = field(default_factory=dict)
    #: the fitted learned-regressor section (PR 20), or None for a
    #: purely analytic/median-factor table — see
    #: :func:`validate_learned_section` for the schema. Inert unless
    #: ``GORDO_TPU_PERFMODEL`` is on.
    learned: Optional[dict] = None
    version: int = COST_TABLE_VERSION

    def precision_factor(self, precision: Optional[str]) -> float:
        return float(
            self.precision_factors.get(normalize_precision(precision), 1.0)
        )

    # -- learned-section evaluation -----------------------------------------

    def learned_entry(self, target: str, program: str) -> Optional[dict]:
        """The fitted model for ``(target, program)``, or None."""
        if not self.learned:
            return None
        return (self.learned.get("targets") or {}).get(target, {}).get(
            program
        )

    def learned_predict(
        self, target: str, program: str, features: Sequence[float]
    ) -> Optional[float]:
        """Evaluate the fitted log-linear model for ``(target,
        program)`` on a :func:`learned_feature_vector`: ``exp(intercept
        + coef·x)`` in the target's unit (ms or bytes). None when no
        model is fitted, the shape is out of the training domain, or the
        evaluation misbehaves — every None falls back analytic."""
        entry = self.learned_entry(target, program)
        if entry is None:
            return None
        try:
            lo, hi = entry["lo"], entry["hi"]
            for x, lo_i, hi_i in zip(features, lo, hi):
                if not (
                    lo_i - LEARNED_DOMAIN_SLACK
                    <= x
                    <= hi_i + LEARNED_DOMAIN_SLACK
                ):
                    return None
            coef = entry["coef"]
            z = float(coef[0]) + sum(
                float(c) * float(x) for c, x in zip(coef[1:], features)
            )
            value = math.exp(z)
        except (TypeError, ValueError, KeyError, IndexError, OverflowError):
            return None
        if not math.isfinite(value) or value < 0.0:
            return None
        return value

    def to_dict(self) -> dict:
        doc = {
            "version": self.version,
            "throughput": self.throughput,
            "compile_per_flop": self.compile_per_flop,
            "compile_floor_s": self.compile_floor_s,
            "dispatch_s": self.dispatch_s,
            "run_factors": dict(sorted(self.run_factors.items())),
            "compile_factors": dict(sorted(self.compile_factors.items())),
            "precision_factors": dict(sorted(self.precision_factors.items())),
            "samples": dict(sorted(self.samples.items())),
        }
        if self.learned is not None:
            doc["learned"] = self.learned
        return doc

    @classmethod
    def from_dict(cls, doc: dict) -> "CostTable":
        version = int(doc.get("version", 0))
        if version != COST_TABLE_VERSION:
            raise ValueError(
                f"cost table version {version} != supported "
                f"{COST_TABLE_VERSION}; re-run calibration"
            )
        return cls(
            throughput=float(doc.get("throughput", cls.throughput)),
            compile_per_flop=float(
                doc.get("compile_per_flop", cls.compile_per_flop)
            ),
            compile_floor_s=float(doc.get("compile_floor_s", cls.compile_floor_s)),
            dispatch_s=float(doc.get("dispatch_s", cls.dispatch_s)),
            run_factors={
                str(k): float(v) for k, v in (doc.get("run_factors") or {}).items()
            },
            compile_factors={
                str(k): float(v)
                for k, v in (doc.get("compile_factors") or {}).items()
            },
            # pre-precision tables (PR ≤13) carry no factor map: they
            # load with the analytic defaults rather than being rejected
            precision_factors={
                str(k): float(v)
                for k, v in (
                    doc.get("precision_factors") or DEFAULT_PRECISION_FACTORS
                ).items()
            },
            samples={
                str(k): int(v) for k, v in (doc.get("samples") or {}).items()
            },
            # a bad learned section degrades (warn + analytic), it never
            # rejects the table: the median factors are still good
            learned=validate_learned_section(doc.get("learned")),
            version=version,
        )

    def save(self, path: str) -> None:
        payload = json.dumps(self.to_dict(), indent=1, sort_keys=True) + "\n"
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "CostTable":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @property
    def calibrated(self) -> bool:
        return bool(self.run_factors or self.compile_factors)

    @property
    def has_learned(self) -> bool:
        return bool(
            self.learned and (self.learned.get("targets") or {})
        )


def load_table_safe(path: Optional[str]) -> CostTable:
    """A :class:`CostTable` from ``path`` that NEVER raises: a missing,
    truncated, torn or mis-versioned ``cost_table.json`` warns once and
    answers the analytic defaults — the contract the serve engine, the
    stream scorer and the lifecycle supervisor load through (a corrupt
    table must degrade predictions, not take down serving)."""
    if not path:
        return CostTable()
    try:
        return CostTable.load(path)
    except (OSError, ValueError, TypeError, KeyError) as exc:
        logger.warning(
            "Unusable cost table %s (%s); using the analytic defaults",
            path,
            exc,
        )
        return CostTable()


class CostModel:
    """Bucket-shape cost estimates against a :class:`CostTable`.

    ``mesh_shape`` is the trainer mesh's ``(model_axis, data_axis)`` —
    the estimator replicates the trainer's shape rounding so predicted
    program signatures (and therefore compile counts) match what XLA
    will actually see.
    """

    def __init__(
        self,
        table: Optional[CostTable] = None,
        mesh_shape: Tuple[int, int] = (1, 1),
        use_learned: Optional[bool] = None,
    ):
        self.table = table or CostTable()
        self.mesh_shape = (int(mesh_shape[0]), int(mesh_shape[1] or 1))
        #: learned-section participation, resolved ONCE at construction
        #: (``GORDO_TPU_PERFMODEL`` unless the caller pins it) so one
        #: model instance answers consistently for its whole lifetime —
        #: a plan costed half-analytic, half-learned would rank buckets
        #: against each other with two different rulers
        self.use_learned = (
            perfmodel_enabled() if use_learned is None else bool(use_learned)
        )

    def _learned(
        self,
        target: str,
        program: str,
        spec: ModelSpec,
        members: int,
        rows: int,
        epochs: int = 1,
        precision: Optional[str] = None,
    ) -> Optional[float]:
        """One knob-gated learned lookup; None means 'answer analytic'."""
        if not self.use_learned:
            return None
        return self.table.learned_predict(
            target,
            program,
            learned_feature_vector(
                spec_flops_per_sample(spec), members, rows, epochs, precision
            ),
        )

    # -- shape replication --------------------------------------------------

    def stacked_shape(
        self, m: int, n_padded: int, batch_size: int
    ) -> Tuple[int, int]:
        """``(m_total, n_total)`` after the trainer's mesh rounding
        (mirrors ``FleetTrainer._stack_bucket``): the model axis pads to
        a multiple of the mesh's model axis, the sample axis to a whole
        number of batches that also divides across the data axis."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        n_total = -(-n_padded // step) * step
        return m_total, n_total

    def stacked_windowed_shape(
        self, m: int, n_padded: int, offset: int, batch_size: int
    ) -> Tuple[int, int, int]:
        """``(m_total, series_rows, windows_total)`` after the trainer's
        windowed-stacker rounding (mirrors
        ``FleetTrainer._stack_windowed_bucket``): the series axis stays
        at ``n_padded`` exactly; only the virtual window axis mesh-rounds."""
        model_axis, data_axis = self.mesh_shape
        m_total = -(-m // model_axis) * model_axis
        step = abs(batch_size * data_axis) // math.gcd(batch_size, data_axis)
        nv_total = -(-(n_padded - offset) // step) * step
        return m_total, n_padded, nv_total

    # -- analytic estimates -------------------------------------------------

    def train_flops(
        self, spec: ModelSpec, m: int, n: int, epochs: int
    ) -> float:
        """Training FLOPs for ``m`` members × ``n`` (virtual) samples ×
        ``epochs`` epochs at this spec."""
        return (
            _TRAIN_FLOP_FACTOR
            * spec_flops_per_sample(spec)
            * float(m)
            * float(n)
            * float(max(epochs, 1))
        )

    def predict_run_s(
        self,
        program: str,
        spec: ModelSpec,
        m_total: int,
        n_total: int,
        epochs: int,
        precision: Optional[str] = None,
    ) -> float:
        """``precision`` is the program's compute precision (defaults to
        the spec's own ``compute_dtype``) — a feature of predicted step
        cost, corrected by the table's per-precision factor."""
        if precision is None:
            precision = compute_precision(spec)
        learned = self._learned(
            "device_ms", program, spec, m_total, n_total, epochs, precision
        )
        if learned is not None:
            return learned / 1000.0
        flops = self.train_flops(spec, m_total, n_total, epochs)
        factor = self.table.run_factors.get(program, 1.0)
        factor *= self.table.precision_factor(precision)
        return factor * (flops / self.table.throughput) + self.table.dispatch_s

    def predict_compile_s(self, program: str, spec: ModelSpec) -> float:
        # compile cost scales with program complexity, not data volume:
        # the learned model is keyed on the same static features with
        # the shape axes pinned to 1 (the fit side mirrors this)
        learned = self._learned("compile_ms", program, spec, 1, 1)
        if learned is not None:
            return learned / 1000.0
        factor = self.table.compile_factors.get(program, 1.0)
        return factor * (
            self.table.compile_floor_s
            + self.table.compile_per_flop * spec_flops_per_sample(spec)
        )

    def predict_hbm_bytes(
        self,
        spec: ModelSpec,
        m_total: int,
        n_total: int,
        batch_size: int,
        y_aliased: bool = True,
        series_rows: Optional[int] = None,
        precision: Optional[str] = None,
    ) -> int:
        """Resident device bytes of one bucket's training program:
        staged data + per-member params × optimizer copies + one batch
        of activations. ``series_rows`` switches to the windowed layout
        (series resident instead of materialized windows).

        ``precision`` (default: the spec's ``compute_dtype``) scales the
        ACTIVATION bytes — bf16 compute halves them, which changes how
        many members fit under the packer's HBM cap. Master params and
        staged f32 data keep full width during training (the models/nn
        mixed-precision contract: params never store reduced)."""
        if precision is None:
            precision = compute_precision(spec)
        learned = self._learned(
            "hbm_bytes",
            "fleet_windowed_fit" if series_rows is not None else "fleet_fit",
            spec,
            m_total,
            n_total,
            1,
            precision,
        )
        if learned is not None:
            return int(learned)
        f_in = getattr(spec, "n_features", 1)
        f_out = getattr(spec, "n_features_out", f_in)
        if series_rows is not None:
            data = m_total * series_rows * f_in + m_total * n_total * f_out
        else:
            data = m_total * n_total * f_in
            if not y_aliased:
                data += m_total * n_total * f_out
        data += 3 * m_total * n_total  # train/val weights + epoch bookkeeping
        params = spec_param_count(spec) * m_total * _OPTIMIZER_COPIES
        width = max(
            [f_in, f_out, *getattr(spec, "dims", ())] or [1]
        )
        lookback = getattr(spec, "lookback_window", 1)
        activations = m_total * batch_size * width * (
            len(getattr(spec, "dims", ())) + 2
        ) * lookback
        compute_bytes = PRECISION_COMPUTE_BYTES.get(
            normalize_precision(precision), 4
        )
        return int(4 * (data + params) + compute_bytes * activations)

    # -- serve-side estimates (the engine's precision ladder) ---------------

    def serve_weight_bytes(
        self, spec: ModelSpec, members: int, precision: str = "f32"
    ) -> int:
        """Resident weight bytes of one revision bucket at a serving
        precision: bf16 halves them, int8 quarters them (plus the
        per-channel f32 scales — one scale per output unit per member).
        This is the number the precision ladder exists to shrink: the
        HBM traffic every fused batch re-reads."""
        precision = normalize_precision(precision)
        weight_bytes = PRECISION_WEIGHT_BYTES.get(precision, 4)
        params = spec_param_count(spec) * members
        scales = 0
        if precision == "int8":
            dims = tuple(getattr(spec, "dims", ())) + (
                getattr(spec, "n_features_out", 1),
            )
            scales = 4 * members * sum(dims)  # f32 scale per out channel
        return int(weight_bytes * params + scales)

    def predict_serve_hbm_bytes(
        self, spec: ModelSpec, members: int, rows: int, precision: str = "f32"
    ) -> int:
        """Resident bytes of one fused serving batch: the precision's
        weight bucket + the staged payload at the compute width + the
        f32 output."""
        precision = normalize_precision(precision)
        learned = self._learned(
            "hbm_bytes", "fleet_forward", spec, members, rows, 1, precision
        )
        if learned is not None:
            return int(learned)
        f_in = getattr(spec, "n_features", 1)
        f_out = getattr(spec, "n_features_out", f_in)
        compute_bytes = PRECISION_COMPUTE_BYTES.get(precision, 4)
        payload = compute_bytes * members * rows * f_in
        output = 4 * members * rows * f_out  # always float32 out
        return self.serve_weight_bytes(spec, members, precision) + payload + output

    def predict_serve_step_s(
        self, spec: ModelSpec, members: int, rows: int, precision: str = "f32"
    ) -> float:
        """Predicted wall seconds of one fused serving batch (forward
        only — no train factor), with precision as a feature: the
        engine stamps this next to the measured device time on every
        batch span (predicted-vs-actual on the new axis)."""
        learned = self._learned(
            "device_ms", "fleet_forward", spec, members, rows, 1, precision
        )
        if learned is not None:
            return learned / 1000.0
        flops = spec_flops_per_sample(spec) * float(members) * float(rows)
        factor = self.table.run_factors.get("fleet_forward", 1.0)
        factor *= self.table.precision_factor(precision)
        return factor * (flops / self.table.throughput) + self.table.dispatch_s


def calibrate(
    trace_path: str, table: Optional[CostTable] = None
) -> CostTable:
    """
    Fit per-program correction factors from a ``build_trace.jsonl``.

    Reads every ``device_program`` span carrying the planner's static
    features (``params``/``flops_per_sample``/``members``/``epochs``,
    recorded by the trainer's program spans), splits them into compile
    (first call per signature) and run samples, and sets each program's
    factor to the MEDIAN of actual/analytic ratios — median, not mean,
    because a shared host's neighbor stalls put multi-second one-sided
    outliers into any wall-clock sample set.

    Returns a new :class:`CostTable`; the input ``table`` (default: the
    analytic defaults) provides the baseline constants the factors
    correct. Spans missing the static features (older traces) are
    skipped.
    """
    base = table or CostTable()
    model = CostModel(CostTable(  # factor-free baseline for the ratios
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
    ))
    run_ratios: Dict[str, list] = {}
    compile_ratios: Dict[str, list] = {}
    counts: Dict[str, int] = {}
    for span in _iter_spans(trace_path):
        if span.get("name") != "device_program":
            continue
        attrs = span.get("attributes") or {}
        program = str(attrs.get("program", ""))
        flops_per_sample = attrs.get("flops_per_sample")
        if not program or flops_per_sample is None:
            continue
        try:
            m = int(attrs.get("stacked_members") or attrs.get("members") or 0)
            n = int(attrs.get("stacked_samples") or 0)
            epochs = int(attrs.get("epochs") or 1)
            # prefer the device-measured time when the span carries one;
            # a span whose device_ms is present but zero/negative is a
            # broken sample and is SKIPPED — folding its wall-clock
            # duration into the median would let dispatch/queue noise
            # masquerade as device time
            device_ms = attrs.get("device_ms")
            if device_ms is not None:
                seconds = float(device_ms) / 1000.0
            else:
                seconds = float(span.get("duration_ms") or 0.0) / 1000.0
            flops_per_sample = float(flops_per_sample)
        except (TypeError, ValueError):
            continue
        if m <= 0 or n <= 0 or seconds <= 0.0:
            continue
        counts[program] = counts.get(program, 0) + 1
        flops = _TRAIN_FLOP_FACTOR * flops_per_sample * m * n * max(epochs, 1)
        analytic_run = flops / base.throughput + base.dispatch_s
        if attrs.get("compile"):
            analytic_compile = (
                base.compile_floor_s + base.compile_per_flop * flops_per_sample
            )
            # the first call is trace+compile+first run; subtract the
            # analytic run share so the factor corrects the compile part
            compile_ratios.setdefault(program, []).append(
                max(seconds - analytic_run, 1e-3) / analytic_compile
            )
        else:
            run_ratios.setdefault(program, []).append(seconds / analytic_run)

    def medians(ratios: Dict[str, list]) -> Dict[str, float]:
        out = {}
        for program, values in ratios.items():
            values = sorted(values)
            out[program] = round(values[len(values) // 2], 6)
        return out

    calibrated = CostTable(
        throughput=base.throughput,
        compile_per_flop=base.compile_per_flop,
        compile_floor_s=base.compile_floor_s,
        dispatch_s=base.dispatch_s,
        run_factors=medians(run_ratios),
        compile_factors=medians(compile_ratios),
        samples=counts,
    )
    logger.info(
        "Calibrated cost table from %s: %d program kind(s), %d span(s)",
        trace_path,
        len(counts),
        sum(counts.values()),
    )
    return calibrated


def _iter_spans(trace_path: str) -> Iterable[dict]:
    with open(trace_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn tail from a killed build
            if isinstance(doc, dict):
                yield doc
