"""
Shared shape ladders: the one quantization vocabulary for build AND serve.

Every distinct array shape handed to a jitted fleet program mints one
XLA compilation, so both planes quantize their ragged axes up a small
ladder of allowed sizes. This module used to live in ``serve/ladder.py``
(the micro-batcher's member/row ladders); the build planner needs the
same machinery for its sample/series axes, so the implementation moved
here and ``gordo_tpu.serve.ladder`` re-exports it — a fleet planned with
these rungs warms exactly the shapes the serving engine will batch into.

Two ladder families:

- **explicit rung lists** (:func:`parse_ladder`, :data:`DEFAULT_ROW_LADDER`,
  :func:`member_ladder`): serve-side, where the rung count itself is the
  contract (programs per spec ≤ ``|member ladder| × |row ladder|``).
- **geometric rounding** (:func:`round_up_ladder`, :func:`geometric_rungs`):
  build-side, where the axis is open-ended (sample counts, series
  lengths) and what matters is the growth *ratio* — pow2 (ratio 2) can
  nearly double padded work per axis; a 1.25 ladder caps waste at 25%
  for ~3x the distinct shapes, and the planner's compile-budget knob
  then merges rungs back down where the trade is wrong.
"""

from typing import List, Optional, Sequence, Tuple

from ..utils.env import env_float, env_str

#: default row-count rungs: factor-4 geometric — 5 programs per member
#: rung, worst-case 4x row padding, typical sensor payloads (tens to a
#: few thousand rows) land in the first three rungs
DEFAULT_ROW_LADDER: Tuple[int, ...] = (32, 128, 512, 2048, 8192)

ROW_LADDER_ENV = "GORDO_TPU_BATCH_ROW_LADDER"

#: growth ratio for the windowed (LSTM) series axis — pow2 padding on
#: the time axis nearly doubled padded work for long series; 1.25 caps
#: the waste at 25% per member
SERIES_PAD_RATIO_ENV = "GORDO_TPU_SERIES_PAD_RATIO"
DEFAULT_SERIES_PAD_RATIO = 1.25

#: growth ratio for the packed strategy's dense sample axis
SAMPLE_PAD_RATIO_ENV = "GORDO_TPU_PLAN_PAD_RATIO"
DEFAULT_SAMPLE_PAD_RATIO = 1.25


def parse_ladder(text: str) -> Tuple[int, ...]:
    """A comma-separated rung list as a sorted, deduplicated tuple of
    positive ints; raises ``ValueError`` on anything else."""
    rungs = sorted({int(part) for part in text.split(",") if part.strip()})
    if not rungs or rungs[0] <= 0:
        raise ValueError(f"ladder needs positive rungs, got {text!r}")
    return tuple(rungs)


def row_ladder() -> Tuple[int, ...]:
    """The configured row ladder (``GORDO_TPU_BATCH_ROW_LADDER``, falling
    back to :data:`DEFAULT_ROW_LADDER` on absent or malformed values)."""
    raw = env_str(ROW_LADDER_ENV, None)
    if raw:
        try:
            return parse_ladder(raw)
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "Invalid %s=%r; using %r", ROW_LADDER_ENV, raw, DEFAULT_ROW_LADDER
            )
    return DEFAULT_ROW_LADDER


def member_ladder(max_size: int) -> Tuple[int, ...]:
    """Powers of two up to (and including) the padded ``max_size``:
    the allowed member-axis shapes of one fused batch."""
    rungs = []
    rung = 1
    while rung < max_size:
        rungs.append(rung)
        rung <<= 1
    rungs.append(rung)
    return tuple(rungs)


def pad_to(n: int, ladder: Sequence[int]) -> Optional[int]:
    """The first rung >= ``n``, or None when ``n`` overflows the ladder
    (the caller's cue to fall back to an unbatched path)."""
    for rung in ladder:
        if n <= rung:
            return rung
    return None


def snap_rows(
    pending_rows: int,
    window_rows: int,
    ladder: Optional[Sequence[int]] = None,
) -> int:
    """The row count a multi-window stream cut should take from
    ``pending_rows`` buffered rows: the largest whole-window span that
    lands exactly on a serve row-ladder rung (``(rung // window_rows) *
    window_rows`` — the rung's whole-window capacity), so a big backlog
    flush runs the SAME compiled shape the request plane batches into
    instead of minting a worst-case 4x-padded one. Below the smallest
    rung-aligned size the whole backlog is taken (freshness beats
    alignment for small flushes); the un-taken remainder is whole
    windows that ride the next watermark flush.

    >>> snap_rows(224, 32)
    128
    >>> snap_rows(96, 32)
    32
    >>> snap_rows(10, 5)
    10
    >>> snap_rows(3, 5)
    0
    """
    window_rows = int(window_rows)
    if window_rows <= 0:
        return 0
    whole = (int(pending_rows) // window_rows) * window_rows
    if whole <= 0:
        return 0
    rungs = ladder if ladder is not None else row_ladder()
    best = 0
    for rung in rungs:
        aligned = (int(rung) // window_rows) * window_rows
        if 0 < aligned <= whole and aligned > best:
            best = aligned
    return best or whole


# -- geometric rounding (build-side open-ended axes) -------------------------


def round_up_ladder(n: int, ratio: float, multiple: int = 1) -> int:
    """
    The smallest geometric-ladder rung >= ``n``. Rung ``k`` is
    ``multiple * ratio**k`` rounded UP to a multiple of ``multiple`` (so
    every rung is directly usable as a whole number of batches); with
    ratio 2 this reproduces pow2 rounding exactly.

    >>> round_up_ladder(100, 2.0, 16)
    128
    >>> round_up_ladder(1100, 2.0)
    2048
    >>> round_up_ladder(1100, 1.25)
    1263
    """
    import math

    if multiple < 1:
        raise ValueError(f"multiple must be >= 1, got {multiple}")
    ratio = max(float(ratio), 1.0001)
    rung, k = multiple, 0
    while rung < n:
        k += 1
        raw = math.ceil(multiple * ratio**k)
        nxt = -(-raw // multiple) * multiple
        rung = max(nxt, rung + multiple)  # always strictly increasing
    return rung


def geometric_rungs(lo: int, hi: int, ratio: float, multiple: int = 1) -> List[int]:
    """All geometric-ladder rungs covering ``[lo, hi]`` (both rounded up
    onto the ladder) — the candidate shape set a packer chooses from."""
    rungs = [round_up_ladder(max(lo, 1), ratio, multiple)]
    while rungs[-1] < hi:
        rungs.append(round_up_ladder(rungs[-1] + 1, ratio, multiple))
    return rungs


def series_pad_ratio() -> float:
    """Growth ratio for the windowed series axis
    (``GORDO_TPU_SERIES_PAD_RATIO``, default 1.25)."""
    value = env_float(SERIES_PAD_RATIO_ENV, DEFAULT_SERIES_PAD_RATIO)
    return value if value and value > 1.0 else DEFAULT_SERIES_PAD_RATIO


def sample_pad_ratio() -> float:
    """Growth ratio for the packed strategy's dense sample axis
    (``GORDO_TPU_PLAN_PAD_RATIO``, default 1.25)."""
    value = env_float(SAMPLE_PAD_RATIO_ENV, DEFAULT_SAMPLE_PAD_RATIO)
    return value if value and value > 1.0 else DEFAULT_SAMPLE_PAD_RATIO
