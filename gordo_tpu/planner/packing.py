"""
Bucket construction as bin packing.

The trainer's original grouping is syntactic — exact ``(spec,
round_up_pow2(n))`` keys — which fragments heterogeneous fleets into
many compiles and discovers over-packed buckets only reactively (the
device-error bisection ladder). This module makes bucket composition an
explicit optimization with three levers:

- **shape ladders** (:mod:`~gordo_tpu.planner.ladder`): the sample axis
  quantizes up a geometric ladder (default ratio 1.25 — pow2's worst
  case wastes ~2x FLOPs per axis) shared with the serving engine;
- **HBM caps**: members best-fit-decreasing into buckets whose predicted
  resident bytes stay under a cap, splitting *before* the OOM the
  bisection ladder would otherwise pay for (staging + compile + the
  failed run, twice per halving);
- **a compile budget**: every distinct stacked shape mints one XLA
  program, so rungs merge upward (cheapest padding-waste increase
  first) until the planned program count fits the budget — the explicit
  trade between padding waste and compile count. Buckets split under
  the HBM cap additionally pad their member axis to a shared pow2 rung,
  so k same-rung buckets cost one compile, not k.

Strategies: ``naive`` keeps the trainer's historical exact-key grouping
— dense members still pad pow2 bit-for-bit; windowed members now pad
their series axis up the geometric ladder (the deliberate time-axis
fix, so existing LSTM fleets DO get new padded shapes on the default
path) — ``packed`` is the cost-optimized packer. Both are deterministic
in member order.

Known limitation: the cost model prices the plain ``fleet_fit`` /
``fleet_windowed_fit`` programs. When the trainer's block-diagonal MXU
packing kicks in (``GORDO_TPU_PACKING``, g>1) the realized program is
``fleet_packed_fit`` with a different stacked layout, so predictions
for those buckets are approximate — predicted-vs-actual telemetry
still records honestly what ran.

Dependency note: members are duck-typed (``.name``/``.spec``/``.n`` or
``.series``/``.n_windows``) — this module must not import
``gordo_tpu.parallel`` (the trainer imports *us*).
"""

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..models.spec import ModelSpec
from ..utils.env import env_int, env_str
from .costmodel import CostModel
from .ladder import round_up_ladder, sample_pad_ratio, series_pad_ratio

logger = logging.getLogger(__name__)

NAIVE = "naive"
PACKED = "packed"
STRATEGIES = (NAIVE, PACKED)

STRATEGY_ENV = "GORDO_TPU_PLAN_STRATEGY"
COMPILE_BUDGET_ENV = "GORDO_TPU_PLAN_COMPILE_BUDGET"
HBM_CAP_ENV = "GORDO_TPU_PLAN_HBM_CAP_BYTES"

#: default per-bucket resident-bytes cap for the packed strategy — the
#: build-path analog of GORDO_TPU_CV_CHUNK_BYTES, applied to the cost
#: model's predicted footprint (data + optimizer copies + activations),
#: not just raw staged bytes
DEFAULT_HBM_CAP_BYTES = 4 << 30


def default_strategy() -> str:
    """The build-wide strategy (``GORDO_TPU_PLAN_STRATEGY``; default
    ``naive`` — the historical grouping stays the default until a plan
    or an explicit flag opts a build in)."""
    raw = (env_str(STRATEGY_ENV, NAIVE) or NAIVE).strip().lower()
    if raw not in STRATEGIES:
        logger.warning("Invalid %s=%r; using %r", STRATEGY_ENV, raw, NAIVE)
        return NAIVE
    return raw


def compile_budget() -> int:
    """Hard program-count cap for the packed strategy
    (``GORDO_TPU_PLAN_COMPILE_BUDGET``; 0 = no cap, rung merging stops
    at the cost model's compile-vs-padding break-even instead)."""
    return max(0, env_int(COMPILE_BUDGET_ENV, 0))


def hbm_cap_bytes() -> int:
    return max(1 << 20, env_int(HBM_CAP_ENV, DEFAULT_HBM_CAP_BYTES))


def _round_up_pow2(n: int, batch_size: int) -> int:
    """The trainer's historical pad target: next power of two, at least
    one full batch (kept in sync with ``parallel/fleet.py`` via the
    naive-parity test)."""
    target = max(n, batch_size)
    power = 1
    while power < target:
        power <<= 1
    return ((power + batch_size - 1) // batch_size) * batch_size


def member_is_windowed(member: Any) -> bool:
    return hasattr(member, "series")


def member_samples(member: Any) -> int:
    """The member's (virtual) sample count on the padded axis."""
    return len(member.series) if member_is_windowed(member) else member.n


def naive_pad_target(member: Any, batch_size: int) -> int:
    """The naive strategy's pad target for one member — pow2 on the
    dense sample axis, the geometric series ladder on the windowed time
    axis (the pow2 time-axis padding was the measured ~2x waste case)."""
    if member_is_windowed(member):
        return round_up_ladder(len(member.series), series_pad_ratio())
    return _round_up_pow2(member.n, batch_size)


def member_offset(member: Any) -> int:
    if member_is_windowed(member):
        return len(member.series) - member.n_windows
    return 0


def _spec_program(member: Any) -> str:
    return "fleet_windowed_fit" if member_is_windowed(member) else "fleet_fit"


def _member_bytes(cost_model: CostModel, member: Any, n_padded: int, batch: int) -> int:
    """One member's marginal predicted footprint inside a bucket padded
    to ``n_padded`` (the bin-packing item weight)."""
    if member_is_windowed(member):
        return cost_model.predict_hbm_bytes(
            member.spec,
            1,
            n_padded - member_offset(member),
            batch,
            series_rows=n_padded,
        )
    y_aliased = getattr(member, "y", None) is getattr(member, "X", None)
    return cost_model.predict_hbm_bytes(
        member.spec, 1, n_padded, batch, y_aliased=y_aliased
    )


@dataclass
class PlannedBucket:
    """One training bucket the trainer will run as one device program.

    ``n_padded`` is the pre-mesh-rounding sample-axis pad target (the
    bucket key the trainer historically carried); ``m_padded`` an
    optional member-axis pad target (dummy zero-weight members up to a
    shared rung so sibling buckets reuse one compile); ``predicted``
    the cost model's estimates for the *padded* program.
    """

    bucket_id: str
    program: str
    spec: ModelSpec
    members: List[Any]
    n_padded: int
    m_padded: Optional[int] = None
    offset: int = 0
    windowed: bool = False
    predicted: Dict[str, Any] = field(default_factory=dict)

    @property
    def member_names(self) -> List[str]:
        return [m.name for m in self.members]


def _bucket_key(spec: ModelSpec, config: Any) -> str:
    """Deterministic (cross-process) short id for a (spec geometry, fit
    config) pair. The config MUST participate: a FleetPlan holds buckets
    from every fit-config group, and two groups sharing a spec and rung
    would otherwise collide on id — ``materialize_buckets`` keys member
    rosters by id, so a collision trains the pooled members twice."""
    import hashlib

    fit = (
        getattr(config, "epochs", None),
        getattr(config, "batch_size", None),
        getattr(config, "validation_split", None),
        getattr(config, "shuffle", None),
        tuple(getattr(config, "early_stopping", None) or ()) or None,
    )
    return hashlib.sha256(f"{spec!r}|{fit!r}".encode()).hexdigest()[:10]


# -- strategies ---------------------------------------------------------------


def _naive_buckets(members: Sequence[Any], config: Any) -> List[PlannedBucket]:
    """The historical grouping: one bucket per exact
    ``(spec, pad_target[, offset])`` key, members in input order."""
    grouped: Dict[Tuple, List[Any]] = {}
    for member in members:
        key = (
            member.spec,
            naive_pad_target(member, config.batch_size),
            member_offset(member),
            member_is_windowed(member),
        )
        grouped.setdefault(key, []).append(member)
    buckets = []
    for (spec, n_padded, offset, windowed), bucket_members in grouped.items():
        buckets.append(
            PlannedBucket(
                bucket_id=f"{_bucket_key(spec, config)}-n{n_padded}"
                + (f"-o{offset}" if windowed else ""),
                program=_spec_program(bucket_members[0]),
                spec=spec,
                members=bucket_members,
                n_padded=n_padded,
                offset=offset,
                windowed=windowed,
            )
        )
    return buckets


def _packed_buckets(
    members: Sequence[Any],
    config: Any,
    cost_model: CostModel,
    budget: Optional[int] = None,
    hbm_cap: Optional[int] = None,
) -> List[PlannedBucket]:
    budget = compile_budget() if budget is None else budget
    hbm_cap = hbm_cap_bytes() if hbm_cap is None else hbm_cap
    batch = config.batch_size
    input_pos = {m.name: i for i, m in enumerate(members)}

    # 1. quantize each member up the geometric ladder
    rung_groups: Dict[Tuple, List[Any]] = {}
    for member in members:
        if member_is_windowed(member):
            rung = round_up_ladder(len(member.series), series_pad_ratio())
        else:
            rung = round_up_ladder(
                max(member.n, batch), sample_pad_ratio(), multiple=batch
            )
        key = (
            member.spec,
            member_offset(member),
            member_is_windowed(member),
            rung,
        )
        rung_groups.setdefault(key, []).append(member)

    # 2. the compile-vs-padding trade: merging a rung into the next one
    #    up (within one (spec, offset) family — shapes across specs can
    #    never merge) removes one compiled program at the price of extra
    #    padded samples for the merged members. Merge while the cost
    #    model says the compile saved outweighs the run time added
    #    (cheapest merge first); with an explicit ``budget``, keep
    #    merging past break-even until the program count fits.
    def _candidate_merges():
        families: Dict[Tuple, List[Tuple]] = {}
        for key in rung_groups:
            families.setdefault(key[:3], []).append(key)
        merges = []  # (added_run_s, compile_saved_s, src_key, dst_key)
        for family_keys in families.values():
            family_keys.sort(key=lambda k: k[3])
            for src, dst in zip(family_keys[:-1], family_keys[1:]):
                spec, _, windowed, _ = src
                program = "fleet_windowed_fit" if windowed else "fleet_fit"
                added_flops = (
                    (dst[3] - src[3])
                    * len(rung_groups[src])
                    * cost_model.train_flops(spec, 1, 1, config.epochs)
                )
                added_run_s = (
                    cost_model.table.run_factors.get(program, 1.0)
                    * added_flops
                    / cost_model.table.throughput
                )
                compile_saved_s = cost_model.predict_compile_s(program, spec)
                merges.append((added_run_s, compile_saved_s, src, dst))
        return merges

    while len(rung_groups) > 1:
        merges = _candidate_merges()
        if not merges:
            break
        if budget and len(rung_groups) > budget:
            # forced past break-even: take the cheapest padding increase
            # (index tiebreak keeps ties deterministic — spec keys are
            # not orderable)
            pick = min(
                range(len(merges)), key=lambda i: (merges[i][0], i)
            )
        else:
            # voluntary: take the largest net win across ALL families —
            # a family whose cheapest-padding merge is unprofitable must
            # not mask a profitable merge elsewhere
            pick = max(
                range(len(merges)),
                key=lambda i: (merges[i][1] - merges[i][0], -i),
            )
            added_run_s, compile_saved_s = merges[pick][:2]
            if added_run_s >= compile_saved_s:
                break  # padding now costs more than any compile it saves
        _, _, src, dst = merges[pick]
        rung_groups[dst] = rung_groups[dst] + rung_groups.pop(src)

    # 3. HBM cap: best-fit-decreasing inside each rung group, splitting
    #    BEFORE the program would out-size device memory.
    buckets: List[PlannedBucket] = []
    for (spec, offset, windowed, rung), group in rung_groups.items():
        # rung merges append groups out of input order; restore it so
        # bucket rosters (and the plan JSON) are input-order stable
        group = sorted(group, key=lambda m: input_pos[m.name])
        weights = {
            m.name: _member_bytes(cost_model, m, rung, batch) for m in group
        }
        order = sorted(
            range(len(group)), key=lambda i: (-weights[group[i].name], i)
        )
        bins: List[Tuple[List[Any], int]] = []  # (members, used_bytes)
        for i in order:
            member = group[i]
            size = weights[member.name]
            best_bin = None
            for b, (bin_members, used) in enumerate(bins):
                if used + size <= hbm_cap:
                    if best_bin is None or used > bins[best_bin][1]:
                        best_bin = b
            if best_bin is None:
                bins.append(([member], size))
            else:
                bin_members, used = bins[best_bin]
                bin_members.append(member)
                bins[best_bin] = (bin_members, used + size)
        # restore input order inside each bin (fold-major contracts and
        # deterministic artifacts both key off member order)
        packed_bins = [
            sorted(bin_members, key=lambda m: input_pos[m.name])
            for bin_members, _ in bins
        ]
        # sibling bins share one compile by padding their member axis to
        # a common pow2 rung (dummies are zero-weight vmap rows — per-
        # member numerics are unaffected, see parallel/fleet.py RNG note)
        m_padded = None
        if len(packed_bins) > 1:
            m_padded = round_up_ladder(max(len(b) for b in packed_bins), 2.0)
        for idx, bin_members in enumerate(packed_bins):
            buckets.append(
                PlannedBucket(
                    bucket_id=f"{_bucket_key(spec, config)}-n{rung}"
                    + (f"-o{offset}" if windowed else "")
                    + (f"-b{idx}" if len(packed_bins) > 1 else ""),
                    program=_spec_program(bin_members[0]),
                    spec=spec,
                    members=bin_members,
                    n_padded=rung,
                    m_padded=m_padded,
                    offset=offset,
                    windowed=windowed,
                )
            )
    return buckets


def annotate_predictions(
    buckets: Sequence[PlannedBucket], config: Any, cost_model: CostModel
) -> None:
    """Fill each bucket's ``predicted`` dict (run/compile seconds, HBM
    bytes, padded-FLOP waste, stacked shape) and attribute each distinct
    stacked signature's compile to its FIRST bucket — later buckets of
    the same signature hit the jit cache, exactly like the telemetry's
    first-call-per-signature attribution."""
    seen_signatures = set()
    for bucket in buckets:
        m = max(len(bucket.members), bucket.m_padded or 0)
        if bucket.windowed:
            # the trainer's windowed stacker keeps the series axis at
            # n_padded exactly and mesh-rounds only the window axis
            m_total, n_series, n_total = cost_model.stacked_windowed_shape(
                m, bucket.n_padded, bucket.offset, config.batch_size
            )
            shape = [m_total, n_series, n_total]
        else:
            m_total, n_total = cost_model.stacked_shape(
                m, bucket.n_padded, config.batch_size
            )
            shape = [m_total, n_total]
        signature = (repr(bucket.spec), bucket.program, tuple(shape))
        compiles = 0 if signature in seen_signatures else 1
        seen_signatures.add(signature)
        true_flops = sum(
            cost_model.train_flops(
                bucket.spec,
                1,
                member_samples(member) - (bucket.offset if bucket.windowed else 0),
                config.epochs,
            )
            for member in bucket.members
        )
        padded_flops = cost_model.train_flops(
            bucket.spec, m_total, n_total, config.epochs
        )
        run_s = cost_model.predict_run_s(
            bucket.program, bucket.spec, m_total, n_total, config.epochs
        )
        compile_s = (
            cost_model.predict_compile_s(bucket.program, bucket.spec)
            if compiles
            else 0.0
        )
        if bucket.windowed:
            hbm = cost_model.predict_hbm_bytes(
                bucket.spec,
                m_total,
                n_total,
                config.batch_size,
                series_rows=bucket.n_padded,
            )
        else:
            aliased = all(
                getattr(mm, "y", None) is getattr(mm, "X", None)
                for mm in bucket.members
            )
            hbm = cost_model.predict_hbm_bytes(
                bucket.spec, m_total, n_total, config.batch_size, y_aliased=aliased
            )
        bucket.predicted = {
            "members": len(bucket.members),
            "stacked_shape": shape,
            "compiles": compiles,
            "compile_s": round(compile_s, 6),
            "run_s": round(run_s, 6),
            "hbm_bytes": int(hbm),
            "flops_true": float(f"{true_flops:.6g}"),
            "flops_padded": float(f"{padded_flops:.6g}"),
            "padding_waste": round(
                1.0 - true_flops / padded_flops if padded_flops else 0.0, 6
            ),
        }


def plan_train_buckets(
    members: Sequence[Any],
    config: Any,
    strategy: Optional[str] = None,
    cost_model: Optional[CostModel] = None,
    plan: Optional[Any] = None,
    budget: Optional[int] = None,
    hbm_cap: Optional[int] = None,
) -> List[PlannedBucket]:
    """
    Group ``members`` (a mix of dense and windowed fleet members) into
    training buckets.

    With a :class:`~gordo_tpu.planner.plan.FleetPlan`, members the plan
    covers keep their planned bucket composition and pad targets
    (numerics-stable across ``--resume``: a member's padded shape never
    changes because its neighbors finished); uncovered members — CV fold
    members, late additions — pack live with ``strategy``.
    """
    if not members:
        return []
    strategy = strategy or default_strategy()
    if strategy not in STRATEGIES:
        raise ValueError(f"unknown plan strategy {strategy!r}")
    cost_model = cost_model or CostModel()

    planned: List[PlannedBucket] = []
    remaining = list(members)
    if plan is not None:
        planned, remaining = plan.materialize_buckets(members)
    if remaining:
        if strategy == PACKED:
            planned += _packed_buckets(
                remaining, config, cost_model, budget=budget, hbm_cap=hbm_cap
            )
        else:
            planned += _naive_buckets(remaining, config)
    annotate_predictions(planned, config, cost_model)
    return planned
