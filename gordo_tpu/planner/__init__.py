"""
Cost-model-driven fleet build planning.

The fleet trainer's original bucketing is purely syntactic: members
group by exact ``(spec, round_up_pow2(n))`` keys, so heterogeneous
fleets fragment into many compiles, pow2 padding wastes up to ~2x FLOPs
per axis, and over-packed buckets are only discovered reactively by the
device-error bisection ladder. This package turns bucket construction
into explicit, explainable, cost-optimized scheduling:

- :mod:`~gordo_tpu.planner.ladder` — the shared shape ladders; build and
  serve quantize with the same code, so a fleet planned here warms the
  same programs the serving engine batches into.
- :mod:`~gordo_tpu.planner.costmodel` — an analytic compile + step-time
  + HBM estimator per bucket shape, with :func:`calibrate` fitting
  correction factors from the telemetry trace (``build_trace.jsonl``)
  and persisting them as a versioned ``cost_table.json`` — the "static
  features plus a small calibration set" recipe of the learned-TPU-
  cost-model line of work (PAPERS.md).
- :mod:`~gordo_tpu.planner.packing` — bucket construction as bin
  packing: geometric shape ladders, best-fit-decreasing over members
  with per-bucket HBM caps (split *before* the OOM, not bisect after),
  and a compile-budget knob trading padding waste against program count.
- :mod:`~gordo_tpu.planner.plan` — the deterministic, JSON-serializable
  :class:`FleetPlan` artifact (buckets, predicted wall-clock / compiles
  / padding waste / HBM, config hash for journal compatibility).
- :mod:`~gordo_tpu.planner.report` — the human-readable plan table.

Dependency direction: this package imports model specs and stdlib only —
never ``parallel``/``serializer``/``server`` — so the trainer can import
it without cycles.
"""

from .costmodel import (
    COST_TABLE_FILE,
    LEARNED_FEATURES,
    LEARNED_TARGETS,
    LEARNED_VERSION,
    PERFMODEL_ENV,
    CostModel,
    CostTable,
    calibrate,
    learned_feature_vector,
    load_table_safe,
    perfmodel_enabled,
    spec_flops_per_sample,
    spec_param_count,
    validate_learned_section,
)
from .ladder import (
    DEFAULT_ROW_LADDER,
    geometric_rungs,
    member_ladder,
    pad_to,
    parse_ladder,
    round_up_ladder,
    row_ladder,
    sample_pad_ratio,
    series_pad_ratio,
)
from .packing import (
    NAIVE,
    PACKED,
    STRATEGIES,
    PlannedBucket,
    default_strategy,
    plan_train_buckets,
)
from .plan import (
    PLAN_FILE,
    FleetPlan,
    PlanError,
    build_plan_doc,
    config_fingerprint,
)
from .report import render_plan

__all__ = [
    "COST_TABLE_FILE",
    "CostModel",
    "CostTable",
    "DEFAULT_ROW_LADDER",
    "FleetPlan",
    "LEARNED_FEATURES",
    "LEARNED_TARGETS",
    "LEARNED_VERSION",
    "NAIVE",
    "PERFMODEL_ENV",
    "PACKED",
    "PLAN_FILE",
    "PlanError",
    "PlannedBucket",
    "STRATEGIES",
    "build_plan_doc",
    "calibrate",
    "config_fingerprint",
    "default_strategy",
    "geometric_rungs",
    "learned_feature_vector",
    "load_table_safe",
    "member_ladder",
    "pad_to",
    "parse_ladder",
    "perfmodel_enabled",
    "plan_train_buckets",
    "render_plan",
    "validate_learned_section",
    "round_up_ladder",
    "row_ladder",
    "sample_pad_ratio",
    "series_pad_ratio",
    "spec_flops_per_sample",
    "spec_param_count",
]
