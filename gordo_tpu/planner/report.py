"""
Human rendering of a :class:`~gordo_tpu.planner.plan.FleetPlan` — the
``gordo-tpu plan`` CLI's table (``--as-json`` prints the raw document
instead). One row per bucket: what runs, how big, what it costs, and
how much of it is padding.
"""

from typing import List

from .plan import FleetPlan


def _fmt_bytes(n: int) -> str:
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if value < 1024.0 or unit == "GiB":
            return f"{value:.1f}{unit}" if unit != "B" else f"{int(value)}B"
        value /= 1024.0
    return f"{value:.1f}GiB"


def _fmt_seconds(s: float) -> str:
    return f"{s * 1000:.0f}ms" if s < 1.0 else f"{s:.1f}s"


def render_plan(plan: FleetPlan) -> str:
    """The plan as an aligned text table plus a totals footer."""
    headers = (
        "bucket",
        "program",
        "members",
        "shape",
        "waste",
        "compile",
        "run",
        "hbm",
    )
    rows: List[tuple] = []
    for bucket in plan.buckets:
        predicted = bucket.get("predicted") or {}
        shape = "x".join(str(d) for d in predicted.get("stacked_shape") or [])
        rows.append(
            (
                str(bucket["id"]),
                str(bucket["program"]),
                str(len(bucket["members"])),
                shape,
                f"{100.0 * float(predicted.get('padding_waste', 0.0)):.1f}%",
                _fmt_seconds(float(predicted.get("compile_s", 0.0)))
                if predicted.get("compiles")
                else "cached",
                _fmt_seconds(float(predicted.get("run_s", 0.0))),
                _fmt_bytes(int(predicted.get("hbm_bytes", 0))),
            )
        )
    widths = [
        max(len(headers[i]), *(len(r[i]) for r in rows)) if rows else len(headers[i])
        for i in range(len(headers))
    ]
    lines = [
        "  ".join(h.ljust(widths[i]) for i, h in enumerate(headers)).rstrip(),
        "  ".join("-" * widths[i] for i in range(len(headers))),
    ]
    for row in rows:
        lines.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        )
    totals = plan.totals
    lines.append("")
    lines.append(
        f"strategy={plan.strategy}  buckets={totals.get('buckets', 0)}  "
        f"members={totals.get('members', 0)}  "
        f"compiles={totals.get('compiles', 0)}  "
        f"padding_waste={100.0 * float(totals.get('padding_waste', 0.0)):.1f}%"
    )
    lines.append(
        "predicted: compile "
        f"{_fmt_seconds(float(totals.get('predicted_compile_s', 0.0)))} + run "
        f"{_fmt_seconds(float(totals.get('predicted_run_s', 0.0)))} = "
        f"{_fmt_seconds(float(totals.get('predicted_wall_s', 0.0)))}  "
        f"(hbm peak {_fmt_bytes(int(totals.get('hbm_peak_bytes', 0)))}, "
        f"plan {plan.plan_hash})"
    )
    return "\n".join(lines)
