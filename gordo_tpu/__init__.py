"""
gordo-tpu — a TPU-native model-fleet framework.

Builds thousands of per-asset anomaly-detection models (feedforward / LSTM
autoencoders over time-series sensor data) from a single YAML config, trains
them as vmapped/shard_mapped batches on a TPU mesh (JAX/XLA/Flax), and serves
anomaly scores over HTTP.

Capability parity target: equinor/gordo (see SURVEY.md). The reference fans
out one Kubernetes pod per model (argo-workflow.yml.template:1519-1598); this
framework fans the same fleet out across TPU chips instead.

Version parsing semantics follow the reference (gordo/__init__.py:15-47).
"""

import re
from typing import Optional, Tuple

__version__ = "0.1.0"

_VERSION_RE = re.compile(
    r"^(?P<major>\d+)\.(?P<minor>\d+)\.(?P<patch>\d+)"
    r"(?:[.+-]?(?P<suffix>[0-9A-Za-z.+-]+))?$"
)


def parse_version(version: str) -> Tuple[int, int, int, Optional[str]]:
    """
    Parse a package version string into ``(major, minor, patch, suffix)``.

    A version with any suffix (dev/rc/post segments) is considered
    "unstable"; the builder's cache key includes the full version for
    unstable builds (reference: gordo/builder/build_model.py:606-609).

    Examples
    --------
    >>> parse_version("1.2.3")
    (1, 2, 3, None)
    >>> parse_version("1.2.3.dev4+g12345")
    (1, 2, 3, 'dev4+g12345')
    """
    match = _VERSION_RE.match(version)
    if match is None:
        raise ValueError(f"Unparseable package version: {version!r}")
    major, minor, patch = (int(match.group(g)) for g in ("major", "minor", "patch"))
    return major, minor, patch, match.group("suffix")


def version_is_stable(version: str = __version__) -> bool:
    return parse_version(version)[3] is None


MAJOR_VERSION, MINOR_VERSION = parse_version(__version__)[:2]
