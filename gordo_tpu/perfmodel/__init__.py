"""
The learned performance model: trace-trained predictors that drive the
planner, the serving ladders, warmup ordering and precision selection.

Per "A Learned Performance Model for Tensor Processing Units"
(PAPERS.md), per-program device cost is predictable from static
features; this package closes the loop the analytic cost model
(:mod:`gordo_tpu.planner.costmodel`) opened: it **harvests** training
rows from the telemetry the system already records (``device_program``
spans in ``build_trace.jsonl``, ``serve_batch`` spans in
``serve_trace*.jsonl``), **fits** small closed-form ridge regressors in
log space per (target, program kind), and **promotes** the fit into the
versioned ``cost_table.json`` only when its holdout error beats the
incumbent's — the analytic model stays pinned as the cold-start
fallback, so an empty corpus changes nothing.

Layering: the EVALUATION side (the ``learned`` section schema, the
feature vocabulary, the knob-gated predictions) lives in
``planner/costmodel.py`` because the layering contract forbids
planner→perfmodel imports; this package owns the FIT side and may
import telemetry and planner primitives — never ``server``/``serve``/
``cli`` (declared in ``analysis/contracts.toml``).

Consumers (each behind its own ``GORDO_TPU_PERFMODEL*`` knob, defaults
preserving current behavior):

- ``planner/packing.py`` bucket and rung decisions (automatic: the
  packer costs through :class:`~gordo_tpu.planner.costmodel.CostModel`);
- ``serve/engine.py`` batch-span predictions, per-spec predicted-HBM
  batch caps, predicted-hot warmup ordering, and predicted-HBM-aware
  OOM rung demotion;
- ``serve/precision.py`` model-informed precision rung choice;
- ``stream/scorer.py`` flush predictions;
- ``lifecycle/loop.py`` online recalibration via
  :func:`~gordo_tpu.perfmodel.service.maybe_recalibrate`.

CLI: ``gordo-tpu perfmodel fit|status|eval``.
"""

from .features import (
    TrainingRow,
    corpus_fingerprint,
    harvest_corpus,
    harvest_trace,
    rows_from_spans,
)
from .model import (
    analytic_prediction,
    evaluate_rows,
    fit_ridge,
    fit_section,
    holdout_split,
)
from .service import (
    default_table_path,
    fit_and_promote,
    maybe_recalibrate,
    section_status,
)

__all__ = [
    "TrainingRow",
    "analytic_prediction",
    "corpus_fingerprint",
    "default_table_path",
    "evaluate_rows",
    "fit_and_promote",
    "fit_ridge",
    "fit_section",
    "harvest_corpus",
    "harvest_trace",
    "holdout_split",
    "maybe_recalibrate",
    "rows_from_spans",
    "section_status",
]
