"""
The regressor: closed-form ridge in log space, pure Python.

Each ``(target, program)`` population gets its own log-linear model
``log(y) = intercept + coef · features`` — per the learned-TPU-cost-
model recipe (PAPERS.md), program cost is near-multiplicative in shape,
so a linear fit in log space captures it with 7 coefficients and no
iterative training. Ridge (tiny L2 on the non-intercept terms) keeps
the normal equations solvable when a corpus only exercised one rung of
an axis (a column of identical values is singular without it).

Honesty machinery:

- :func:`holdout_split` carves a deterministic ~25% holdout BEFORE
  fitting; every quality number this package reports is holdout error,
  never training error.
- :func:`fit_section` refuses populations below the
  ``GORDO_TPU_PERFMODEL_MIN_SAMPLES`` floor — a regressor fit on six
  spans would promote noise.
- :func:`analytic_prediction` replays the analytic model on the same
  feature vector, so the promotion gate compares like against like.
  HBM has no feature-only analytic counterpart (the formula needs the
  spec geometry, which the log-FLOPs feature cannot recover), so its
  baseline is the train-median predictor — "beat predicting the
  median" is the weakest gate that still rejects a garbage fit.
"""

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..planner.costmodel import (
    _TRAIN_FLOP_FACTOR,
    LEARNED_FEATURES,
    LEARNED_VERSION,
    CostTable,
)
from ..utils.env import env_int
from .features import TrainingRow

#: floor under measured values before taking logs (ms or bytes)
_EPS = 1e-9

#: default L2 strength on the non-intercept coefficients
_DEFAULT_L2 = 1e-3

MIN_SAMPLES_ENV = "GORDO_TPU_PERFMODEL_MIN_SAMPLES"


def fit_ridge(
    xs: Sequence[Sequence[float]],
    ys: Sequence[float],
    l2: float = _DEFAULT_L2,
) -> List[float]:
    """Closed-form ridge: coefficients ``[intercept, w_1..w_d]``
    minimizing ``Σ (intercept + w·x - y)^2 + l2·|w|^2`` (the intercept
    is not penalized). Normal equations solved by Gaussian elimination
    with partial pivoting — no numpy, the planner layer is importable
    everywhere."""
    if not xs:
        raise ValueError("cannot fit on an empty sample set")
    d = len(xs[0]) + 1  # intercept column first
    a = [[0.0] * d for _ in range(d)]
    b = [0.0] * d
    for x, y in zip(xs, ys):
        row = (1.0, *x)
        for i in range(d):
            b[i] += row[i] * y
            for j in range(d):
                a[i][j] += row[i] * row[j]
    for i in range(1, d):  # ridge on everything but the intercept
        a[i][i] += float(l2)
    # Gaussian elimination, partial pivoting
    for col in range(d):
        pivot = max(range(col, d), key=lambda r: abs(a[r][col]))
        if abs(a[pivot][col]) < 1e-12:
            raise ValueError("singular design matrix (raise l2)")
        if pivot != col:
            a[col], a[pivot] = a[pivot], a[col]
            b[col], b[pivot] = b[pivot], b[col]
        inv = 1.0 / a[col][col]
        for r in range(col + 1, d):
            f = a[r][col] * inv
            if f == 0.0:
                continue
            for c in range(col, d):
                a[r][c] -= f * a[col][c]
            b[r] -= f * b[col]
    coef = [0.0] * d
    for i in range(d - 1, -1, -1):
        acc = b[i] - sum(a[i][j] * coef[j] for j in range(i + 1, d))
        coef[i] = acc / a[i][i]
    return coef


def holdout_split(
    rows: Sequence[TrainingRow],
) -> Tuple[List[TrainingRow], List[TrainingRow]]:
    """Deterministic ~25% holdout: rows sort by value, every 4th goes to
    the holdout — striding a sorted population stratifies the split
    across the shape range instead of gambling on arrival order (worker
    sink merge order is not stable)."""
    ordered = sorted(rows)
    train: List[TrainingRow] = []
    holdout: List[TrainingRow] = []
    for index, row in enumerate(ordered):
        (holdout if index % 4 == 3 else train).append(row)
    if not holdout and len(train) > 1:  # tiny populations still hold one out
        holdout.append(train.pop())
    return train, holdout


def evaluate_rows(
    rows: Sequence[TrainingRow],
    predict: Callable[[TrainingRow], Optional[float]],
) -> Tuple[float, int]:
    """``(mae_log, n_scored)``: mean absolute error in log space over
    the rows ``predict`` answered (None answers are excluded from both
    numerator and count). Log-space MAE is unit-free — 0.1 ≈ ±10%
    multiplicative error whether the target is ms or bytes. An empty
    scored set is ``(inf, 0)``."""
    total, n = 0.0, 0
    for row in rows:
        pred = predict(row)
        if pred is None or pred <= 0.0:
            continue
        total += abs(math.log(pred + _EPS) - math.log(max(row.y, 0.0) + _EPS))
        n += 1
    return (total / n, n) if n else (math.inf, 0)


def coef_predict(coef: Sequence[float], features: Sequence[float]) -> float:
    """``exp(intercept + coef·x)`` — the same arithmetic
    ``CostTable.learned_predict`` runs, minus the domain gate (holdout
    evaluation must score every row, not just the in-domain ones)."""
    z = float(coef[0]) + sum(
        float(c) * float(x) for c, x in zip(coef[1:], features)
    )
    return math.exp(z)


def _shape_from_features(
    features: Sequence[float],
) -> Tuple[float, float, float, float, str]:
    """Invert :func:`~gordo_tpu.planner.costmodel.learned_feature_vector`:
    ``(flops_per_sample, members, rows, epochs, precision)``."""
    flops = math.exp(features[0]) - 1.0
    members = math.exp(features[1])
    rows = math.exp(features[2])
    epochs = math.exp(features[3])
    precision = (
        "bf16" if features[4] >= 0.5 else "int8" if features[5] >= 0.5 else "f32"
    )
    return flops, members, rows, epochs, precision


def analytic_prediction(
    table: CostTable, target: str, program: str, features: Sequence[float]
) -> Optional[float]:
    """What the ANALYTIC model (this ``table``'s factors, no learned
    section) predicts for the same feature vector, in the target's unit.
    None for ``hbm_bytes`` — its analytic formula needs the spec
    geometry, which log-FLOPs cannot recover."""
    flops, members, rows, epochs, precision = _shape_from_features(features)
    if target == "device_ms":
        if program == "fleet_forward":
            total_flops = flops * members * rows
            factor = table.run_factors.get(program, 1.0)
        else:
            total_flops = (
                _TRAIN_FLOP_FACTOR * flops * members * rows * max(epochs, 1.0)
            )
            factor = table.run_factors.get(program, 1.0)
        factor *= table.precision_factor(precision)
        return (
            factor * (total_flops / table.throughput) + table.dispatch_s
        ) * 1000.0
    if target == "compile_ms":
        factor = table.compile_factors.get(program, 1.0)
        return (
            factor * (table.compile_floor_s + table.compile_per_flop * flops)
        ) * 1000.0
    return None


def min_samples_floor(override: Optional[int] = None) -> int:
    """The smallest population :func:`fit_section` will fit."""
    if override is not None:
        return max(int(override), 2)
    return max(env_int(MIN_SAMPLES_ENV, 32), 2)


def fit_section(
    rows: Sequence[TrainingRow],
    min_samples: Optional[int] = None,
    l2: float = _DEFAULT_L2,
) -> Optional[dict]:
    """Fit every ``(target, program)`` population in ``rows`` that
    clears the sample floor, and assemble the ``learned`` section dict
    ``CostTable.from_dict`` validates (:data:`LEARNED_VERSION` schema).
    None when NO population qualifies — the caller keeps the incumbent
    table untouched (cold start stays analytic)."""
    floor = min_samples_floor(min_samples)
    populations: Dict[Tuple[str, str], List[TrainingRow]] = {}
    for row in rows:
        populations.setdefault((row.target, row.program), []).append(row)
    targets: Dict[str, Dict[str, dict]] = {}
    skipped: Dict[str, int] = {}
    for (target, program), population in sorted(populations.items()):
        if len(population) < floor:
            skipped[f"{target}/{program}"] = len(population)
            continue
        train, holdout = holdout_split(population)
        try:
            coef = fit_ridge(
                [r.features for r in train],
                [math.log(max(r.y, 0.0) + _EPS) for r in train],
                l2=l2,
            )
        except ValueError:
            skipped[f"{target}/{program}"] = len(population)
            continue
        width = len(LEARNED_FEATURES)
        lo = [
            min(r.features[i] for r in train) for i in range(width)
        ]
        hi = [
            max(r.features[i] for r in train) for i in range(width)
        ]
        mae, scored = evaluate_rows(
            holdout, lambda r: coef_predict(coef, r.features)
        )
        if not math.isfinite(mae):
            skipped[f"{target}/{program}"] = len(population)
            continue
        targets.setdefault(target, {})[program] = {
            "coef": [round(c, 10) for c in coef],
            "lo": [round(v, 6) for v in lo],
            "hi": [round(v, 6) for v in hi],
            "n": len(population),
            "holdout_mae_log": round(mae, 6),
        }
    if not targets:
        return None
    return {
        "version": LEARNED_VERSION,
        "features": list(LEARNED_FEATURES),
        "targets": targets,
        "skipped": dict(sorted(skipped.items())),
    }
