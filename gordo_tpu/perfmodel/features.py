"""
Training-row extraction: telemetry spans → (features, target) pairs.

The corpus is what the system already records — nothing new is traced
for the model's benefit:

- ``device_program`` spans (``build_trace.jsonl``, recorded by the
  fleet trainer since PR 3) carry the planner's static features
  (``flops_per_sample``/``stacked_members``/``stacked_samples``/
  ``epochs``) plus the compile-vs-run split; run spans train the
  ``device_ms`` target, compile spans the ``compile_ms`` target.
  Crucially this includes the block-diagonal (g>1) shapes the analytic
  model is blind to (the PR 5 caveat): the regressor trains on whatever
  the device actually ran.
- ``serve_batch`` spans (``serve_trace*.jsonl``) carry the fused batch
  shape (``padded_members``/``padded_rows``/``precision``) and, since
  PR 20, ``flops_per_sample`` — each with the measured ``device_ms``
  next to the prediction it will be judged against.
- spans of either kind carrying an ``hbm_bytes`` attribute train the
  peak-HBM target (device-memory sampling is backend-dependent; an
  empty population simply leaves that target analytic).

Discovery reuses the telemetry plane's own machinery
(:func:`~gordo_tpu.telemetry.trace_analysis.trace_bases` +
:func:`~gordo_tpu.telemetry.trace_analysis.read_traces`), so rotated
generations and per-worker sink variants merge exactly the way
``gordo-tpu trace`` reads them. The dependency arrow points
perfmodel→telemetry; telemetry stays stdlib-only.
"""

import hashlib
import logging
import os
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Tuple

from ..planner.costmodel import learned_feature_vector
from ..telemetry import SERVE_TRACE_FILE
from ..telemetry.progress import BUILD_TRACE_FILE
from ..telemetry.trace_analysis import read_trace, read_traces, trace_bases

logger = logging.getLogger(__name__)


class TrainingRow(NamedTuple):
    """One harvested sample: a feature vector and its measured target."""

    target: str  # device_ms | compile_ms | hbm_bytes
    program: str  # fleet_fit / fleet_windowed_fit / fleet_forward / ...
    features: Tuple[float, ...]  # the LEARNED_FEATURES vector
    y: float  # measured value in the target's unit (ms or bytes)


def _float(value: Any) -> Optional[float]:
    try:
        out = float(value)
    except (TypeError, ValueError):
        return None
    return out


def _shape_of(attrs: Dict[str, Any]) -> Optional[Tuple[float, int, int, int]]:
    """(flops_per_sample, members, rows, epochs) from span attributes,
    or None when the static features are missing (older traces)."""
    flops = _float(attrs.get("flops_per_sample"))
    if flops is None or flops < 0.0:
        return None
    try:
        members = int(
            attrs.get("stacked_members")
            or attrs.get("padded_members")
            or attrs.get("members")
            or 0
        )
        rows = int(
            attrs.get("stacked_samples") or attrs.get("padded_rows") or 0
        )
        epochs = int(attrs.get("epochs") or 1)
    except (TypeError, ValueError):
        return None
    if members <= 0 or rows <= 0:
        return None
    return flops, members, rows, epochs


def rows_from_spans(spans: Iterable[dict]) -> List[TrainingRow]:
    """Every usable training row in ``spans``; rows with missing static
    features or missing/zero targets are skipped, never guessed."""
    out: List[TrainingRow] = []
    for span in spans:
        if not isinstance(span, dict):
            continue
        name = span.get("name")
        attrs = span.get("attributes") or {}
        if name == "device_program":
            program = str(attrs.get("program") or "")
            shape = _shape_of(attrs)
            if not program or shape is None:
                continue
            flops, members, rows, epochs = shape
            precision = attrs.get("precision")
            device_ms = _float(attrs.get("device_ms"))
            if device_ms is None:
                device_ms = _float(span.get("duration_ms"))
            if attrs.get("compile"):
                # compile cost tracks program complexity, not data
                # volume: shape axes pin to 1, mirroring
                # CostModel.predict_compile_s's evaluation
                if device_ms is not None and device_ms > 0.0:
                    out.append(
                        TrainingRow(
                            "compile_ms",
                            program,
                            tuple(
                                learned_feature_vector(
                                    flops, 1, 1, 1, precision
                                )
                            ),
                            device_ms,
                        )
                    )
            elif device_ms is not None and device_ms > 0.0:
                out.append(
                    TrainingRow(
                        "device_ms",
                        program,
                        tuple(
                            learned_feature_vector(
                                flops, members, rows, epochs, precision
                            )
                        ),
                        device_ms,
                    )
                )
        elif name == "serve_batch":
            shape = _shape_of(attrs)
            if shape is None:
                continue
            flops, members, rows, _ = shape
            precision = attrs.get("precision")
            device_ms = _float(attrs.get("device_ms"))
            if device_ms is None or device_ms <= 0.0:
                continue
            out.append(
                TrainingRow(
                    "device_ms",
                    "fleet_forward",
                    tuple(
                        learned_feature_vector(
                            flops, members, rows, 1, precision
                        )
                    ),
                    device_ms,
                )
            )
        else:
            continue
        # either span kind may additionally carry a measured HBM peak
        hbm = _float(attrs.get("hbm_bytes"))
        if hbm is not None and hbm > 0.0:
            shape = _shape_of(attrs)
            if shape is None:
                continue
            flops, members, rows, _ = shape
            program = (
                "fleet_forward"
                if name == "serve_batch"
                else str(attrs.get("program") or "")
            )
            if program:
                out.append(
                    TrainingRow(
                        "hbm_bytes",
                        program,
                        tuple(
                            learned_feature_vector(
                                flops,
                                members,
                                rows,
                                1,
                                attrs.get("precision"),
                            )
                        ),
                        hbm,
                    )
                )
    return out


def harvest_trace(path: str) -> List[TrainingRow]:
    """Training rows from ONE trace file (rotated generations of the
    base are read automatically by the caller passing each)."""
    return rows_from_spans(read_trace(path))


def harvest_corpus(directory: str) -> Tuple[List[TrainingRow], Dict[str, Any]]:
    """Training rows from every trace in ``directory`` (a build output
    dir or serving telemetry dir): the build trace and the serve trace,
    each with its rotated generations and per-worker sink variants
    merged the same way ``gordo-tpu trace`` merges them. Returns
    ``(rows, stats)``; an empty/absent corpus is ``([], stats)``, never
    an error — cold start falls back analytic."""
    stats: Dict[str, Any] = {"directory": directory, "traces": [], "spans": 0}
    rows: List[TrainingRow] = []
    if not os.path.isdir(directory):
        return rows, stats
    for base_name in (BUILD_TRACE_FILE, SERVE_TRACE_FILE):
        bases = trace_bases(directory, base_name)
        if not bases:
            continue
        spans = list(read_traces(bases))
        stats["traces"].append({"base": base_name, "sinks": len(bases)})
        stats["spans"] += len(spans)
        rows.extend(rows_from_spans(spans))
    stats["rows"] = len(rows)
    by_key: Dict[str, int] = {}
    for row in rows:
        key = f"{row.target}/{row.program}"
        by_key[key] = by_key.get(key, 0) + 1
    stats["rows_by_model"] = dict(sorted(by_key.items()))
    return rows, stats


def corpus_fingerprint(rows: Iterable[TrainingRow]) -> str:
    """A stable identity for a training corpus — recalibration skips
    refitting when the corpus has not changed since the incumbent fit.
    Order-independent (worker sink merge order is not deterministic)."""
    digest = hashlib.sha256()
    for line in sorted(
        f"{r.target}|{r.program}|{','.join(f'{x:.6f}' for x in r.features)}"
        f"|{r.y:.6f}"
        for r in rows
    ):
        digest.update(line.encode())
        digest.update(b"\0")
    return digest.hexdigest()[:16]
