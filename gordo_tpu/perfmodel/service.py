"""
Fit lifecycle: harvest → fit → accuracy-gated promotion → recalibrate.

A fitted section is only ever INSTALLED by :func:`fit_and_promote`, and
installation is gated per model: a candidate ``(target, program)``
regressor lands in ``cost_table.json`` only when its holdout error
beats every incumbent ruler on the SAME holdout rows — the analytic
model replayed feature-for-feature, and the previously promoted
regressor if one exists. A fit that loses to either is reported and
dropped; a corpus with no winners leaves the table byte-identical. The
analytic model therefore stays the pinned cold-start fallback forever:
it is never deleted, only out-predicted.

:func:`maybe_recalibrate` is the online loop — the lifecycle
supervisor calls it once per cycle (``GORDO_TPU_PERFMODEL_RECAL``
gated, default off). It is exception-safe by contract: a torn trace, a
read-only table directory or a singular fit must never take down the
supervisor, and an unchanged corpus (fingerprint match) skips the
refit entirely.
"""

import logging
import os
from typing import Any, Dict, List, Optional

from ..planner.costmodel import (
    COST_TABLE_FILE,
    CostTable,
    load_table_safe,
)
from ..utils.env import env_bool, env_str
from .features import TrainingRow, corpus_fingerprint, harvest_corpus
from .model import (
    analytic_prediction,
    coef_predict,
    evaluate_rows,
    fit_section,
    holdout_split,
)

logger = logging.getLogger(__name__)

TABLE_ENV = "GORDO_TPU_PERFMODEL_TABLE"
RECAL_ENV = "GORDO_TPU_PERFMODEL_RECAL"

#: a candidate must beat an incumbent ruler by more than this margin of
#: log-MAE to replace it — refitting noise should not churn the table
_PROMOTE_MARGIN = 1e-6


def default_table_path(directory: Optional[str] = None) -> Optional[str]:
    """The cost table a fit should write / a consumer should load:
    ``GORDO_TPU_PERFMODEL_TABLE`` when set, else ``cost_table.json``
    beside the corpus ``directory``, else None (analytic defaults)."""
    configured = env_str(TABLE_ENV, None)
    if configured:
        return configured
    if directory:
        return os.path.join(directory, COST_TABLE_FILE)
    return None


def _median_baseline(train: List[TrainingRow]) -> Optional[float]:
    if not train:
        return None
    values = sorted(r.y for r in train)
    return values[len(values) // 2]


def _gate_entry(
    target: str,
    program: str,
    entry: dict,
    population: List[TrainingRow],
    incumbent: CostTable,
) -> Dict[str, Any]:
    """Score one candidate model against every incumbent ruler on the
    candidate's own holdout rows (same deterministic split the fit
    used). Returns the verdict record the report carries."""
    train, holdout = holdout_split(population)
    candidate_mae = float(entry["holdout_mae_log"])
    analytic_mae, analytic_n = evaluate_rows(
        holdout,
        lambda r: analytic_prediction(incumbent, target, program, r.features),
    )
    if analytic_n == 0:
        # no feature-only analytic counterpart (hbm_bytes): the weakest
        # honest baseline is predicting the training median
        median = _median_baseline(train)
        analytic_mae, analytic_n = evaluate_rows(
            holdout, lambda r: median
        )
    incumbent_entry = incumbent.learned_entry(target, program)
    incumbent_mae: Optional[float] = None
    if incumbent_entry is not None:
        incumbent_mae, scored = evaluate_rows(
            holdout,
            lambda r: coef_predict(incumbent_entry["coef"], r.features),
        )
        if scored == 0:
            incumbent_mae = None
    beats_analytic = candidate_mae <= analytic_mae + _PROMOTE_MARGIN
    beats_incumbent = (
        incumbent_mae is None
        or candidate_mae <= incumbent_mae + _PROMOTE_MARGIN
    )
    return {
        "target": target,
        "program": program,
        "n": int(entry["n"]),
        "holdout_mae_log": candidate_mae,
        "analytic_mae_log": round(analytic_mae, 6)
        if analytic_mae != float("inf")
        else None,
        "incumbent_mae_log": round(incumbent_mae, 6)
        if incumbent_mae is not None
        else None,
        "accepted": bool(beats_analytic and beats_incumbent),
        "reason": "promoted"
        if beats_analytic and beats_incumbent
        else ("loses to analytic" if not beats_analytic else "loses to incumbent"),
    }


def fit_and_promote(
    directory: str,
    table_path: Optional[str] = None,
    min_samples: Optional[int] = None,
    force: bool = False,
) -> Dict[str, Any]:
    """Harvest ``directory``, fit, gate, and (maybe) write the table.

    Returns the full report: corpus stats, per-model verdicts, and
    whether a table was written. ``force`` skips the accuracy gate (an
    operator override for bootstrap experiments) but never the sample
    floor. An empty corpus promotes nothing and writes nothing."""
    rows, stats = harvest_corpus(directory)
    report: Dict[str, Any] = {
        "directory": directory,
        "corpus": stats,
        "promoted": False,
        "models": [],
    }
    path = table_path or default_table_path(directory)
    report["table"] = path
    if not rows:
        report["reason"] = "empty corpus; analytic fallback stays pinned"
        return report
    fingerprint = corpus_fingerprint(rows)
    report["fingerprint"] = fingerprint
    incumbent = load_table_safe(path if path and os.path.exists(path) else None)
    incumbent_meta = (incumbent.learned or {}).get("corpus") or {}
    if not force and incumbent_meta.get("fingerprint") == fingerprint:
        report["reason"] = "corpus unchanged since incumbent fit"
        return report
    section = fit_section(rows, min_samples=min_samples)
    if section is None:
        report["reason"] = (
            "no (target, program) population clears the sample floor"
        )
        return report
    populations: Dict[tuple, List[TrainingRow]] = {}
    for row in rows:
        populations.setdefault((row.target, row.program), []).append(row)
    accepted: Dict[str, Dict[str, dict]] = {}
    for target, programs in sorted(section["targets"].items()):
        for program, entry in sorted(programs.items()):
            verdict = _gate_entry(
                target, program, entry, populations[(target, program)], incumbent
            )
            if force and not verdict["accepted"]:
                verdict["accepted"] = True
                verdict["reason"] = "forced"
            report["models"].append(verdict)
            if verdict["accepted"]:
                accepted.setdefault(target, {})[program] = entry
    if not accepted:
        report["reason"] = "no candidate beat the incumbent rulers"
        return report
    # carry forward incumbent models for keys this corpus did not refit:
    # a serve-only recalibration must not evict the build-side models
    for target, programs in ((incumbent.learned or {}).get("targets") or {}).items():
        for program, entry in programs.items():
            accepted.setdefault(target, {}).setdefault(program, entry)
    section["targets"] = {
        t: dict(sorted(p.items())) for t, p in sorted(accepted.items())
    }
    section["corpus"] = {
        "fingerprint": fingerprint,
        "rows": len(rows),
        "directory": os.path.abspath(directory),
    }
    promoted = CostTable(
        throughput=incumbent.throughput,
        compile_per_flop=incumbent.compile_per_flop,
        compile_floor_s=incumbent.compile_floor_s,
        dispatch_s=incumbent.dispatch_s,
        run_factors=dict(incumbent.run_factors),
        compile_factors=dict(incumbent.compile_factors),
        precision_factors=dict(incumbent.precision_factors),
        samples=dict(incumbent.samples),
        learned=section,
    )
    if path:
        promoted.save(path)
        report["promoted"] = True
        report["reason"] = "promoted"
    else:
        report["reason"] = "no table path; fit evaluated but not installed"
    report["section"] = {
        "models": sum(len(p) for p in section["targets"].values()),
        "targets": sorted(section["targets"]),
    }
    return report


def section_status(table_path: Optional[str]) -> Dict[str, Any]:
    """What the table at ``table_path`` currently carries — the
    ``gordo-tpu perfmodel status`` document."""
    table = load_table_safe(table_path)
    doc: Dict[str, Any] = {
        "table": table_path,
        "exists": bool(table_path and os.path.exists(table_path)),
        "calibrated": table.calibrated,
        "learned": table.has_learned,
        "models": [],
    }
    if table.learned:
        corpus = table.learned.get("corpus") or {}
        if corpus:
            doc["corpus"] = dict(corpus)
        for target, programs in sorted(
            (table.learned.get("targets") or {}).items()
        ):
            for program, entry in sorted(programs.items()):
                doc["models"].append(
                    {
                        "target": target,
                        "program": program,
                        "n": int(entry.get("n", 0)),
                        "holdout_mae_log": entry.get("holdout_mae_log"),
                    }
                )
    return doc


def maybe_recalibrate(
    directory: str, table_path: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """One online recalibration attempt, supervisor-safe: gated on
    ``GORDO_TPU_PERFMODEL_RECAL`` (default off), fingerprint-skipped on
    an unchanged corpus, and NEVER raises — any failure logs a warning
    and returns None (the incumbent table keeps serving)."""
    if not env_bool(RECAL_ENV, False):
        return None
    try:
        return fit_and_promote(directory, table_path=table_path)
    except Exception as exc:  # noqa: BLE001 — supervisor safety contract
        logger.warning(
            "Perfmodel recalibration from %s failed (%s); keeping the "
            "incumbent table",
            directory,
            exc,
        )
        return None
