"""
Device-mesh construction for fleet training.

The framework's scale axis is the *model fleet* (SURVEY.md §2.9: the
reference fans one k8s pod out per machine; we fan the same fleet across
TPU chips). The canonical mesh is 2D:

- ``models`` — embarrassingly parallel axis: each chip group trains a
  disjoint shard of the stacked model batch (no collectives needed).
- ``data`` — optional second axis sharding each model's sample dimension;
  GSPMD inserts the gradient reductions (psum over ``data``) that the
  reference had no analog for (it had no in-process distributed training
  at all).

Multi-host: `jax.distributed.initialize()` (see ``initialize_backend``)
makes ``jax.devices()`` span the slice; the same mesh code then shards over
ICI/DCN without change.
"""

import logging
import os

from ..utils.env import env_str
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

MODEL_AXIS = "models"
DATA_AXIS = "data"

#: directory for JAX's persistent compilation cache — repeated fleet
#: builds and server restarts reuse compiled programs instead of paying
#: the XLA compile again (the FleetPlan's compile-count predictions
#: count *cold* compiles; a warm cache turns them into disk loads)
COMPILE_CACHE_ENV = "GORDO_TPU_COMPILE_CACHE"

_compile_cache_configured = False


def configure_compile_cache() -> Optional[str]:
    """
    Point JAX's persistent compilation cache at ``$GORDO_TPU_COMPILE_CACHE``
    (no-op when unset). Idempotent — called from every mesh/backend init
    path so any entrypoint (build, plan, serve) gets the same cache.

    The min-compile-time threshold is zeroed: fleet programs are many
    small autoencoders, and JAX's 1s default would skip exactly the
    programs a heterogeneous fleet recompiles most often.
    """
    global _compile_cache_configured
    cache_dir = env_str(COMPILE_CACHE_ENV, None)
    if not cache_dir:
        return None
    if _compile_cache_configured:
        return cache_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except (OSError, AttributeError, ValueError) as exc:
        logger.warning(
            "Persistent compile cache not enabled (%s=%r): %r",
            COMPILE_CACHE_ENV,
            cache_dir,
            exc,
        )
        return None
    _compile_cache_configured = True
    # device telemetry inventories the configured cache (entries/bytes)
    # for the fleet-status surface and the Prometheus device collector
    from ..telemetry.device import note_compile_cache_dir

    note_compile_cache_dir(cache_dir)
    logger.info("JAX persistent compilation cache at %s", cache_dir)
    return cache_dir


def initialize_backend(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """
    Initialize multi-host JAX when running on a multi-host TPU slice; no-op
    for single-process runs. This replaces the reference's "distributed
    backend" row (which was k8s pod fan-out, SURVEY.md §2.9) with XLA
    collectives over ICI/DCN.
    """
    configure_compile_cache()
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallelism: int = 1,
    axis_names: Tuple[str, str] = (MODEL_AXIS, DATA_AXIS),
) -> Mesh:
    """
    Build the fleet mesh over ``devices`` (default: all local devices).

    ``data_parallelism`` chips cooperate per model shard; the rest of the
    device count spreads the model axis.
    """
    configure_compile_cache()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % data_parallelism != 0:
        raise ValueError(
            f"data_parallelism={data_parallelism} does not divide device "
            f"count {n}"
        )
    grid = np.array(devices).reshape(n // data_parallelism, data_parallelism)
    return Mesh(grid, axis_names)


def model_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for arrays stacked on a leading model axis: [M, ...]."""
    return NamedSharding(
        mesh, PartitionSpec(mesh.axis_names[0], *([None] * extra_dims))
    )


def model_data_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for [M, N, ...] arrays: models × sample axis."""
    return NamedSharding(
        mesh,
        PartitionSpec(mesh.axis_names[0], mesh.axis_names[1], *([None] * extra_dims)),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
