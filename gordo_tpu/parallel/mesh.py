"""
Device-mesh construction for fleet training.

The framework's scale axis is the *model fleet* (SURVEY.md §2.9: the
reference fans one k8s pod out per machine; we fan the same fleet across
TPU chips). The canonical mesh is 2D:

- ``models`` — embarrassingly parallel axis: each chip group trains a
  disjoint shard of the stacked model batch (no collectives needed).
- ``data`` — optional second axis sharding each model's sample dimension;
  GSPMD inserts the gradient reductions (psum over ``data``) that the
  reference had no analog for (it had no in-process distributed training
  at all).

Multi-host: `jax.distributed.initialize()` (see ``initialize_backend``)
makes ``jax.devices()`` span the slice; the same mesh code then shards over
ICI/DCN without change.
"""

import logging
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

MODEL_AXIS = "models"
DATA_AXIS = "data"


def initialize_backend(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """
    Initialize multi-host JAX when running on a multi-host TPU slice; no-op
    for single-process runs. This replaces the reference's "distributed
    backend" row (which was k8s pod fan-out, SURVEY.md §2.9) with XLA
    collectives over ICI/DCN.
    """
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallelism: int = 1,
    axis_names: Tuple[str, str] = (MODEL_AXIS, DATA_AXIS),
) -> Mesh:
    """
    Build the fleet mesh over ``devices`` (default: all local devices).

    ``data_parallelism`` chips cooperate per model shard; the rest of the
    device count spreads the model axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % data_parallelism != 0:
        raise ValueError(
            f"data_parallelism={data_parallelism} does not divide device "
            f"count {n}"
        )
    grid = np.array(devices).reshape(n // data_parallelism, data_parallelism)
    return Mesh(grid, axis_names)


def model_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for arrays stacked on a leading model axis: [M, ...]."""
    return NamedSharding(
        mesh, PartitionSpec(mesh.axis_names[0], *([None] * extra_dims))
    )


def model_data_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """Sharding for [M, N, ...] arrays: models × sample axis."""
    return NamedSharding(
        mesh,
        PartitionSpec(mesh.axis_names[0], mesh.axis_names[1], *([None] * extra_dims)),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
