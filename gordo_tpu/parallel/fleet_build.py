"""
FleetBuilder: the whole-project build — every machine in one YAML trained
as mesh-sharded model batches, producing per-machine artifacts identical
in contract to ModelBuilder's.

Replaces the reference's per-machine Argo pod DAG
(argo-workflow.yml.template:1519-1598) with chip fan-out. Per machine it
reproduces ModelBuilder semantics (gordo/builder/build_model.py):

- data fetch (concurrent across machines, host-side)
- host-side pipeline transformers (scalers) fitted per machine
- CV folds → per-tag + aggregate metric scores and DiffBased threshold
  math, with fold boundaries expressed as weight masks so every fold of
  every machine in a bucket trains in one device program
- final fit → params injected back into per-machine estimator objects
- metadata tree + artifact save (model.pkl / metadata.json / info.json)

Model definitions the fleet path supports: a JaxBaseEstimator, optionally
inside an sklearn Pipeline (host transformers before it), optionally
wrapped by DiffBasedAnomalyDetector. Anything else transparently falls
back to the sequential ModelBuilder so `fleet_build` always builds the
full config.
"""

import concurrent.futures
import contextlib
import datetime
import logging
import os
import time
import types
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd
from sklearn.base import clone as sklearn_clone
from sklearn.model_selection import KFold, TimeSeriesSplit
from sklearn.pipeline import Pipeline

import gordo_tpu
from .. import serializer, telemetry
from ..builder.build_model import ModelBuilder
from ..dataset import GordoBaseDataset
from ..machine import Machine
from ..telemetry.progress import BUILD_TRACE_FILE
from ..utils.profiling import maybe_trace
from ..machine.metadata import (
    BuildMetadata,
    CrossValidationMetaData,
    DatasetBuildMetadata,
    ModelBuildMetadata,
    RobustnessMetadata,
    TrainingSummaryMetadata,
)
from ..models.anomaly.diff import (
    DiffBasedAnomalyDetector,
    DiffBasedKFCVAnomalyDetector,
)
from ..models.estimators import JaxBaseEstimator, JaxLSTMBaseEstimator
from ..models.training import FitConfig, fit_config_from_kwargs, split_fit_kwargs
from ..ops.windows import model_offset as calc_model_offset
from ..ops.windows import window_targets
from ..utils.env import env_float, env_int, env_str
from ..utils.faults import fault_point
from ..utils.retry import retry_call
from .fleet import (
    FleetMember,
    FleetTrainer,
    WindowedFleetMember,
    is_device_error,
    stack_member_params,
)
from .journal import BuildJournal, clean_staging_dirs

logger = logging.getLogger(__name__)


@dataclass
class _Plan:
    """Everything needed to train + reassemble one machine."""

    machine: Machine
    dataset: GordoBaseDataset
    model_obj: Any  # the unfitted object graph from the definition
    detector: Optional[DiffBasedAnomalyDetector]
    pipeline: Optional[Pipeline]
    estimator: JaxBaseEstimator
    X: pd.DataFrame = None
    y: pd.DataFrame = None
    X_arr: np.ndarray = None  # transformed (post host-transformers) inputs
    y_arr: np.ndarray = None
    # Dense models: estimator-space samples [N, F]. Windowed (LSTM) models:
    # None — the raw series (X_arr) stays resident and windows are gathered
    # on device (models/training.py build_raw_windowed_fit_fn), avoiding
    # the lookback× host/HBM blowup of materialized windows.
    windows: np.ndarray = None
    targets: np.ndarray = None
    n_windows: int = 0  # virtual sample count (== len(X_arr) for dense)
    shuffle_perm: Optional[np.ndarray] = None  # detector-level row shuffle
    offset: int = 0
    spec: Any = None
    fit_config: FitConfig = None
    seed: int = 42
    query_duration: float = 0.0
    cv_scores: Dict[str, Any] = field(default_factory=dict)
    cv_splits: Dict[str, Any] = field(default_factory=dict)
    cv_duration: float = 0.0
    train_duration: float = 0.0
    # Robustness counters surfaced in BuildMetadata.robustness:
    data_retries: int = 0  # data-fetch attempts beyond the first
    fleet_retries: int = 0  # diverged-member reseed retries (CV + final)
    bucket_bisects: int = 0  # split-retry events this machine rode through
    # Final-fit History summary (final/best loss, epochs, early stop),
    # baked into BuildMetadata.model.training at assembly.
    training_summary: Optional[TrainingSummaryMetadata] = None
    _scoring_setup_cache: Any = None  # (metrics, fitted scoring scaler)


class FleetBuildError(RuntimeError):
    pass


def _cv_chunk_bytes() -> int:
    """Per-program staging budget for CV fold members (raw member data;
    the device program's true footprint is a few × this for gradients and
    optimizer moments). Override with GORDO_TPU_CV_CHUNK_BYTES."""
    return env_int("GORDO_TPU_CV_CHUNK_BYTES", 1 << 30)


def _member_nbytes(member) -> int:
    """Raw staged bytes of one fold member (X + non-aliased y, or series)."""
    if isinstance(member, WindowedFleetMember):
        return member.series.nbytes + member.targets.nbytes
    n = member.X.nbytes
    if member.y is not member.X:
        n += member.y.nbytes
    return n


def _chunk_by_bytes(members, items, budget: int):
    """Split (members, items) into order-preserving chunks whose summed
    member bytes stay under ``budget`` (every chunk holds ≥1 member)."""
    chunks = []
    start, used = 0, 0
    for i, member in enumerate(members):
        size = _member_nbytes(member)
        if i > start and used + size > budget:
            chunks.append((members[start:i], items[start:i]))
            start, used = i, 0
        used += size
    if start < len(members):
        chunks.append((members[start:], items[start:]))
    return chunks


def _fold_member_name(machine_name: str, fold_idx: int) -> str:
    """Unique member name for one machine's fold model. '::' cannot occur
    in machine names (k8s-name validated), so no collision is possible."""
    return f"{machine_name}::fold{fold_idx}"


def _try_call(fn, *args):
    """Run ``fn``; return the exception instead of raising (thread-pool
    safe capture for failFast:false semantics). Interpreter-shutdown
    signals are explicitly NOT captured: ``failFast:false`` means one
    machine's failure spares the rest, not that a Ctrl-C or SystemExit
    (e.g. an injected process kill) gets silently journaled as a
    per-machine build error and the build marches on."""
    try:
        fn(*args)
        return None
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as exc:  # noqa: BLE001 - recorded per machine
        return exc


class FleetBuilder:
    def __init__(
        self,
        machines: Sequence[Machine],
        trainer: Optional[FleetTrainer] = None,
        data_workers: int = 16,
        fail_fast: bool = False,
        data_retries: Optional[int] = None,
        data_backoff: Optional[float] = None,
        data_deadline: Optional[float] = None,
        plan_strategy: Optional[str] = None,
        fleet_plan: Optional[Any] = None,
        cost_table: Optional[Any] = None,
        health_ledger: Optional[Any] = None,
    ):
        self.machines = list(machines)
        if trainer is None:
            # GORDO_TPU_PACKING=auto|<int> turns on block-diagonal model
            # packing (models/packing.py) for the whole build path —
            # including the `build-fleet` CLI — without new flags.

            packing: Any = env_str("GORDO_TPU_PACKING", None)
            if packing and packing != "auto":
                try:
                    packing = int(packing)
                except ValueError:
                    logger.warning(
                        "Invalid GORDO_TPU_PACKING=%r (want an int or "
                        "'auto'); packing disabled",
                        packing,
                    )
                    packing = None
            trainer = FleetTrainer(packing=packing)
        # Bucket planning (gordo_tpu.planner): strategy / pre-computed
        # FleetPlan / calibrated cost table ride on the trainer — it is
        # the component that materializes buckets. Explicit arguments win
        # over whatever the (possibly caller-provided) trainer carries.
        if plan_strategy is not None:
            trainer.plan_strategy = plan_strategy
        if fleet_plan is not None:
            trainer.fleet_plan = fleet_plan
        if cost_table is not None:
            trainer.cost_table = cost_table
        self.trainer = trainer
        # A plan handed in (directly or already on the trainer) is
        # REPLAYED; otherwise each build computes a fresh one — a trainer
        # reused across builds must not leak the previous fleet's plan
        # (or the strategy a replayed plan switched it to).
        self._external_plan = getattr(trainer, "fleet_plan", None)
        self._external_strategy = getattr(trainer, "plan_strategy", None)
        self.data_workers = data_workers
        # The reference DAG runs with failFast:false
        # (argo-workflow.yml.template: one machine's builder pod failing
        # does not stop the fleet); mirror that — failed machines are
        # recorded in ``build_errors`` and the rest of the fleet builds.
        self.fail_fast = fail_fast
        self.build_errors: Dict[str, BaseException] = {}
        # Wall-clock per build phase (seconds), for the bench's host/device
        # breakdown: plan, data_fetch, stage, cv_train (device programs),
        # cv_score (host threshold/metric math), cv_finalize, final_fit,
        # assemble, dump.
        self.phase_seconds: Dict[str, float] = defaultdict(float)
        # Data-plane retry knobs (reference analog: the builder pod's
        # retryStrategy with backoff); env-overridable for operators.
        self.data_retries = (
            env_int("GORDO_TPU_DATA_RETRIES", 2)
            if data_retries is None
            else data_retries
        )
        self.data_backoff = (
            env_float("GORDO_TPU_DATA_BACKOFF", 0.5)
            if data_backoff is None
            else data_backoff
        )
        self.data_deadline = (
            env_float("GORDO_TPU_DATA_DEADLINE", None)
            if data_deadline is None
            else data_deadline
        )
        # Fleet-wide robustness counters (surfaced in BuildMetadata per
        # machine and as Prometheus counters at build end).
        self.robustness: Dict[str, int] = defaultdict(int)
        # Machines degraded out of the fleet path to the sequential
        # ModelBuilder after an isolated device failure: name -> cause.
        self.degraded: Dict[str, BaseException] = {}
        # Machine names skipped by --resume (journaled complete).
        self.resumed: List[str] = []
        self._journal: Optional[BuildJournal] = None
        self._config_hashes: Dict[str, str] = {}
        # Telemetry: the per-build span recorder + live progress surface
        # (installed by build(); NULL/None outside one, so every
        # instrumentation site stays unconditional).
        self.recorder: Any = telemetry.NULL_RECORDER
        self.progress: Optional[telemetry.BuildProgress] = None
        self._project = ""
        # Predicted-vs-actual bookkeeping for the FleetPlan: the span
        # listener attributes final-fit device programs here so the
        # cost model's error is observable (event + gauges at build end).
        self._current_phase = ""
        self._plan_actuals: Dict[str, float] = defaultdict(float)
        # Per-member fleet health ledger (telemetry/fleet_health.py):
        # build provenance — final losses, failures, degradations —
        # lands per machine, so the fleet console can answer "which of
        # my machines are degraded" without parsing the span trace.
        # An explicit `health_ledger` overrides the default
        # ledger-per-output-dir: lifecycle incremental rebuilds train
        # into a .lifecycle/build-<rev> STAGING dir, but their
        # provenance belongs in the anchor collection's ledger — the
        # one the fleet-status surfaces actually read.
        self._health_ledger_override = health_ledger
        self._ledger: Any = telemetry.NULL_LEDGER
        self._output_revision: Optional[str] = None
        # Measured device-utilization actuals: member-axis occupancy of
        # the executed final-fit programs and the max observed HBM peak
        # (Device.memory_stats), joined against the FleetPlan's
        # predictions in _export_plan_accuracy.
        self._member_actuals: Dict[str, int] = defaultdict(int)
        self._device_peak_bytes = 0
        self._last_device_sample = 0.0

    #: phases that end with a device-utilization sample (``cv_*`` phases
    #: recur once per bucket chunk and are throttled by time instead)
    _DEVICE_SAMPLED_PHASES = frozenset(
        {"stage", "cv_train", "final_fit", "assemble", "dump"}
    )

    @contextlib.contextmanager
    def _phase(self, name: str):
        if self.progress is not None:
            self.progress.phase(name)
        start = time.time()
        previous_phase, self._current_phase = self._current_phase, name
        try:
            with self.recorder.span(
                "build_phase", phase=name, machines=len(self.machines)
            ):
                yield
        finally:
            self._current_phase = previous_phase
            self.phase_seconds[name] += time.time() - start
            self._sample_device(name)

    def _sample_device(self, phase: str) -> None:
        """Emit a ``device_utilization`` event (HBM in-use/peak +
        compile-cache counters) at the end of device-heavy phases,
        time-throttled so a thousand-chunk CV loop costs a handful of
        samples, not a thousand. Tracks the build's max observed HBM
        peak for the plan-accuracy join."""
        if phase not in self._DEVICE_SAMPLED_PHASES:
            return
        now = time.time()
        if now - self._last_device_sample < 1.0 and phase != "final_fit":
            return
        self._last_device_sample = now
        try:
            snapshot = telemetry.emit_device_utilization(
                self.recorder, phase=phase
            )
        except Exception as exc:  # noqa: BLE001 - device telemetry is advisory
            logger.debug("device utilization not sampled: %r", exc)
            return
        if snapshot and snapshot.get("available"):
            self._device_peak_bytes = max(
                self._device_peak_bytes,
                int(snapshot.get("max_peak_bytes_in_use") or 0),
            )

    def _fail(self, name: str, exc: BaseException):
        if self._journal is not None:
            self._journal.record(name, "failed", error=repr(exc))
        if self.fail_fast:
            raise exc
        logger.error("Fleet build of machine %s failed: %r", name, exc)
        first_failure = name not in self.build_errors
        self.build_errors[name] = exc
        if first_failure:
            self.recorder.event("machine_failed", machine=name, error=repr(exc))
            if self.progress is not None:
                self.progress.machine_failed(name)
                self._update_progress_gauges()

    def _skipped(self, name: str) -> bool:
        """A machine out of the fleet path: failed, or degraded to the
        sequential builder (it finishes there, not here)."""
        return name in self.build_errors or name in self.degraded

    def _degrade(self, plan: "_Plan", exc: BaseException):
        """Pull one machine out of the fleet path after its device
        program failed in isolation; it rebuilds on the sequential
        ModelBuilder path (the same escape hatch unsupported definitions
        take), so a poisonous member costs one sequential build instead
        of the fleet."""
        name = plan.machine.name
        logger.warning(
            "Fleet degrade: %s falls back to the sequential builder after "
            "an isolated device failure: %r",
            name,
            exc,
        )
        self.robustness["sequential_degraded"] += 1
        self.degraded[name] = exc
        self.recorder.event(
            "machine_degraded", machine=name, error=repr(exc)
        )
        if self.progress is not None:
            self.progress.degraded = len(self.degraded)
            self.progress.write()

    # ------------------------------------------------------------------ API

    def build(
        self,
        output_dir: Optional[str] = None,
        model_register_dir: Optional[str] = None,
        replace_cache: bool = False,
        resume: bool = False,
    ) -> List[Tuple[Any, Machine]]:
        """
        Train the whole fleet; optionally dump per-machine artifacts to
        ``output_dir/<machine-name>/``. With a ``model_register_dir``, the
        content-addressed build cache applies per machine exactly as in
        ``ModelBuilder.build`` — cache hits skip training entirely and
        fresh builds are registered for the next run.

        With an ``output_dir`` the build keeps a journal
        (``build_state.json``, written with atomic replaces) of every
        machine's status; ``resume=True`` replays it after a crash —
        machines journaled ``built`` under an unchanged config hash with
        a complete artifact on disk are skipped entirely (recorded in
        ``self.resumed``), and only the remainder is replanned. Resumed
        machines are not re-loaded, so they do not appear in the return
        value; their artifacts are already in place.

        Telemetry (on unless ``GORDO_TPU_TELEMETRY`` is falsy): the
        build records a span per phase and device program into
        ``self.recorder`` (JSONL-sunk to ``<output_dir>/build_trace.jsonl``
        or ``$GORDO_TPU_TELEMETRY_DIR``), heartbeats a live
        ``build_status.json`` beside the journal, and exports phase/
        compile durations, member final losses and machine-progress
        gauges to Prometheus as they happen.
        """
        self.build_errors = {}
        self.phase_seconds = defaultdict(float)
        self.robustness = defaultdict(int)
        self.degraded = {}
        self.resumed = []
        self._journal = None
        self._plan_actuals = defaultdict(float)
        self._member_actuals = defaultdict(int)
        self._device_peak_bytes = 0
        self._project = self.machines[0].project_name if self.machines else ""
        self._output_revision = (
            os.path.basename(os.path.normpath(output_dir))
            if output_dir is not None
            else None
        )
        if self._health_ledger_override is not None:
            self._ledger = self._health_ledger_override
        elif output_dir is not None:
            self._ledger = telemetry.ledger_for(
                output_dir, project=self._project
            )
        else:
            self._ledger = telemetry.NULL_LEDGER

        recorder: Any = telemetry.NULL_RECORDER
        self.progress = None
        if telemetry.enabled():
            trace_path = None
            if output_dir is not None:
                trace_dir = env_str(telemetry.TRACE_DIR_ENV, None) or output_dir
                try:
                    os.makedirs(trace_dir, exist_ok=True)
                    trace_path = os.path.join(trace_dir, BUILD_TRACE_FILE)
                except OSError as exc:
                    logger.debug("No span trace sink: %r", exc)
            recorder = telemetry.SpanRecorder(
                sink_path=trace_path, service="gordo-tpu-fleet-build"
            )
            recorder.add_listener(self._export_span)
            self.progress = telemetry.BuildProgress(
                output_dir,
                project=self._project,
                total=len(self.machines),
                phase_seconds=self.phase_seconds,
            )
            self._update_progress_gauges()
        self.recorder = recorder
        try:
            with telemetry.activate(recorder):
                with recorder.span(
                    "fleet_build",
                    project=self._project,
                    machines=len(self.machines),
                ):
                    try:
                        results = self._run_build(
                            output_dir, model_register_dir, replace_cache, resume
                        )
                    finally:
                        # The build-computed plan (and any strategy a
                        # replayed plan switched the trainer to) must not
                        # outlive the build on a shared trainer: a later
                        # FleetBuilder reusing this trainer would
                        # otherwise replay THIS fleet's plan as if the
                        # caller had passed it.
                        self.trainer.fleet_plan = self._external_plan
                        self.trainer.plan_strategy = self._external_strategy
        except Exception:
            # a build-level failure (per-machine failures do NOT raise);
            # SystemExit/KeyboardInterrupt skip this on purpose — a
            # killed build leaves the status "running", like a real kill
            if self.progress is not None:
                self.progress.finish("failed")
                self._update_progress_gauges()
            raise
        finally:
            recorder.close()
            self._ledger.flush()
        if self.progress is not None:
            self.progress.finish("complete")
            self._update_progress_gauges()
        return results

    def _run_build(
        self,
        output_dir: Optional[str],
        model_register_dir: Optional[str],
        replace_cache: bool,
        resume: bool,
    ) -> List[Tuple[Any, Machine]]:
        machines = self.machines
        trainer_bisects_start = getattr(self.trainer, "bucket_bisects", 0)
        trainer_counts_start = dict(getattr(self.trainer, "bisect_counts", {}))
        config_hashes: Dict[str, str] = {}
        if output_dir is not None:
            config_hashes = {
                m.name: ModelBuilder.calculate_cache_key(m) for m in machines
            }
            self._config_hashes = config_hashes
            # Orphaned `.<name>.tmp-*` staging dirs from a killed run are
            # dead weight either way; sweep them before anything else.
            clean_staging_dirs(output_dir)
            self._journal = (
                BuildJournal.load(output_dir) if resume else BuildJournal(output_dir)
            )
            if resume:
                remaining = []
                for machine in machines:
                    if self._journal.resumable(
                        machine.name, config_hashes[machine.name]
                    ):
                        self.resumed.append(machine.name)
                    else:
                        remaining.append(machine)
                machines = remaining
                logger.info(
                    "Resume: %d machine(s) already built and verified, "
                    "%d to build",
                    len(self.resumed),
                    len(machines),
                )
                if self.progress is not None:
                    self.progress.resumed = len(self.resumed)
                    self.progress.write(force=True)

        cached_results: List[Tuple[Any, Machine]] = []
        if model_register_dir:
            # register() dumps atomically under builds/ too — sweep any
            # staging orphans a killed build left in the shared registry.
            clean_staging_dirs(os.path.join(str(model_register_dir), "builds"))
            to_probe, machines = machines, []
            for machine in to_probe:
                cached = ModelBuilder(machine).load_cached(
                    model_register_dir, replace_cache=replace_cache
                )
                if cached is not None:
                    cached_results.append(cached)
                else:
                    machines.append(machine)
            logger.info(
                "Fleet cache: %d hits, %d to build",
                len(cached_results),
                len(machines),
            )
            if self.progress is not None:
                self.progress.cached = len(cached_results)
                self.progress.write(force=True)

        with self._phase("plan"):
            plans, fallbacks = self._plan_all(machines)
        if self._journal is not None:
            for machine in machines:
                self._journal.record(
                    machine.name,
                    "planned",
                    config_hash=config_hashes.get(machine.name),
                    flush=False,
                )
            self._journal.flush()
        plans = self._load_all_data(plans)
        self._prepare_fleet_plan(plans, output_dir)

        def alive(ps):
            return [p for p in ps if not self._skipped(p.machine.name)]

        # CV folds then final fit, bucketed across all plans at once
        cv_plans = [
            p
            for p in alive(plans)
            if p.machine.evaluation.get("cv_mode", "full_build").lower()
            in ("full_build", "cross_val_only")
        ]
        if cv_plans:
            with maybe_trace("fleet-cross-validation"):
                self._run_cross_validation(cv_plans)
            if self._journal is not None:
                for plan in alive(cv_plans):
                    self._journal.record(
                        plan.machine.name, "cv_done", flush=False
                    )
                self._journal.flush()
        final_plans = [
            p
            for p in alive(plans)
            if p.machine.evaluation.get("cv_mode", "full_build").lower()
            != "cross_val_only"
        ]
        with maybe_trace("fleet-final-fit"):
            self._run_final_fit(final_plans)

        # Attribute trainer-INTERNAL bisections (resolved inside
        # FleetTrainer without surfacing here) to their machines before
        # assembly bakes the per-machine robustness metadata: member
        # names are `machine` or `machine::foldN`.
        trainer_counts = getattr(self.trainer, "bisect_counts", {})
        if trainer_counts:
            per_machine: Dict[str, int] = defaultdict(int)
            for member_name, count in trainer_counts.items():
                delta = count - trainer_counts_start.get(member_name, 0)
                if delta > 0:
                    per_machine[member_name.split("::", 1)[0]] += delta
            for plan in plans:
                plan.bucket_bisects += per_machine.get(plan.machine.name, 0)

        results = []
        with self._phase("assemble"):
            for plan in alive(plans):
                try:
                    results.append(self._assemble(plan))
                except Exception as exc:
                    self._fail(plan.machine.name, exc)
        for machine in fallbacks:
            logger.info("Fleet fallback to ModelBuilder for %s", machine.name)
            try:
                results.append(ModelBuilder(machine).build())
            except Exception as exc:
                self._fail(machine.name, exc)
        # Machines degraded out of the fleet after isolated device
        # failures rebuild sequentially, exactly like unsupported
        # definitions; a machine that fails here too is a real failure
        # (recorded with the sequential cause, the device cause logged).
        degraded_machines = {m.name: m for m in machines}
        for name, cause in self.degraded.items():
            machine = degraded_machines.get(name)
            if machine is None:
                continue
            logger.info(
                "Sequential rebuild of degraded machine %s (device cause: %r)",
                name,
                cause,
            )
            try:
                results.append(ModelBuilder(machine).build())
            except Exception as exc:
                self._fail(name, exc)

        if model_register_dir:
            for model, machine in results:
                try:
                    ModelBuilder(machine).register(model, machine, model_register_dir)
                except Exception as exc:
                    self._fail(machine.name, exc)

        results = cached_results + results
        if output_dir is not None:
            with self._phase("dump"):
                results = self._dump_all(results, output_dir)
            # compact the per-machine event overlay into the base journal
            # so a finished build leaves one clean state file
            self._journal.flush()
        # Fold in bisections the trainer resolved internally (they never
        # surfaced as exceptions here, but they are still split-retry
        # events an operator wants on a dashboard).
        self.robustness["bucket_bisects"] += max(
            0, getattr(self.trainer, "bucket_bisects", 0) - trainer_bisects_start
        )
        self._record_prometheus(machines)
        self._export_plan_accuracy()
        return [
            (model, machine)
            for model, machine in results
            if machine.name not in self.build_errors
        ]

    def _export_span(self, span: dict) -> None:
        """Live Prometheus export of finished telemetry spans — phase
        durations, first-call (compile) program durations, and member
        final losses land in /metrics as they happen, not at build end.
        Best-effort like every metrics path: the build must not care
        whether a Prometheus stack is configured."""
        name = span["name"]
        attrs = span.get("attributes") or {}
        seconds = float(span.get("duration_ms") or 0.0) / 1000.0
        if (
            name == "device_program"
            and self._current_phase == "final_fit"
            and str(attrs.get("program", "")).endswith("_fit")
        ):
            # The plan covers exactly the final-fit fit programs; their
            # observed cost is the plan's predicted-vs-actual 'actual'.
            self._plan_actuals["seconds"] += seconds
            if attrs.get("compile"):
                self._plan_actuals["compiles"] += 1
            # Measured member-axis occupancy: `members` is the live
            # bucket size, `stacked_members` the padded rung the program
            # actually executed — the measured counterpart of the plan's
            # predicted padding waste.
            live = attrs.get("members")
            padded = attrs.get("stacked_members")
            if live is not None and padded:
                self._member_actuals["live"] += int(live)
                self._member_actuals["padded"] += int(padded)
        self._feed_health_ledger(name, attrs)
        try:
            from ..server.prometheus import metrics as prom

            if name == "build_phase":
                prom.record_fleet_build_phase(
                    self._project, str(attrs.get("phase", "")), seconds
                )
            elif name == "device_program" and attrs.get("compile"):
                prom.record_fleet_compile(
                    self._project,
                    str(attrs.get("program", "")),
                    str(attrs.get("shape", "")),
                    seconds,
                )
            elif name == "member_trained":
                loss = attrs.get("final_loss")
                if loss is not None and np.isfinite(loss):
                    prom.record_member_final_loss(self._project, float(loss))
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("Telemetry span not exported: %r", exc)

    def _feed_health_ledger(self, name: str, attrs: Dict[str, Any]) -> None:
        """Per-member build provenance into the fleet health ledger
        (telemetry/fleet_health.py) as the build's own events happen.
        Per-member VALUES live in the ledger; Prometheus only ever sees
        the bounded loss histogram and the aggregate health counts (the
        PR 8 cardinality contract)."""
        machine = attrs.get("machine")
        if not machine:
            return
        try:
            if name == "member_trained":
                loss = attrs.get("final_loss")
                self._ledger.record_build(
                    str(machine),
                    final_loss=(
                        float(loss)
                        if loss is not None and np.isfinite(loss)
                        else None
                    ),
                    retries=attrs.get("retries"),
                )
            elif name == "machine_built":
                # an artifact landing supersedes a PREVIOUS build's
                # failure evidence (a recovered machine must not read
                # 'degraded' forever) — but a machine that degraded to
                # the sequential builder in THIS build keeps the flag
                # its artifact genuinely carries (None = leave as-is)
                self._ledger.record_build(
                    str(machine),
                    revision=self._output_revision,
                    failed=False,
                    degraded=False if str(machine) not in self.degraded else None,
                )
            elif name == "machine_failed":
                self._ledger.record_build(
                    str(machine), failed=True, error=attrs.get("error")
                )
            elif name == "machine_degraded":
                self._ledger.record_build(
                    str(machine), degraded=True, error=attrs.get("error")
                )
        except Exception as exc:  # noqa: BLE001 - the ledger is advisory
            logger.debug("Health ledger not fed: %r", exc)

    def _update_progress_gauges(self) -> None:
        """Push the live machine-progress counters to the Prometheus
        gauges (best-effort; called from the dump pool too — Gauge.set
        is thread-safe)."""
        if self.progress is None:
            return
        try:
            from ..server.prometheus.metrics import set_fleet_build_progress

            set_fleet_build_progress(
                self._project,
                self.progress.total,
                self.progress.completed,
                self.progress.failed,
            )
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("Progress gauges not exported: %r", exc)

    def _record_prometheus(self, machines: Sequence[Machine]):
        """Best-effort robustness counter export; the build must not care
        whether a Prometheus stack is configured."""
        if not any(self.robustness.values()):
            return
        try:
            from ..server.prometheus.metrics import record_fleet_build_robustness

            project = machines[0].project_name if machines else ""
            record_fleet_build_robustness(project, dict(self.robustness))
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("Robustness counters not exported: %r", exc)

    def _dump_all(self, results, output_dir: str):
        """Per-machine artifact dump, thread-pooled: pickling releases the
        GIL for the array copies and the file writes overlap, so the dump
        phase scales with cores instead of machine count. Per-machine
        error capture keeps failFast:false semantics.

        Each artifact is written atomically (staging dir + rename), so a
        crash at any instant leaves either a complete artifact or none —
        never a half-written ``model.pkl`` a later resume or the serving
        store could load. Completion is journaled per machine before the
        kill-injection site, so a death right after machine N leaves N
        resumable machines."""

        def dump_one(item):
            model, machine = item
            path = os.path.join(output_dir, machine.name)
            serializer.dump_atomic(model, path, metadata=machine.to_dict())
            if self._journal is not None:
                # Record the hash too: cache-hit machines skip the planning
                # pass (where it is normally journaled), and resume needs it.
                self._journal.record(
                    machine.name,
                    "built",
                    config_hash=self._config_hashes.get(machine.name),
                )
            # Progress lands BEFORE the kill-injection site, mirroring
            # the journal: a death right after machine N leaves a status
            # document (and gauges) that already show N completed —
            # exactly, with GORDO_TPU_TELEMETRY_HEARTBEAT=0 (the fault
            # drills); within one heartbeat interval otherwise.
            self.recorder.event("machine_built", machine=machine.name)
            if self.progress is not None:
                self.progress.machine_completed(machine.name)
                self._update_progress_gauges()
            fault_point("process_kill_after_n_machines", machine.name)

        to_dump = [
            (model, machine)
            for model, machine in results
            # A machine can fail *after* assembly (e.g. at register);
            # never dump artifacts for machines already in build_errors.
            if machine.name not in self.build_errors
        ]
        pool = concurrent.futures.ThreadPoolExecutor(min(8, max(1, len(to_dump))))
        try:
            outcomes = list(pool.map(lambda it: _try_call(dump_one, it), to_dump))
        except (KeyboardInterrupt, SystemExit):
            # Interpreter shutdown mid-dump: stop scheduling new dumps.
            # In-flight atomic writes either land whole (and are
            # journaled) or vanish with their staging dirs; queued
            # machines stay journaled un-built, exactly what a later
            # ``--resume`` expects.
            pool.shutdown(wait=True, cancel_futures=True)
            raise
        finally:
            pool.shutdown(wait=True)
        saved = []
        for (model, machine), exc in zip(to_dump, outcomes):
            if exc is not None:
                self._fail(machine.name, exc)
                continue
            saved.append((model, machine))
        return saved

    # ------------------------------------------------------------- planning

    def _plan_all(
        self, machines: Optional[Sequence[Machine]] = None
    ) -> Tuple[List[_Plan], List[Machine]]:
        plans, fallbacks = [], []
        for machine in self.machines if machines is None else machines:
            plan = self._plan_machine(machine)
            if plan is None:
                fallbacks.append(machine)
            else:
                plans.append(plan)
        return plans, fallbacks

    @staticmethod
    def _plan_machine(machine: Machine) -> Optional[_Plan]:
        model_obj = serializer.from_definition(machine.model)
        obj = model_obj
        detector = None
        if isinstance(obj, DiffBasedAnomalyDetector):
            detector = obj
            obj = obj.base_estimator
        pipeline = None
        if isinstance(obj, Pipeline):
            pipeline = obj
            obj = obj.steps[-1][1]
        if not isinstance(obj, JaxBaseEstimator):
            return None
        if isinstance(obj, JaxLSTMBaseEstimator) and isinstance(
            detector, DiffBasedKFCVAnomalyDetector
        ):
            # scattered KFold test indices don't map cleanly onto window
            # semantics; keep exact reference behavior via the fallback
            return None
        dataset = (
            machine.dataset
            if isinstance(machine.dataset, GordoBaseDataset)
            else GordoBaseDataset.from_dict(machine.dataset)
        )
        return _Plan(
            machine=machine,
            dataset=dataset,
            model_obj=model_obj,
            detector=detector,
            pipeline=pipeline,
            estimator=obj,
        )

    # ------------------------------------------------------- bucket planning

    def _final_fit_plans(self, plans: List[_Plan]) -> List[_Plan]:
        """The plans whose machines will take the final fit (the member
        set a FleetPlan covers; ``cross_val_only`` machines never final-
        fit, and CV fold members pack live by design — fold models are
        shape-twins of their machine, differing only in weight masks)."""
        return [
            p
            for p in plans
            if not self._skipped(p.machine.name)
            and p.machine.evaluation.get("cv_mode", "full_build").lower()
            != "cross_val_only"
        ]

    def _plan_strategy_name(self) -> str:
        from ..planner import default_strategy

        return self.trainer.plan_strategy or default_strategy()

    @staticmethod
    def _plan_member_proxy(plan: _Plan):
        """A shape-only stand-in for the member ``plan`` will train: the
        packer reads name/spec/sample-count/aliasing, and building REAL
        members here would materialize every machine's shuffled window
        copies during the bucket_plan phase — resident through all of CV
        instead of appearing one final-fit bucket at a time."""
        if plan.windows is None:
            return types.SimpleNamespace(
                name=plan.machine.name,
                spec=plan.spec,
                series=range(len(plan.X_arr)),
                n_windows=len(plan.targets),
            )
        x_token = object()
        return types.SimpleNamespace(
            name=plan.machine.name,
            spec=plan.spec,
            n=len(plan.windows),
            X=x_token,
            y=x_token if plan.windows is plan.targets else object(),
        )

    def _compute_fleet_plan(self, final_plans: List[_Plan], strategy: str):
        """Pack the final-fit members into buckets and assemble the
        deterministic :class:`~gordo_tpu.planner.FleetPlan` artifact."""
        from .. import planner

        by_config: Dict[FitConfig, List[Any]] = {}
        for plan in final_plans:
            by_config.setdefault(plan.fit_config, []).append(
                self._plan_member_proxy(plan)
            )
        cost_model = self.trainer.cost_model()
        buckets_by_config = [
            (
                config,
                planner.plan_train_buckets(
                    members, config, strategy=strategy, cost_model=cost_model
                ),
            )
            for config, members in by_config.items()
        ]
        fingerprint = planner.config_fingerprint(
            [
                self._config_hashes.get(p.machine.name)
                or ModelBuilder.calculate_cache_key(p.machine)
                for p in final_plans
            ]
        )
        return planner.build_plan_doc(
            buckets_by_config,
            strategy,
            cost_model.mesh_shape,
            cost_model.table,
            fingerprint,
        )

    def _prepare_fleet_plan(self, plans: List[_Plan], output_dir: Optional[str]):
        """Fix the final-fit bucket composition BEFORE training: replay
        an externally provided plan (``build-fleet --plan-from``) or
        compute a fresh one, hand it to the trainer, persist it beside
        the artifacts, journal its hash, and export its predictions."""
        from .. import planner

        final_plans = self._final_fit_plans(plans)
        strategy = self._plan_strategy_name()
        if not final_plans:
            return
        with self._phase("bucket_plan"):
            plan = self._external_plan
            if plan is not None:
                expected = planner.config_fingerprint(
                    [
                        self._config_hashes.get(p.machine.name)
                        or ModelBuilder.calculate_cache_key(p.machine)
                        for p in final_plans
                    ]
                )
                recorded = str(plan.doc.get("config_fingerprint", ""))
                if recorded and recorded != expected:
                    # Stale plans stay usable: members it does not know
                    # (or whose data outgrew their pad target) repack
                    # live; warn so the operator re-plans eventually.
                    logger.warning(
                        "FleetPlan %s was computed for a different config "
                        "set (fingerprint %s != %s); unknown members will "
                        "be packed live",
                        plan.plan_hash,
                        recorded,
                        expected,
                    )
                strategy = plan.strategy or strategy
            else:
                plan = self._compute_fleet_plan(final_plans, strategy)
            self.trainer.fleet_plan = plan
            # The strategy must ride with the plan: members the plan
            # does not cover — every CV fold member, late additions —
            # pack live with trainer.plan_strategy, and a packed plan
            # replayed onto a default trainer would otherwise run its
            # whole CV phase naive while journal and gauges say packed.
            self.trainer.plan_strategy = strategy
            totals = plan.totals
            self.recorder.event(
                "fleet_plan",
                plan_hash=plan.plan_hash,
                strategy=strategy,
                replayed=self._external_plan is not None,
                buckets=totals.get("buckets", 0),
                members=totals.get("members", 0),
                compiles=totals.get("compiles", 0),
                predicted_wall_s=totals.get("predicted_wall_s", 0.0),
                padding_waste=totals.get("padding_waste", 0.0),
            )
            if output_dir is not None:
                try:
                    plan.save(os.path.join(output_dir, planner.PLAN_FILE))
                except OSError as exc:
                    logger.warning("FleetPlan not persisted: %r", exc)
            if self._journal is not None:
                # The replay-vs-replan signal --resume acts on: a resumed
                # build whose plan hash changed is REPLANNING the
                # remaining members (config or strategy drift), not
                # replaying the journaled build's shapes.
                previous = self._journal.plan()
                if previous and previous.get("plan_hash") != plan.plan_hash:
                    logger.info(
                        "FleetPlan %s differs from the journaled %s: "
                        "remaining members are replanned%s",
                        plan.plan_hash,
                        previous.get("plan_hash"),
                        ""
                        if self._external_plan is None
                        else " (a different --plan-from was supplied)",
                    )
                self._journal.set_plan(plan.plan_hash, strategy)
            try:
                from ..server.prometheus.metrics import set_fleet_plan_prediction

                set_fleet_plan_prediction(
                    self._project,
                    strategy,
                    float(totals.get("predicted_wall_s", 0.0)),
                    float(totals.get("padding_waste", 0.0)),
                    int(totals.get("compiles", 0)),
                )
            except Exception as exc:  # noqa: BLE001 - metrics are advisory
                logger.debug("Plan prediction gauges not exported: %r", exc)

    def plan_only(self):
        """Plan without training: machine planning + data fetch/stage +
        bucket packing, returning the :class:`~gordo_tpu.planner.FleetPlan`
        the `gordo-tpu plan` CLI renders and ``build-fleet --plan-from``
        replays. Machines that would fall back to the sequential builder
        (unsupported definitions) are not part of a fleet plan."""
        plans, fallbacks = self._plan_all()
        if fallbacks:
            logger.info(
                "%d machine(s) use the sequential builder and are not "
                "fleet-planned: %s",
                len(fallbacks),
                ", ".join(m.name for m in fallbacks[:5]),
            )
        plans = self._load_all_data(plans)
        return self._compute_fleet_plan(
            self._final_fit_plans(plans), self._plan_strategy_name()
        )

    def _export_plan_accuracy(self):
        """Predicted-vs-actual at build end: what the FleetPlan promised
        against the final-fit fit-programs the span listener observed."""
        plan = getattr(self.trainer, "fleet_plan", None)
        if plan is None:
            return
        totals = plan.totals
        actual_seconds = round(float(self._plan_actuals.get("seconds", 0.0)), 3)
        actual_compiles = int(self._plan_actuals.get("compiles", 0))
        # MEASURED utilization actuals beside the predicted numbers:
        # member-axis occupancy of the executed final-fit programs and
        # the max HBM peak Device.memory_stats() reported during the
        # build (None on backends without the stats) — the feedback the
        # ROADMAP's learned-performance-model work trains on.
        padded = int(self._member_actuals.get("padded", 0))
        measured_waste = (
            round(1.0 - self._member_actuals["live"] / padded, 6)
            if padded
            else None
        )
        measured_hbm = self._device_peak_bytes or None
        # the precision feature rides the accuracy record: which compute
        # precisions the planned programs ran at (the cost model's new
        # axis — predicted-vs-actual is only comparable per precision)
        try:
            from ..planner.costmodel import compute_precision

            plan_precisions = sorted(
                {compute_precision(bucket.spec) for bucket in plan.buckets}
            )
        except Exception:  # noqa: BLE001 - a replayed plan may carry
            # serialized bucket entries; the feature is advisory
            plan_precisions = None
        accuracy = dict(
            plan_hash=plan.plan_hash,
            strategy=plan.strategy,
            precisions=plan_precisions,
            predicted_compiles=totals.get("compiles", 0),
            actual_compiles=actual_compiles,
            predicted_wall_s=totals.get("predicted_wall_s", 0.0),
            actual_fit_s=actual_seconds,
            predicted_padding_waste=totals.get("padding_waste", 0.0),
            measured_member_waste=measured_waste,
            predicted_hbm_peak_bytes=totals.get("hbm_peak_bytes", 0),
            measured_hbm_peak_bytes=measured_hbm,
        )
        self.recorder.event("fleet_plan_accuracy", **accuracy)
        self._ledger.record_plan_accuracy(accuracy)
        try:
            from ..server.prometheus.metrics import set_fleet_plan_actuals

            set_fleet_plan_actuals(
                self._project, plan.strategy, actual_seconds, actual_compiles
            )
        except Exception as exc:  # noqa: BLE001 - metrics are advisory
            logger.debug("Plan actuals not exported: %r", exc)

    # ---------------------------------------------------------------- data

    def _load_all_data(self, plans: List[_Plan]) -> List[_Plan]:
        """Fetch + stage every plan; failed machines drop out of the fleet
        (failFast:false) and are recorded in ``build_errors``.

        Fetches retry with exponential backoff (``GORDO_TPU_DATA_RETRIES``
        extra attempts, ``GORDO_TPU_DATA_BACKOFF`` base seconds, optional
        per-machine ``GORDO_TPU_DATA_DEADLINE``) — the in-process analog
        of the reference builder pod's retryStrategy. Deterministic
        config errors (insufficient data, bad tags) are not retried."""
        from ..dataset.exceptions import ConfigException, InsufficientDataError

        def load(plan: _Plan):
            start = time.time()

            def fetch():
                fault_point("data_fetch", plan.machine.name)
                return plan.dataset.get_data()

            def note_retry(attempt: int, exc: BaseException):
                # Per-plan counter only: each plan's retries run in ONE
                # pool thread, so this is race-free; the fleet total is
                # summed on the main thread below (incrementing the shared
                # dict from 16 fetch threads would drop updates).
                plan.data_retries += 1
                logger.warning(
                    "Data fetch retry %d for %s after %r",
                    attempt,
                    plan.machine.name,
                    exc,
                )

            X, y = retry_call(
                fetch,
                attempts=1 + max(0, self.data_retries),
                backoff=self.data_backoff,
                deadline=self.data_deadline,
                no_retry=(ConfigException, InsufficientDataError),
                on_retry=note_retry,
            )
            plan.query_duration = time.time() - start
            plan.X, plan.y = X, y

        with self._phase("data_fetch"):
            pool = concurrent.futures.ThreadPoolExecutor(self.data_workers)
            try:
                outcomes = list(
                    pool.map(lambda p: _try_call(load, p), plans)
                )
            except (KeyboardInterrupt, SystemExit):
                # Same contract as _dump_all: a shutdown signal must not
                # wait on thousands of queued fetches (and their backoff
                # ladders) before the process dies.
                pool.shutdown(wait=True, cancel_futures=True)
                raise
            finally:
                pool.shutdown(wait=True)
        self.robustness["data_fetch_retries"] += sum(
            p.data_retries for p in plans
        )
        surviving = []
        with self._phase("stage"):
            for plan, exc in zip(plans, outcomes):
                if exc is not None:
                    self._fail(plan.machine.name, exc)
                    continue
                try:
                    self._stage_arrays(plan)
                except Exception as stage_exc:
                    self._fail(plan.machine.name, stage_exc)
                    continue
                surviving.append(plan)
        if self._journal is not None:
            for plan in surviving:
                self._journal.record(plan.machine.name, "data_loaded", flush=False)
            self._journal.flush()
        return surviving

    @staticmethod
    def _stage_arrays(plan: _Plan):
        """Fit host transformers, window if LSTM, resolve spec + fit config."""
        X_arr = np.asarray(plan.X.to_numpy(), np.float32)
        y_arr = np.asarray(plan.y.to_numpy(), np.float32)
        if plan.pipeline is not None and len(plan.pipeline.steps) > 1:
            transformed = plan.X
            for _, transformer in plan.pipeline.steps[:-1]:
                transformed = transformer.fit_transform(transformed, plan.y)
            X_arr = np.asarray(
                getattr(transformed, "to_numpy", lambda: transformed)(), np.float32
            )
        plan.X_arr, plan.y_arr = X_arr, y_arr

        est = plan.estimator
        est.kwargs.update(
            {"n_features": X_arr.shape[1], "n_features_out": y_arr.shape[1]}
        )
        fit_kwargs, factory_kwargs = split_fit_kwargs(est.sk_params)
        if isinstance(est, JaxLSTMBaseEstimator):
            lookback, lookahead = est.lookback_window, est.lookahead
            plan.offset = calc_model_offset(lookback, lookahead)
            plan.windows = None  # on-device windowing; series stays resident
            plan.targets = window_targets(y_arr, lookback, lookahead)
            plan.n_windows = len(plan.targets)
            fit_kwargs["shuffle"] = False
        else:
            plan.offset = 0
            # Pure-AE builds train y == X; aliasing lets the fleet stacker
            # stage (and transfer to device) the block once. The content
            # check is a host-side memcmp — orders of magnitude cheaper
            # than the duplicate copy + tunnel transfer it avoids.
            if (
                X_arr is not y_arr
                and X_arr.shape == y_arr.shape
                and np.array_equal(X_arr, y_arr)
            ):
                y_arr = X_arr
            plan.windows, plan.targets = X_arr, y_arr
            plan.n_windows = len(X_arr)
        if plan.detector is not None and getattr(plan.detector, "shuffle", False):
            # Sequential DiffBased.fit row-shuffles before training
            # (diff.py: sklearn_shuffle(..., random_state=0)); mirror it as
            # a stored permutation applied to training members only —
            # scoring always runs on chronological windows.
            from sklearn.utils import shuffle as sklearn_shuffle

            plan.shuffle_perm = sklearn_shuffle(
                np.arange(plan.n_windows), random_state=0
            )
        plan.spec = est._build_spec(factory_kwargs)
        config, host_callbacks = fit_config_from_kwargs(fit_kwargs)
        if host_callbacks:
            raise FleetBuildError(
                f"{plan.machine.name}: custom host callbacks are not supported "
                "in fleet builds"
            )
        plan.fit_config = config
        plan.seed = int(fit_kwargs.get("seed", 42))

    # ------------------------------------------------------------------- CV

    def _run_cross_validation(self, plans: List[_Plan]):
        """
        Per-fold fleet training. Fold boundaries become train-weight masks
        over window indices; every (spec, config) bucket trains all its
        machines' folds together.
        """
        start = time.time()
        fold_state: Dict[str, Dict[str, Any]] = {p.machine.name: {} for p in plans}

        max_folds = 0
        per_plan_folds: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        for plan in plans:
            try:
                splits = list(self._cv_for(plan).split(plan.X_arr))
                plan.cv_splits = self._split_metadata(plan, splits)
            except Exception as exc:
                self._fail(plan.machine.name, exc)
                continue
            per_plan_folds[plan.machine.name] = splits
            max_folds = max(max_folds, len(splits))

        # Every machine's EVERY fold goes into one member list per fit
        # config: fold models of the same (spec, shape) differ only in
        # their train-weight masks, so they join a single vmapped bucket
        # and the whole CV trains as ONE device program per architecture
        # group — one dispatch and one result fetch where a fold-major
        # loop paid max_folds of each (SURVEY §7: "fold = extra batch
        # axis"). Fold-major append order keeps per-machine fold order for
        # the threshold accumulators downstream.
        grouped: Dict[
            FitConfig, Tuple[List[Any], List[Tuple[_Plan, int]]]
        ] = {}
        for fold_idx in range(max_folds):
            for plan in plans:
                if self._skipped(plan.machine.name):
                    continue
                splits = per_plan_folds[plan.machine.name]
                if fold_idx >= len(splits):
                    continue
                train_idx, _ = splits[fold_idx]
                try:
                    weights = self._window_train_weights(plan, train_idx)
                    member = self._make_member(
                        plan,
                        weights,
                        seed=plan.seed + 1000 * (fold_idx + 1),
                        name=_fold_member_name(plan.machine.name, fold_idx),
                    )
                except Exception as exc:
                    self._fail(plan.machine.name, exc)
                    continue
                members, fold_items = grouped.setdefault(plan.fit_config, ([], []))
                members.append(member)
                fold_items.append((plan, fold_idx))
        for config, (members, fold_items) in grouped.items():
            live_items = [
                (plan, fold_idx)
                for plan, fold_idx in fold_items
                if not self._skipped(plan.machine.name)
            ]
            live_members = [
                m
                for m, (plan, _) in zip(members, fold_items)
                if not self._skipped(plan.machine.name)
            ]
            # Chunk by staged bytes: n_machines × n_folds members in ONE
            # program is the fast path, but an unbounded super-bucket
            # could out-size HBM on big fleets. Chunks preserve the
            # fold-major order (threshold accumulators are last-fold-wins
            # per machine).
            for chunk_members, chunk_items in _chunk_by_bytes(
                live_members, live_items, _cv_chunk_bytes()
            ):
                self._train_and_score_folds(
                    chunk_members, chunk_items, config, per_plan_folds, fold_state
                )

        with self._phase("cv_finalize"):
            for plan in plans:
                if self._skipped(plan.machine.name):
                    continue
                try:
                    self._finalize_cv(plan, fold_state[plan.machine.name])
                except Exception as exc:
                    self._fail(plan.machine.name, exc)
                    continue
                plan.cv_duration = time.time() - start

    @staticmethod
    def _make_member(
        plan: _Plan,
        train_weights: Optional[np.ndarray],
        seed: int,
        name: Optional[str] = None,
    ):
        """Training member with the detector-level shuffle applied.
        ``name`` overrides the member name (CV submits every fold of a
        machine into one bucket, so fold members need distinct names)."""
        perm = plan.shuffle_perm
        name = name or plan.machine.name
        if plan.windows is None:
            # Windowed (LSTM) path: ship the raw series; the shuffle becomes
            # the order map and weights move into virtual (shuffled) space.
            if perm is not None and train_weights is not None:
                train_weights = train_weights[perm]
            return WindowedFleetMember(
                name=name,
                spec=plan.spec,
                series=plan.X_arr,
                targets=plan.targets,
                order=perm,
                train_weights=train_weights,
                seed=seed,
            )
        if perm is None:
            X, y = plan.windows, plan.targets
        else:
            cached = getattr(plan, "_shuffled_windows_cache", None)
            if cached is None:
                X = plan.windows[perm]
                # Preserve y-is-X aliasing through the permutation gather.
                y = X if plan.targets is plan.windows else plan.targets[perm]
                plan._shuffled_windows_cache = (X, y)
            else:
                X, y = cached
            if train_weights is not None:
                train_weights = train_weights[perm]
        return FleetMember(
            name=name,
            spec=plan.spec,
            X=X,
            y=y,
            train_weights=train_weights,
            seed=seed,
        )

    @staticmethod
    def _cv_for(plan: _Plan):
        if isinstance(plan.detector, DiffBasedKFCVAnomalyDetector):
            return KFold(n_splits=5, shuffle=True, random_state=0)
        cv_def = plan.machine.evaluation.get("cv")
        if cv_def:
            return serializer.from_definition(cv_def)
        return TimeSeriesSplit(n_splits=3)

    def _window_train_weights(self, plan: _Plan, train_idx: np.ndarray) -> np.ndarray:
        """Row-index fold → window-index training mask."""
        n_windows = plan.n_windows
        weights = np.zeros(n_windows, np.float32)
        if plan.offset == 0:
            weights[train_idx[train_idx < n_windows]] = 1.0
        else:
            # windowed models need contiguous [0, b) folds (TimeSeriesSplit);
            # scattered folds have no clean window mapping
            if len(train_idx) != int(train_idx[-1]) - int(train_idx[0]) + 1:
                raise FleetBuildError(
                    f"{plan.machine.name}: non-contiguous CV folds are not "
                    "supported for windowed (LSTM) models in fleet builds"
                )
            boundary = int(train_idx[-1]) + 1
            weights[: max(boundary - plan.offset, 0)] = 1.0
        return weights

    def _test_window_rows(
        self, plan: _Plan, rows: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fold-test row indices → (window indices to predict, target rows)
        honoring the window offset. Only these windows are staged and
        forwarded — a fold's test split is ~1/(n_folds+1) of the series,
        so predicting all windows would move ~4× the data both ways."""
        if plan.offset == 0:
            rows = rows[rows < plan.n_windows]
            return rows, rows
        # contiguous test [b, c) → window indices [b, c - offset)
        b, c = int(rows[0]), int(rows[-1]) + 1
        window_idx = np.arange(b, max(c - plan.offset, b))
        window_idx = window_idx[window_idx < plan.n_windows]
        return window_idx, window_idx + plan.offset

    _SCORING_BATCH = 256  # windowed scoring scan batch (bounds HBM)

    def _train_and_score_folds(
        self, members, fold_items, config, per_plan_folds, fold_state
    ):
        """
        Train one chunk of fold members and score it. A failing chunk is
        split in half and retried (down to single members), so a bad
        machine — or a chunk that out-sizes device memory despite the
        byte budget — degrades to per-member isolation instead of taking
        every machine of the fit config down.
        """
        # A machine that failed in an earlier chunk of this config must not
        # waste device time training its remaining folds here (its
        # accumulators are dead — _finalize_cv skips failed machines).
        live = [
            i
            for i, (plan, _) in enumerate(fold_items)
            if not self._skipped(plan.machine.name)
        ]
        if len(live) != len(fold_items):
            members = [members[i] for i in live]
            fold_items = [fold_items[i] for i in live]
        if not members:
            return
        try:
            with self._phase("cv_train"):
                fold_results = self.trainer.train(members, config)
        except Exception as exc:
            # CV chunks split on ANY exception — unlike _train_final_group,
            # which gates on device errors. The asymmetry is deliberate:
            # CV's any-exception halving is the pinned bad-machine
            # isolation contract (a member-specific host error — bad
            # shapes, poisoned data — fails only its machine, at
            # O(N log N) retrain cost in the worst chunk-wide case),
            # while the final fit keeps its original fail-the-group
            # semantics for deterministic host errors.
            if len(members) > 1:
                logger.warning(
                    "CV chunk of %d fold-members failed (%s); splitting",
                    len(members),
                    exc,
                )
                self.robustness["bucket_bisects"] += 1
                for plan, _ in fold_items:
                    plan.bucket_bisects += 1
                mid = len(members) // 2
                self._train_and_score_folds(
                    members[:mid], fold_items[:mid], config,
                    per_plan_folds, fold_state,
                )
                self._train_and_score_folds(
                    members[mid:], fold_items[mid:], config,
                    per_plan_folds, fold_state,
                )
                return
            plan = fold_items[0][0]
            if is_device_error(exc):
                self._degrade(plan, exc)
            else:
                self._fail(plan.machine.name, exc)
            return
        # The trainer's own bucket bisection reports members that failed
        # in ISOLATION as error-results instead of raising: degrade those
        # machines to the sequential path first, then score only fold
        # results of machines still on the fleet path (a degraded
        # machine's OTHER folds in this chunk are dead too).
        for (plan, _), result in zip(fold_items, fold_results):
            if result.error is None or self._skipped(plan.machine.name):
                continue
            if is_device_error(result.error):
                self._degrade(plan, result.error)
            else:
                self._fail(plan.machine.name, result.error)
        scorable_items, scorable_results = [], []
        for (plan, fold_idx), result in zip(fold_items, fold_results):
            if result.error is not None or self._skipped(plan.machine.name):
                continue
            plan.fleet_retries += result.retries
            self.robustness["fleet_retries"] += result.retries
            scorable_items.append((plan, fold_idx))
            scorable_results.append(result)
        if not scorable_items:
            return
        try:
            self._score_folds(
                scorable_items, scorable_results, per_plan_folds, fold_state
            )
        except Exception as exc:
            for plan, _ in scorable_items:
                self._fail(plan.machine.name, exc)

    def _score_folds(self, fold_items, fold_results, per_plan_folds, fold_state):
        """
        Score trained fold models: ``fold_items`` is ``[(plan, fold_idx)]``
        in fold-major order (every fold of every machine of one fit
        config). One batched forward per (spec, geometry) group — all
        folds of all machines of an architecture predict in one dispatch.
        Windowed (LSTM) plans predict through the on-device window-gather
        scan; dense plans through the stacked forward.
        """
        by_name = {r.name: r for r in fold_results}
        groups: Dict[Tuple, List[Tuple[_Plan, int]]] = {}
        for plan, fold_idx in fold_items:
            geometry = (
                ("windowed",) if plan.windows is None else plan.windows.shape[1:]
            )
            groups.setdefault((plan.spec, geometry), []).append((plan, fold_idx))
        for (spec, geometry), group in groups.items():
            stacked = stack_member_params(
                [
                    by_name[_fold_member_name(p.machine.name, k)]
                    for p, k in group
                ]
            )
            fold_rows = []  # per item: (train_rows, window_idx, target_rows)
            for plan, fold_idx in group:
                train_rows, test_rows = per_plan_folds[plan.machine.name][fold_idx]
                window_idx, target_rows = self._test_window_rows(plan, test_rows)
                fold_rows.append((train_rows, window_idx, target_rows))
            with self._phase("cv_predict"):
                if geometry == ("windowed",):
                    predictions = self._predict_windowed_group(
                        spec,
                        stacked,
                        [p for p, _ in group],
                        [wi for _, wi, _ in fold_rows],
                    )
                else:
                    n_max = max(len(wi) for _, wi, _ in fold_rows)
                    X = np.zeros(
                        (len(group), n_max) + group[0][0].windows.shape[1:],
                        np.float32,
                    )
                    for i, (p, _) in enumerate(group):
                        X[i, : len(fold_rows[i][1])] = p.windows[fold_rows[i][1]]
                    predictions = self.trainer.predict_bucket(spec, stacked, X)
            with self._phase("cv_score"):
                for i, (plan, fold_idx) in enumerate(group):
                    train_rows, window_idx, target_rows = fold_rows[i]
                    y_true = plan.y_arr[target_rows]
                    y_pred = predictions[i, : len(window_idx)]
                    state = fold_state[plan.machine.name]
                    self._accumulate_metric_scores(plan, y_true, y_pred, fold_idx)
                    if plan.detector is not None:
                        self._accumulate_thresholds(
                            plan, y_true, y_pred, fold_idx, state,
                            y_train=plan.y_arr[train_rows],
                            test_rows=target_rows,
                        )

    def _predict_windowed_group(
        self,
        spec,
        stacked,
        group: List[_Plan],
        window_idx: List[np.ndarray],
    ) -> np.ndarray:
        """Predictions for windowed plans, windows gathered on device (scan
        over _SCORING_BATCH-window batches), model-axis sharded over the
        trainer's mesh like the dense scoring path. ``window_idx`` gives
        each plan's window positions to predict (the fold-test windows)."""
        orders = window_idx
        nv_max = max(len(o) for o in orders)
        n_series_max = max(len(p.X_arr) for p in group)
        series = np.zeros(
            (len(group), n_series_max, group[0].X_arr.shape[1]), np.float32
        )
        order = np.zeros((len(group), nv_max), np.int32)
        for i, p in enumerate(group):
            series[i, : len(p.X_arr)] = p.X_arr
            order[i, : len(orders[i])] = orders[i]
        return self.trainer.predict_windowed_bucket(
            spec, stacked, series, order, batch_size=self._SCORING_BATCH
        )

    @staticmethod
    def _scoring_setup(plan: _Plan):
        """Resolved metrics + the fitted scoring scaler, cached per plan —
        re-deriving them per fold was a measured CV hot spot (63ms per
        machine-fold at 20 tags on CPU)."""
        cached = getattr(plan, "_scoring_setup_cache", None)
        if cached is not None:
            return cached
        evaluation = plan.machine.evaluation
        metrics_list = ModelBuilder.metrics_from_list(evaluation.get("metrics"))
        scaler_def = evaluation.get("scoring_scaler")
        scaler = None
        if scaler_def:
            scaler = (
                serializer.from_definition(scaler_def)
                if isinstance(scaler_def, (str, dict))
                else scaler_def
            )
            # The scoring scaler always fits the FULL target frame (not
            # the fold), so one fit serves every fold.
            scaler = sklearn_clone(scaler).fit(plan.y_arr)
        plan._scoring_setup_cache = (metrics_list, scaler)
        return plan._scoring_setup_cache

    def _accumulate_metric_scores(self, plan, y_true, y_pred, fold_idx):
        metrics_list, scaler = self._scoring_setup(plan)
        if scaler is not None:
            y_true_s, y_pred_s = scaler.transform(y_true), scaler.transform(y_pred)
        else:
            y_true_s, y_pred_s = y_true, y_pred
        tags = [str(c) for c in plan.y.columns]
        fold_key = f"fold-{fold_idx + 1}"
        for metric in metrics_list:
            name = metric.__name__.replace("_", "-")
            per_tag = None
            vectorized = False
            try:
                # One vectorized call for all tags (sklearn regression
                # metrics support multioutput) instead of a Python loop of
                # per-column calls — ~20× fewer sklearn invocations.
                per_tag = np.asarray(
                    metric(y_true_s, y_pred_s, multioutput="raw_values")
                )
                vectorized = per_tag.shape == (len(tags),)
            except TypeError:
                pass
            if not vectorized:
                # Custom metrics may lack multioutput support — or swallow
                # the kwarg and return something else entirely; only trust
                # a correctly-shaped per-tag vector.
                per_tag = np.asarray(
                    [
                        metric(y_true_s[:, i], y_pred_s[:, i])
                        for i in range(len(tags))
                    ]
                )
            for i, tag in enumerate(tags):
                key = f"{name}-{tag.replace(' ', '-')}"
                plan.cv_scores.setdefault(key, {})[fold_key] = float(per_tag[i])
            # sklearn regression metrics aggregate with multioutput=
            # "uniform_average" — the plain mean of the raw_values vector —
            # so when the vectorized call succeeded the aggregate is free.
            plan.cv_scores.setdefault(name, {})[fold_key] = float(
                np.mean(per_tag) if vectorized else metric(y_true_s, y_pred_s)
            )

    @staticmethod
    def _rolling_min_max(values: np.ndarray, window: int):
        """
        ``pd.rolling(window).min().max()`` in vectorized numpy — the
        reference's threshold statistic (diff.py: max over time of the
        min over each ``window``-long run), ~20× cheaper than building a
        pandas object per (machine, fold). Matches pandas NaN semantics:
        windows containing NaN (min_periods=window counts valid values)
        are skipped by the NaN-aware max; no complete window → NaN.
        Works on ``[n]`` (returns float) and ``[n, k]`` (returns ``[k]``).
        """
        values = np.asarray(values, np.float64)
        if len(values) < window:
            return (
                np.nan if values.ndim == 1 else np.full(values.shape[1], np.nan)
            )
        mins = np.lib.stride_tricks.sliding_window_view(
            values, window, axis=0
        ).min(axis=-1)
        if np.isnan(mins).any():
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN slice
                out = np.nanmax(mins, axis=0)
        else:
            out = mins.max(axis=0)
        return float(out) if values.ndim == 1 else out

    @classmethod
    def _accumulate_thresholds(
        cls, plan, y_true, y_pred, fold_idx, state, y_train=None, test_rows=None
    ):
        detector = plan.detector
        # The fold model's scaler is fit on the fold-TRAIN targets
        # (reference: DiffBased.fit → scaler.fit(y) on the train split,
        # then _scaled_mse_per_timestep transforms the test rows with it)
        scaler = sklearn_clone(detector.scaler).fit(
            y_train if y_train is not None else y_true
        )
        scaled_mse = np.mean(
            np.square(scaler.transform(y_pred) - scaler.transform(y_true)), axis=1
        )
        abs_err = np.abs(y_true - y_pred)
        if isinstance(detector, DiffBasedKFCVAnomalyDetector):
            # KFold test rows are scattered; keep them with their original
            # row positions so errors can be re-stitched chronologically
            # before window smoothing (the sequential path smooths in time
            # order — diff.py KFCV cross_validate).
            state.setdefault("kfcv_parts", []).append(
                (np.asarray(test_rows), scaled_mse, abs_err)
            )
        else:
            state["aggregate_threshold"] = cls._rolling_min_max(scaled_mse, 6)
            tag_thresholds = pd.Series(
                cls._rolling_min_max(abs_err, 6), name=f"fold-{fold_idx}"
            )
            state.setdefault("feature_folds", {})[f"fold-{fold_idx}"] = tag_thresholds
            state.setdefault("agg_folds", {})[f"fold-{fold_idx}"] = state[
                "aggregate_threshold"
            ]
            if detector.window is not None:
                smooth_agg = cls._rolling_min_max(scaled_mse, detector.window)
                smooth_tags = pd.Series(
                    cls._rolling_min_max(abs_err, detector.window),
                    name=f"fold-{fold_idx}",
                )
                state["smooth_aggregate_threshold"] = smooth_agg
                state["smooth_feature_thresholds"] = smooth_tags
                state.setdefault("smooth_feature_folds", {})[
                    f"fold-{fold_idx}"
                ] = smooth_tags
                state.setdefault("smooth_agg_folds", {})[f"fold-{fold_idx}"] = smooth_agg

    def _finalize_cv(self, plan: _Plan, state: Dict[str, Any]):
        # fold-stat summary rows (fold-mean/std/min/max) like the reference
        for key, folds in plan.cv_scores.items():
            values = np.array(
                [v for k, v in folds.items() if k.startswith("fold-")]
            )
            folds.update(
                {
                    "fold-mean": float(values.mean()),
                    "fold-std": float(values.std()),
                    "fold-max": float(values.max()),
                    "fold-min": float(values.min()),
                }
            )
        detector = plan.detector
        if detector is None:
            return
        feature_names = [str(c) for c in plan.y.columns]
        if isinstance(detector, DiffBasedKFCVAnomalyDetector):
            # Stitch fold errors back into chronological (row) order before
            # rolling-window smoothing
            n = len(plan.y_arr)
            mse_full = np.full(n, np.nan)
            abs_full = np.full((n, len(feature_names)), np.nan)
            for rows, mse_part, abs_part in state["kfcv_parts"]:
                mse_full[rows] = mse_part
                abs_full[rows] = abs_part
            detector.aggregate_threshold_ = float(
                detector._calculate_threshold(pd.Series(mse_full))
            )
            thresholds = detector._calculate_threshold(
                pd.DataFrame(abs_full, columns=feature_names)
            )
            detector.feature_thresholds_ = thresholds
        elif "feature_folds" in state:
            folds_df = pd.DataFrame(state["feature_folds"]).T
            folds_df.columns = feature_names
            detector.feature_thresholds_per_fold_ = folds_df
            detector.aggregate_thresholds_per_fold_ = state["agg_folds"]
            last = folds_df.iloc[-1]
            last.name = folds_df.index[-1]
            detector.feature_thresholds_ = last
            detector.aggregate_threshold_ = state["aggregate_threshold"]
            detector.smooth_aggregate_threshold_ = state.get(
                "smooth_aggregate_threshold"
            )
            smooth = state.get("smooth_feature_thresholds")
            if smooth is not None:
                smooth = smooth.copy()
                smooth.index = feature_names
            detector.smooth_feature_thresholds_ = smooth
            if "smooth_feature_folds" in state:
                smooth_df = pd.DataFrame(state["smooth_feature_folds"]).T
                smooth_df.columns = feature_names
                detector.smooth_feature_thresholds_per_fold_ = smooth_df
                detector.smooth_aggregate_thresholds_per_fold_ = state[
                    "smooth_agg_folds"
                ]

    # ------------------------------------------------------------ final fit

    def _run_final_fit(self, plans: List[_Plan]):
        if not plans:
            return
        start = time.time()
        # group per distinct fit config to keep train() calls homogeneous
        by_config: Dict[FitConfig, List[_Plan]] = {}
        for plan in plans:
            by_config.setdefault(plan.fit_config, []).append(plan)
        for config, group in by_config.items():
            members, member_plans = [], []
            for plan in group:
                try:
                    members.append(self._make_member(plan, None, seed=plan.seed))
                    member_plans.append(plan)
                except Exception as exc:
                    self._fail(plan.machine.name, exc)
            if not members:
                continue
            self._train_final_group(members, member_plans, config, start)

    def _train_final_group(self, members, member_plans, config, start):
        """
        Final-fit one config group with the same degradation ladder as
        the CV chunks: a failing group splits in half and retries (down
        to single members), an isolated device failure degrades that one
        machine to the sequential builder, anything else fails just that
        machine — one poisonous machine or an over-packed group never
        takes the fleet's final fit down.
        """
        live = [
            i
            for i, plan in enumerate(member_plans)
            if not self._skipped(plan.machine.name)
        ]
        if len(live) != len(member_plans):
            members = [members[i] for i in live]
            member_plans = [member_plans[i] for i in live]
        if not members:
            return
        try:
            with self._phase("final_fit"):
                results = self.trainer.train(members, config)
        except Exception as exc:
            # Split-retry DEVICE errors only (the trainer's own rule): a
            # host-side exception is deterministic and would fail every
            # half identically — 2N-1 futile retrains of a 100-machine
            # group, each paying staging + compile. The trainer already
            # converts in-bucket device errors to error-results, so this
            # is the net for failures outside its per-bucket scope.
            if is_device_error(exc) and len(members) > 1:
                logger.warning(
                    "Final-fit group of %d members failed (%s); splitting",
                    len(members),
                    exc,
                )
                self.robustness["bucket_bisects"] += 1
                for plan in member_plans:
                    plan.bucket_bisects += 1
                mid = len(members) // 2
                self._train_final_group(
                    members[:mid], member_plans[:mid], config, start
                )
                self._train_final_group(
                    members[mid:], member_plans[mid:], config, start
                )
                return
            if is_device_error(exc):
                self._degrade(member_plans[0], exc)
                return
            for plan in member_plans:
                self._fail(plan.machine.name, exc)
            return
        for plan, result in zip(member_plans, results):
            if result.error is not None:
                if is_device_error(result.error):
                    self._degrade(plan, result.error)
                else:
                    self._fail(plan.machine.name, result.error)
                continue
            try:
                plan.fleet_retries += result.retries
                self.robustness["fleet_retries"] += result.retries
                plan.estimator.params_ = result.params
                plan.estimator.spec_ = plan.spec
                plan.estimator._history = result.history
                plan.train_duration = time.time() - start
                plan.training_summary = TrainingSummaryMetadata.from_history(
                    result.history
                )
                self.recorder.event(
                    "member_trained",
                    machine=plan.machine.name,
                    final_loss=plan.training_summary.final_loss,
                    best_loss=plan.training_summary.best_loss,
                    epochs_run=plan.training_summary.epochs_run,
                    early_stop_epoch=plan.training_summary.early_stop_epoch,
                    retries=result.retries,
                )
                if plan.detector is not None:
                    plan.detector.scaler.fit(plan.y)
            except Exception as exc:
                self._fail(plan.machine.name, exc)

    # ------------------------------------------------------------- assembly

    def _assemble(self, plan: _Plan) -> Tuple[Any, Machine]:
        machine = plan.machine.copy()
        machine.metadata.build_metadata = BuildMetadata(
            model=ModelBuildMetadata(
                model_offset=plan.offset,
                model_creation_date=str(
                    datetime.datetime.now(datetime.timezone.utc).astimezone()
                ),
                model_builder_version=gordo_tpu.__version__,
                model_training_duration_sec=plan.train_duration,
                cross_validation=CrossValidationMetaData(
                    cv_duration_sec=plan.cv_duration,
                    scores=plan.cv_scores,
                    splits=plan.cv_splits,
                ),
                model_meta=ModelBuilder._extract_metadata_from_model(plan.model_obj),
                training=plan.training_summary or TrainingSummaryMetadata(),
            ),
            dataset=DatasetBuildMetadata(
                query_duration_sec=plan.query_duration,
                dataset_meta=plan.dataset.get_metadata(),
            ),
            robustness=RobustnessMetadata(
                fleet_retries=plan.fleet_retries,
                bucket_bisects=plan.bucket_bisects,
                data_fetch_retries=plan.data_retries,
            ),
            drift_baseline=ModelBuilder._drift_baseline(plan.X),
        )
        return plan.model_obj, machine

    @staticmethod
    def _split_metadata(plan: _Plan, splits) -> Dict[str, Any]:
        metadata = {}
        index = plan.X.index
        for i, (train, test) in enumerate(splits):
            for label, idx in (("train", train), ("test", test)):
                for endpoint, pos in (("start", idx[0]), ("end", idx[-1])):
                    value = index[pos]
                    metadata[f"fold-{i + 1}-{label}-{endpoint}"] = (
                        value.isoformat() if hasattr(value, "isoformat") else int(value)
                    )
        return metadata


def fleet_build(
    machines: Sequence[Machine],
    output_dir: Optional[str] = None,
    trainer: Optional[FleetTrainer] = None,
    resume: bool = False,
) -> List[Tuple[Any, Machine]]:
    """Convenience wrapper: build the whole fleet."""
    return FleetBuilder(machines, trainer=trainer).build(
        output_dir=output_dir, resume=resume
    )


def rebuild_stale(
    machines: Sequence[Machine],
    stale_names: Sequence[str],
    output_dir: str,
    base_plan: Optional[Any] = None,
    base_plan_path: Optional[str] = None,
    resume: bool = True,
    trainer: Optional[FleetTrainer] = None,
    health_ledger: Optional[Any] = None,
) -> FleetBuilder:
    """
    Partial-fleet rebuild: train ONLY ``stale_names`` (the drift-tripped
    subset the lifecycle loop hands in) into ``output_dir``, leaving
    every other member untouched — the incremental half of the
    self-healing loop (``gordo_tpu.lifecycle``).

    Reuses the full crash-safety stack: the rebuild keeps its own
    journal in ``output_dir`` and ``resume=True`` (the default — a
    lifecycle restart must converge on the same canary, not restart it)
    skips members already rebuilt. When the base build's FleetPlan is
    available (``base_plan`` in memory or ``base_plan_path`` on disk,
    typically ``<base revision>/fleet_plan.json``) it is REPLAYED:
    :meth:`~gordo_tpu.planner.FleetPlan.materialize_buckets` re-binds
    bucket rosters by name, so a stale member keeps its planned pad
    targets and trains under the exact program shape of its original
    build — members the plan does not cover (or whose data outgrew the
    pad target) repack live, and the untouched majority is simply never
    in the member list.

    Returns the builder (artifacts + journal are in ``output_dir``;
    callers read ``build_errors``/``resumed`` off it).
    """
    stale = set(stale_names)
    unknown = stale - {m.name for m in machines}
    if unknown:
        raise FleetBuildError(
            f"stale members not in the machine set: {sorted(unknown)}"
        )
    if base_plan is None and base_plan_path and os.path.isfile(base_plan_path):
        from ..planner import FleetPlan

        try:
            base_plan = FleetPlan.load(base_plan_path)
        except ValueError as exc:
            logger.warning(
                "Base FleetPlan %s unusable (%s); stale members pack live",
                base_plan_path,
                exc,
            )
    builder = FleetBuilder(
        [m for m in machines if m.name in stale],
        trainer=trainer,
        fleet_plan=base_plan,
        # provenance belongs in the CALLER's (anchor) ledger, not one
        # keyed to this staging dir nothing ever reads
        health_ledger=health_ledger,
    )
    builder.build(output_dir=output_dir, resume=resume)
    return builder
