from .fleet import (
    FleetMember,
    FleetResult,
    FleetTrainer,
    WindowedFleetMember,
    is_device_error,
)
from .fleet_build import FleetBuilder, fleet_build, rebuild_stale
from .journal import BuildJournal, artifact_complete, clean_staging_dirs
from .sequence import ring_windowed_anomaly_scores, ring_windowed_predict
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    initialize_backend,
    make_mesh,
    model_data_sharding,
    model_sharding,
)

__all__ = [
    "FleetTrainer",
    "FleetMember",
    "WindowedFleetMember",
    "FleetResult",
    "FleetBuilder",
    "fleet_build",
    "rebuild_stale",
    "is_device_error",
    "BuildJournal",
    "artifact_complete",
    "clean_staging_dirs",
    "make_mesh",
    "model_sharding",
    "model_data_sharding",
    "initialize_backend",
    "MODEL_AXIS",
    "DATA_AXIS",
    "ring_windowed_predict",
    "ring_windowed_anomaly_scores",
]
