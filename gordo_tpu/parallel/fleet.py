"""
The fleet trainer: thousands of per-machine models as one stacked,
vmapped, mesh-sharded computation.

This is the TPU-native replacement for the reference's scale axis — one
Argo-scheduled k8s pod per model build
(argo-workflow.yml.template:1519-1598). Here the fleet becomes:

1. **Bucketing** — machines are grouped by (ModelSpec, FitConfig, padded
   shape). Specs are frozen dataclasses, so each distinct architecture
   geometry compiles exactly once regardless of fleet size (no retrace
   storms).
2. **Stacking** — each bucket's data becomes ``X[M, N, ...]`` with weight
   masks expressing ragged lengths, validation splits and CV-fold
   boundaries (masks are *data*, so per-machine differences never cause
   recompilation).
3. **vmap + GSPMD** — the single-model fused fit program
   (models/training.py: one jitted scan over epochs×batches) is vmapped
   over the model axis and sharded over a ``(models, data)`` mesh;
   training M models is a single device program. The model axis needs no
   collectives; sharding the sample axis makes XLA insert gradient psums
   over ``data``.

RNG: each member trains with its own fold of a PRNG key, so fleet results
are independent of bucket composition and deterministic per seed.
"""

import logging
from collections import defaultdict
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..models.nn import forward_fn_for, init_fn_for
from ..models.spec import ModelSpec
from ..models.training import FitConfig, History, build_raw_fit_fn
from .mesh import make_mesh, model_data_sharding, model_sharding

logger = logging.getLogger(__name__)


@dataclass
class FleetMember:
    """One machine's training problem, already staged as arrays."""

    name: str
    spec: ModelSpec
    X: np.ndarray  # [n, ...features]
    y: np.ndarray  # [n, n_features_out]
    train_weights: Optional[np.ndarray] = None  # defaults to all rows
    val_weights: Optional[np.ndarray] = None
    seed: int = 42

    def __post_init__(self):
        if len(self.X) != len(self.y):
            raise ValueError(
                f"{self.name}: X ({len(self.X)}) and y ({len(self.y)}) lengths differ"
            )

    @property
    def n(self) -> int:
        return len(self.X)


@dataclass
class FleetResult:
    name: str
    params: Any  # host numpy pytree
    history: History


def host_prng_keys(seeds: Sequence[int]) -> np.ndarray:
    """
    Threefry PRNG keys built host-side, bit-identical to
    ``jax.random.PRNGKey(seed)`` (the uint32 pair ``(seed >> 32, seed &
    0xFFFFFFFF)`` in two's complement). ``PRNGKey`` is a tiny device
    program per call — at fleet scale those round trips dominated staging
    (measured 3.4s/1024 members over the axon tunnel);
    tests/parallel/test_fleet.py asserts the bit-equality.
    """
    if jax.config.jax_enable_x64:
        # int64 two's complement for negative seeds, like PRNGKey.
        raw = np.asarray(seeds, np.int64).view(np.uint64)
        hi = (raw >> np.uint64(32)).astype(np.uint32)
        lo = (raw & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    else:
        # x64 disabled (the default): PRNGKey casts the seed to int32, so
        # the high word is always zero and the low word wraps modulo 2^32.
        lo = np.asarray(seeds, np.int64).astype(np.int32).view(np.uint32)
        hi = np.zeros_like(lo)
    return np.stack([hi, lo], axis=-1)


@lru_cache(maxsize=None)
def _fleet_fit_program(spec: ModelSpec, config: FitConfig):
    """jit(vmap) of the raw fused fit over a leading model axis."""
    raw_fit = build_raw_fit_fn(spec, config)
    return jax.jit(jax.vmap(raw_fit))


@lru_cache(maxsize=None)
def fleet_predict_program(spec: ModelSpec):
    """jit(vmap) forward: (stacked params, X[M, N, ...]) -> [M, N, out]."""
    forward = forward_fn_for(spec)

    def predict(params, X):
        return forward(spec, params, X)[0]

    return jax.jit(jax.vmap(predict))


@lru_cache(maxsize=None)
def _fleet_init_program(spec: ModelSpec):
    init = init_fn_for(spec)

    def init_one(key):
        return init(key, spec)

    return jax.jit(jax.vmap(init_one))


class FleetTrainer:
    """
    Trains homogeneous-spec buckets of models as single device programs.

    Parameters
    ----------
    mesh
        Fleet mesh (default: all local devices on the model axis).
    """

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()

    # -- bucketing ----------------------------------------------------------

    @staticmethod
    def bucket(
        members: Sequence[FleetMember], config: FitConfig
    ) -> Dict[Tuple, List[FleetMember]]:
        """
        Group members into compilation buckets. The padded sample count is
        rounded up to the next power of two (≥ one batch) so ragged fleets
        land in few distinct shapes.
        """
        buckets: Dict[Tuple, List[FleetMember]] = defaultdict(list)
        for member in members:
            n_padded = _round_up_pow2(member.n, config.batch_size)
            buckets[(member.spec, n_padded)].append(member)
        return dict(buckets)

    # -- training -----------------------------------------------------------

    def train(
        self,
        members: Sequence[FleetMember],
        config: FitConfig,
        initial_params: Optional[Any] = None,
    ) -> List[FleetResult]:
        """
        Train all members (auto-bucketed); returns one FleetResult per
        member in input order.
        """
        by_name: Dict[str, FleetResult] = {}
        for (spec, n_padded), bucket in self.bucket(members, config).items():
            logger.info(
                "Fleet bucket: %d models, spec=%s, padded_n=%d",
                len(bucket),
                type(spec).__name__,
                n_padded,
            )
            for result in self._train_bucket(spec, n_padded, bucket, config):
                by_name[result.name] = result
        return [by_name[m.name] for m in members]

    def _stack_bucket(
        self, spec: ModelSpec, n_padded: int, bucket: List[FleetMember], config: FitConfig
    ):
        """Stack + mask a bucket; returns device-sharded arrays.

        The model axis is padded with zero-weight dummies up to a multiple
        of the mesh's model-axis size (sharding requires divisibility);
        dummy results are dropped by the caller. The sample axis is padded
        to a multiple of the data-axis size for the same reason.
        """
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        m_total = -(-len(bucket) // model_axis) * model_axis
        # The sample axis must stay a whole number of batches (the fit
        # program reshapes [steps, batch]) AND divide across the data axis.
        step = int(np.lcm(config.batch_size, data_axis))
        n_padded = -(-n_padded // step) * step

        def stacked(attr_arrays):
            # Fill a preallocated block instead of pad-then-np.stack: one
            # copy per member, zero rows double as sample padding and
            # zero-weight dummy models.
            out = np.zeros(
                (m_total, n_padded) + np.shape(attr_arrays[0])[1:], np.float32
            )
            for i, a in enumerate(attr_arrays):
                out[i, : len(a)] = a
            return out

        X = stacked([m.X for m in bucket])
        # The AE fleet overwhelmingly trains y == X; staging X once and
        # aliasing saves a second 100s-of-MB host copy + tunnel transfer.
        y = X if all(m.y is m.X for m in bucket) else stacked([m.y for m in bucket])

        wtr = np.zeros((m_total, n_padded), np.float32)
        wval = np.zeros((m_total, n_padded), np.float32)
        for i, member in enumerate(bucket):
            if member.train_weights is not None:
                wtr[i, : member.n] = member.train_weights
            else:
                n_val = int(member.n * config.validation_split)
                wtr[i, : member.n - n_val] = 1.0
                if n_val:
                    wval[i, member.n - n_val : member.n] = 1.0
            if member.val_weights is not None:
                wval[i, : member.n] = member.val_weights

        rngs = host_prng_keys([m.seed for m in bucket] + [0] * (m_total - len(bucket)))
        w_sharding = model_data_sharding(self.mesh)
        X_dev = jax.device_put(X, model_data_sharding(self.mesh, extra_dims=X.ndim - 2))
        y_dev = (
            X_dev
            if y is X
            else jax.device_put(y, model_data_sharding(self.mesh, extra_dims=y.ndim - 2))
        )
        wtr, wval, rngs = jax.device_put(
            (wtr, wval, rngs),
            (w_sharding, w_sharding, model_sharding(self.mesh, extra_dims=1)),
        )
        return X_dev, y_dev, wtr, wval, rngs

    def _train_bucket(
        self,
        spec: ModelSpec,
        n_padded: int,
        bucket: List[FleetMember],
        config: FitConfig,
    ) -> List[FleetResult]:
        X, y, wtr, wval, rngs = self._stack_bucket(spec, n_padded, bucket, config)

        # Mirror fit_single's derivation exactly so a fleet member trains
        # bit-for-bit like the single-model path: fit rng and init rng are
        # the two halves of split(PRNGKey(seed)).
        split_keys = jax.vmap(jax.random.split)(rngs)
        rngs, init_rngs = split_keys[:, 0], split_keys[:, 1]
        params = _fleet_init_program(spec)(init_rngs)
        params = jax.device_put(params, model_sharding(self.mesh, extra_dims=0))
        tx = spec.optimizer.to_optax()
        opt_state = jax.jit(jax.vmap(tx.init))(params)

        fit = _fleet_fit_program(spec, config)
        params, _, losses, val_losses, epochs_ran = fit(
            params, opt_state, X, y, wtr, X, y, wval, rngs
        )

        host_params = jax.device_get(params)
        losses = np.asarray(losses)
        val_losses = np.asarray(val_losses)
        epochs_ran = np.asarray(epochs_ran)

        results = []
        for i, member in enumerate(bucket):
            ran = int(epochs_ran[i])
            history = {"loss": [float(l) for l in losses[i][:ran]]}
            member_val = val_losses[i][:ran]
            # NaN marks "no validation rows for this member" (see
            # weighted_mean_loss); only members with real validation data
            # get a val_loss history.
            if ran and not np.all(np.isnan(member_val)):
                history["val_loss"] = [float(l) for l in member_val]
            member_params = jax.tree_util.tree_map(
                lambda a: np.asarray(a[i]), host_params
            )
            results.append(
                FleetResult(
                    name=member.name,
                    params=member_params,
                    history=History(
                        history=history,
                        params={
                            "epochs": config.epochs,
                            "steps": n_padded // config.batch_size,
                            "verbose": 0,
                            "metrics": list(history),
                        },
                        epoch=list(range(ran)),
                    ),
                )
            )
        return results

    # -- prediction ---------------------------------------------------------

    def predict_bucket(
        self, spec: ModelSpec, stacked_params, X: np.ndarray
    ) -> np.ndarray:
        """Forward the whole bucket: X[M, N, ...] -> [M, N, out]."""
        X = np.asarray(X, np.float32)
        m = X.shape[0]
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        m_total = -(-m // model_axis) * model_axis
        n = X.shape[1]
        n_total = -(-n // data_axis) * data_axis
        if m_total != m or n_total != n:
            padded = np.zeros((m_total, n_total) + X.shape[2:], X.dtype)
            padded[:m, :n] = X
            X = padded
            stacked_params = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [a, np.repeat(np.asarray(a)[:1], m_total - m, axis=0)]
                )
                if m_total != m
                else np.asarray(a),
                stacked_params,
            )
        X = jax.device_put(X, model_data_sharding(self.mesh, extra_dims=X.ndim - 2))
        out = np.asarray(fleet_predict_program(spec)(stacked_params, X))
        return out[:m, :n]


def _round_up_pow2(n: int, batch_size: int) -> int:
    """Pad target: next power of two, at least one full batch."""
    target = max(n, batch_size)
    power = 1
    while power < target:
        power <<= 1
    return ((power + batch_size - 1) // batch_size) * batch_size


def stack_member_params(results: Sequence[FleetResult]):
    """Re-stack per-member host params into a fleet pytree (serving path)."""
    return jax.tree_util.tree_map(
        lambda *leaves: np.stack(leaves), *[r.params for r in results]
    )
