"""
The fleet trainer: thousands of per-machine models as one stacked,
vmapped, mesh-sharded computation.

This is the TPU-native replacement for the reference's scale axis — one
Argo-scheduled k8s pod per model build
(argo-workflow.yml.template:1519-1598). Here the fleet becomes:

1. **Bucketing** — machines are grouped by (ModelSpec, FitConfig, padded
   shape). Specs are frozen dataclasses, so each distinct architecture
   geometry compiles exactly once regardless of fleet size (no retrace
   storms).
2. **Stacking** — each bucket's data becomes ``X[M, N, ...]`` with weight
   masks expressing ragged lengths, validation splits and CV-fold
   boundaries (masks are *data*, so per-machine differences never cause
   recompilation).
3. **vmap + GSPMD** — the single-model fused fit program
   (models/training.py: one jitted scan over epochs×batches) is vmapped
   over the model axis and sharded over a ``(models, data)`` mesh;
   training M models is a single device program. The model axis needs no
   collectives; sharding the sample axis makes XLA insert gradient psums
   over ``data``.

RNG: each member trains with its own fold of a PRNG key, so fleet results
are independent of bucket composition and deterministic per seed.
"""

import logging
from dataclasses import dataclass, replace
from functools import lru_cache
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import telemetry
from ..models.nn import forward_fn_for, init_fn_for
from ..models.spec import ModelSpec
from ..models.training import (
    FitConfig,
    History,
    build_raw_fit_fn,
    segmented_config,
)
from ..planner.costmodel import (
    CostModel,
    spec_flops_per_sample,
    spec_param_count,
)
# _round_up_pow2 (the historical dense pad target) now lives in the
# planner — the naive strategy is its one implementation; re-exported
# here for the long-standing import path.
from ..planner.packing import (  # noqa: F401
    _round_up_pow2,
    naive_pad_target,
    plan_train_buckets,
)
from ..utils.faults import InjectedDeviceError, fault_point
from .mesh import make_mesh, model_data_sharding, model_sharding

logger = logging.getLogger(__name__)

try:  # the canonical runtime-error alias moved between jax versions
    from jax.errors import JaxRuntimeError as _XlaRuntimeError
except ImportError:  # pragma: no cover - older jaxlib spelling
    from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError


def is_device_error(exc: BaseException) -> bool:
    """True for failures raised BY a device program — XLA runtime errors
    (``RESOURCE_EXHAUSTED`` OOMs, preempted/poisoned device programs) and
    their injected test stand-ins. These are the failures worth bucket
    bisection: the bucket may simply be over-packed, or one member's
    geometry may be poisonous, and retrying halves isolates which.
    Host-side errors (bad config, data bugs) are deterministic and are
    NOT classified as device errors."""
    if isinstance(exc, (InjectedDeviceError, _XlaRuntimeError)):
        return True
    return "RESOURCE_EXHAUSTED" in str(exc)


@dataclass
class FleetMember:
    """One machine's training problem, already staged as arrays."""

    name: str
    spec: ModelSpec
    X: np.ndarray  # [n, ...features]
    y: np.ndarray  # [n, n_features_out]
    train_weights: Optional[np.ndarray] = None  # defaults to all rows
    val_weights: Optional[np.ndarray] = None
    seed: int = 42

    def __post_init__(self):
        if len(self.X) != len(self.y):
            raise ValueError(
                f"{self.name}: X ({len(self.X)}) and y ({len(self.y)}) lengths differ"
            )

    @property
    def n(self) -> int:
        return len(self.X)


@dataclass
class WindowedFleetMember:
    """
    One windowed (LSTM) machine's training problem as the RAW series plus
    window bookkeeping — windows are gathered on device per batch
    (models/training.py build_raw_windowed_fit_fn), so fleet HBM holds
    ``[n, F]`` per member instead of the ``lookback×`` window blowup.
    """

    name: str
    spec: ModelSpec  # an LSTMSpec (carries lookback_window)
    series: np.ndarray  # [n, F] raw input series
    targets: np.ndarray  # [n_windows, F_out] via ops.windows.window_targets
    order: Optional[np.ndarray] = None  # virtual slot -> window start; None=arange
    train_weights: Optional[np.ndarray] = None  # per virtual slot
    val_weights: Optional[np.ndarray] = None
    seed: int = 42

    def __post_init__(self):
        lookback = self.spec.lookback_window
        # Validate on the window count (targets length), not raw series
        # length: lookahead shortens the window set too, and zero windows
        # would otherwise train nothing yet report a clean 0.0-loss history.
        if len(self.targets) < 1:
            raise ValueError(
                f"{self.name}: series of {len(self.series)} rows too short "
                f"for lookback {lookback} (no complete windows)"
            )

    @property
    def n_windows(self) -> int:
        return len(self.targets)


@dataclass
class FleetResult:
    name: str
    params: Any  # host numpy pytree (None when ``error`` is set)
    history: History
    seed: int = 0  # the RNG seed this member actually trained with
    retries: int = 0  # diverged-member reseed retries that led to this result
    #: set when this member's device program failed in ISOLATION after
    #: bucket bisection — the member trained nothing; callers decide the
    #: degradation policy (FleetBuilder falls back to the sequential
    #: ModelBuilder path)
    error: Optional[BaseException] = None


def _bucket_nbytes(bucket) -> int:
    """Raw staged bytes of a bucket's members (span attribution)."""
    total = 0
    for member in bucket:
        if isinstance(member, WindowedFleetMember):
            total += member.series.nbytes + member.targets.nbytes
        else:
            total += member.X.nbytes
            if member.y is not member.X:
                total += member.y.nbytes
    return total


def _calibration_attrs(
    spec: ModelSpec, config: FitConfig, stacked_members: int, stacked_samples: int
):
    """The cost model's static features on a ``device_program`` span —
    exactly what :func:`gordo_tpu.planner.costmodel.calibrate` reads back
    from ``build_trace.jsonl`` to fit per-program correction factors."""
    return dict(
        params=spec_param_count(spec),
        flops_per_sample=spec_flops_per_sample(spec),
        stacked_members=int(stacked_members),
        stacked_samples=int(stacked_samples),
        epochs=config.epochs,
    )


def _traced_outputs(outputs):
    """Block on a device program's outputs when a telemetry recorder is
    active, so the enclosing program span times real device work — jit
    dispatch is async and would otherwise measure ~0 for cache hits. The
    fetch right after waits on the same buffers, so the extra sync is
    free; with telemetry off this is a pass-through."""
    if telemetry.get_recorder().enabled:
        return jax.block_until_ready(outputs)
    return outputs


def _fill_weight_row(wtr, wval, i, n, member, config: FitConfig):
    """One member's train/val masks: explicit weights, or the Keras-style
    tail validation split over its ``n`` (virtual) samples."""
    if member.train_weights is not None:
        wtr[i, : len(member.train_weights)] = member.train_weights
    else:
        n_val = int(n * config.validation_split)
        wtr[i, : n - n_val] = 1.0
        if n_val:
            wval[i, n - n_val : n] = 1.0
    if member.val_weights is not None:
        wval[i, : len(member.val_weights)] = member.val_weights


#: jit'd ravel+concat of same-dtype leaves: turns a many-leaf pytree fetch
#: into one contiguous device buffer, so the host sees ONE transfer.
_flat_concat = jax.jit(lambda *leaves: jnp.concatenate([l.ravel() for l in leaves]))

#: _flat_concat compiles one XLA program per distinct (leaf count, shapes,
#: dtypes) signature for the process lifetime; trees with more leaves than
#: this are coalesced in chunks of this size rather than per-leaf — the
#: largest fleets are exactly where per-leaf round trips (~70ms each over
#: a tunneled accelerator) hurt most, while chunking keeps each program's
#: signature bounded so the jit cache can't grow without limit.
_FLAT_CONCAT_MAX_LEAVES = 256


def fetch_to_host(tree):
    """
    Device arrays → host numpy, multi-host safe: results of the sharded
    fleet programs span every process's devices, and ``device_get`` cannot
    fetch non-addressable shards — each process instead all-gathers the
    global value (one collective over ICI/DCN, symmetric across the SPMD
    processes). Single-process runs keep the plain ``device_get`` path.

    Single-process fetches of multi-leaf pytrees are COALESCED: every
    same-dtype leaf is raveled and concatenated on-device (one fused XLA
    program), fetched as one contiguous buffer, and sliced back on the
    host. Device→host readback pays a fixed per-transfer latency (PCIe
    round trip; ~70ms through a remote-accelerator tunnel), so fetching a
    fleet's params/losses/epoch-counters as 11+ separate arrays costs 11
    round trips where one or two suffice — this was 90% of measured fleet
    training wall-clock on a tunneled TPU v5e.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # tiled=True is the only mode for global arrays (and for them it
        # just means "replicate the global value", no reshaping).
        return multihost_utils.process_allgather(tree, tiled=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if len(leaves) <= 1 or not all(isinstance(l, jax.Array) for l in leaves):
        return jax.device_get(tree)
    by_dtype: Dict[Any, List[int]] = {}
    for idx, leaf in enumerate(leaves):
        by_dtype.setdefault(leaf.dtype, []).append(idx)
    host_leaves: List[Any] = [None] * len(leaves)
    for idxs in by_dtype.values():
        for start in range(0, len(idxs), _FLAT_CONCAT_MAX_LEAVES):
            chunk = idxs[start : start + _FLAT_CONCAT_MAX_LEAVES]
            group = [leaves[i] for i in chunk]
            flat = np.asarray(_flat_concat(*group))
            offset = 0
            for i, leaf in zip(chunk, group):
                size = leaf.size
                # copy: a view would pin the whole coalesced buffer for as
                # long as any one leaf lives (e.g. one member's params kept
                # in a FleetResult would retain every pack's)
                host_leaves[i] = (
                    flat[offset : offset + size].reshape(leaf.shape).copy()
                )
                offset += size
    return jax.tree_util.tree_unflatten(treedef, host_leaves)


def host_prng_keys(seeds: Sequence[int]) -> np.ndarray:
    """
    Threefry PRNG keys built host-side, bit-identical to
    ``jax.random.PRNGKey(seed)`` (the uint32 pair ``(seed >> 32, seed &
    0xFFFFFFFF)`` in two's complement). ``PRNGKey`` is a tiny device
    program per call — at fleet scale those round trips dominated staging
    (measured 3.4s/1024 members over the axon tunnel);
    tests/parallel/test_fleet.py asserts the bit-equality.
    """
    if jax.config.jax_enable_x64:
        # int64 two's complement for negative seeds, like PRNGKey.
        raw = np.asarray(seeds, np.int64).view(np.uint64)
        hi = (raw >> np.uint64(32)).astype(np.uint32)
        lo = (raw & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    else:
        # x64 disabled (the default): PRNGKey casts the seed to int32, so
        # the high word is always zero and the low word wraps modulo 2^32.
        lo = np.asarray(seeds, np.int64).astype(np.int32).view(np.uint32)
        hi = np.zeros_like(lo)
    return np.stack([hi, lo], axis=-1)


@lru_cache(maxsize=None)
def _fleet_fit_program(spec: ModelSpec, config: FitConfig):
    """jit(vmap) of the raw fused fit over a leading model axis."""
    raw_fit = build_raw_fit_fn(spec, config)
    return jax.jit(jax.vmap(raw_fit))


@lru_cache(maxsize=None)
def _fleet_windowed_fit_program(spec: ModelSpec, config: FitConfig):
    """jit(vmap) of the on-device-windowing fused fit over the model axis."""
    from ..models.training import build_raw_windowed_fit_fn

    raw_fit = build_raw_windowed_fit_fn(spec, config)
    return jax.jit(jax.vmap(raw_fit))


@lru_cache(maxsize=None)
def _fleet_segmented_fit_program(
    spec: ModelSpec, config: FitConfig, segments_per_update: int
):
    """jit(vmap) of the segmented (stateful-scan) LSTM fit over the model
    axis (models/training.py build_raw_segmented_fit_fn)."""
    from ..models.training import build_raw_segmented_fit_fn

    raw_fit = build_raw_segmented_fit_fn(spec, config, segments_per_update)
    return jax.jit(jax.vmap(raw_fit))


#: the shared GORDO_TPU_LSTM_SEGMENTED knob parser lives beside the
#: segmented program builder (models/training.py) — both the fleet and
#: the single-model estimator path read it from there
_segmented_config = segmented_config


@lru_cache(maxsize=None)
def fleet_windowed_predict_program(spec: ModelSpec, batch_size: int):
    """
    jit(vmap) forward for windowed members: windows gathered from the raw
    series per scan step, so prediction memory stays bounded like training.

    ``(stacked params, series[M, n, F], order[M, nv]) -> [M, nv, F_out]``
    (``nv`` must be a multiple of ``batch_size``).
    """
    import jax.numpy as jnp

    forward = forward_fn_for(spec)
    lookback = spec.lookback_window

    def predict_one(params, series, order):
        steps = order.shape[0] // batch_size

        def step(_, starts):
            idx = starts[:, None] + jnp.arange(lookback)[None, :]
            out, _ = forward(spec, params, series[idx])
            return None, out

        _, outs = jax.lax.scan(
            step, None, order.reshape(steps, batch_size)
        )
        return outs.reshape(steps * batch_size, -1)

    return jax.jit(jax.vmap(predict_one))


@lru_cache(maxsize=None)
def fleet_predict_program(spec: ModelSpec):
    """jit(vmap) forward: (stacked params, X[M, N, ...]) -> [M, N, out]."""
    forward = forward_fn_for(spec)

    def predict(params, X):
        return forward(spec, params, X)[0]

    return jax.jit(jax.vmap(predict))


@lru_cache(maxsize=None)
def _packed_fit_program(pspec, config: FitConfig):
    """jit(vmap) of the packed block-diagonal fit over the pack axis."""
    from ..models.packing import build_packed_fit_fn

    return jax.jit(jax.vmap(build_packed_fit_fn(pspec, config)))


@lru_cache(maxsize=None)
def _packed_init_program(pspec):
    from ..models.packing import init_packed

    return jax.jit(jax.vmap(lambda keys: init_packed(keys, pspec)))


@lru_cache(maxsize=None)
def _fleet_init_program(spec: ModelSpec):
    init = init_fn_for(spec)

    def init_one(key):
        return init(key, spec)

    return jax.jit(jax.vmap(init_one))


class FleetTrainer:
    """
    Trains homogeneous-spec buckets of models as single device programs.

    Parameters
    ----------
    mesh
        Fleet mesh (default: all local devices on the model axis).
    packing
        Block-diagonal model packing (models/packing.py): ``None``/1 off,
        an int for a fixed factor, or ``"auto"`` to fill the 128-lane MXU
        tile (``128 // widest layer``). Packing G models turns G tiny
        matmuls into one tile-filling matmul — per-model math is
        preserved exactly (masked block-diagonal weights; see the module
        docstring for the shared-shuffle caveat). Applies to feedforward
        buckets without early stopping; everything else falls back to the
        unpacked program.
    plan_strategy
        Bucket-construction strategy (``gordo_tpu.planner``): ``naive``
        (the historical exact-key grouping; the default, also via
        ``GORDO_TPU_PLAN_STRATEGY``) or ``packed`` (cost-model bin
        packing: geometric shape ladders, HBM caps, compile budget).
    fleet_plan
        An optional :class:`gordo_tpu.planner.FleetPlan`: members the
        plan covers train in their planned buckets with their planned
        pad targets; uncovered members (CV folds) pack live with
        ``plan_strategy``.
    cost_table
        A calibrated :class:`gordo_tpu.planner.CostTable` for the packed
        strategy's cost model (default: the analytic table).
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        packing=None,
        plan_strategy: Optional[str] = None,
        fleet_plan: Optional[Any] = None,
        cost_table: Optional[Any] = None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.packing = packing
        self.plan_strategy = plan_strategy
        self.fleet_plan = fleet_plan
        self.cost_table = cost_table
        #: lifetime count of device-error bucket bisection events (the
        #: FleetBuilder folds the per-build delta into its robustness
        #: counters / Prometheus export)
        self.bucket_bisects = 0
        #: lifetime per-member split-event counts (member name -> events
        #: its bucket rode through); lets the builder attribute trainer-
        #: internal bisections to machines in BuildMetadata.robustness
        self.bisect_counts: Dict[str, int] = {}

    def _packing_factor(self, spec, n_members: int, config: FitConfig) -> int:
        from ..models.packing import auto_packing
        from ..models.spec import FeedForwardSpec

        if not self.packing or self.packing == 1:
            return 1
        if not isinstance(spec, FeedForwardSpec):
            return 1
        if config.early_stopping is not None:
            return 1
        from ..ops.losses import resolve_loss

        try:
            resolve_loss(spec.loss)
        except ValueError:
            return 1
        if self.packing == "auto":
            return auto_packing(spec, n_members)
        return max(1, min(int(self.packing), n_members))

    # -- bucketing ----------------------------------------------------------
    # Bucket construction lives in gordo_tpu.planner.packing
    # (plan_train_buckets); the ``naive`` strategy there reproduces the
    # grouping that used to be FleetTrainer.bucket/bucket_windowed.

    def cost_model(self) -> CostModel:
        """The planner cost model bound to this trainer's mesh shape."""
        shape = self.mesh.devices.shape
        mesh_shape = (shape[0], shape[1] if len(shape) > 1 else 1)
        return CostModel(self.cost_table, mesh_shape=mesh_shape)

    def train(
        self,
        members: Sequence[Any],
        config: FitConfig,
        initial_params: Optional[Any] = None,
        retry_failed: int = 1,
    ) -> List[FleetResult]:
        """
        Train all members (auto-bucketed); returns one FleetResult per
        member in input order. Accepts a mix of dense ``FleetMember``s and
        ``WindowedFleetMember``s (LSTM series with on-device windowing).

        ``retry_failed``: members whose training diverged (non-finite final
        loss) are re-vmapped into a retry bucket with a reseeded RNG, up to
        this many times — the chip-level analog of the reference DAG's
        per-pod retryStrategy (SURVEY.md §2.9 elasticity row).

        CONTRACT: a member whose device program fails in ISOLATION (after
        bucket bisection of an ``XlaRuntimeError``/``RESOURCE_EXHAUSTED``)
        does NOT raise — it returns a ``FleetResult`` with ``params=None``
        and the exception in ``error``. Callers must check
        ``result.error`` before using ``result.params`` (FleetBuilder
        degrades such machines to the sequential builder). Host-side
        exceptions still raise for the whole call, as before.
        """
        results = self._train_once(members, config)
        for attempt in range(1, retry_failed + 1):
            failed_idx = [
                i
                for i, r in enumerate(results)
                if r.history.history["loss"]
                and not np.isfinite(r.history.history["loss"][-1])
            ]
            if not failed_idx:
                break
            logger.warning(
                "Fleet retry %d: %d member(s) diverged (%s); reseeding",
                attempt,
                len(failed_idx),
                ", ".join(results[i].name for i in failed_idx[:5]),
            )
            retry_members = []
            for i in failed_idx:
                member = replace(
                    members[i], seed=members[i].seed + 7919 * attempt
                )
                retry_members.append(member)
            retried = self._train_once(retry_members, config)
            for i, result in zip(failed_idx, retried):
                result.retries = attempt
                result.history.params["fleet_retry"] = {
                    "retries": attempt,
                    "seed": result.seed,
                }
                results[i] = result
        return results

    def _train_once(
        self, members: Sequence[Any], config: FitConfig
    ) -> List[FleetResult]:
        by_name: Dict[str, FleetResult] = {}
        failures: Dict[str, BaseException] = {}
        planned = plan_train_buckets(
            members,
            config,
            strategy=self.plan_strategy,
            cost_model=self.cost_model(),
            plan=self.fleet_plan,
        )
        def bucket_m_padded(pb, b):
            """The planned member-axis floor — only while the bucket is
            intact. A bisected half (the OOM recovery ladder) must NOT
            pad back up to the planned rung, or every half re-OOMs at
            the original shape and bisection can never converge."""
            return pb.m_padded if len(b) == len(pb.members) else None

        for pb in planned:
            bucket = pb.members
            if pb.windowed:
                logger.info(
                    "Windowed fleet bucket %s: %d models, spec=%s, padded_n=%d",
                    pb.bucket_id,
                    len(bucket),
                    type(pb.spec).__name__,
                    pb.n_padded,
                )
                self._run_bucket_degraded(
                    lambda b, _p=pb: self._train_windowed_bucket(
                        _p.spec, _p.n_padded, _p.offset, b, config,
                        m_padded=bucket_m_padded(_p, b),
                    ),
                    bucket,
                    by_name,
                    failures,
                )
                continue
            # Sibling HBM-split buckets rely on the shared m_padded rung
            # for their one-compile contract; the block-diagonal packed
            # program has no member-axis floor, so those buckets skip it.
            g = (
                self._packing_factor(pb.spec, len(bucket), config)
                if pb.m_padded is None
                else 1
            )
            logger.info(
                "Fleet bucket %s: %d models, spec=%s, padded_n=%d%s",
                pb.bucket_id,
                len(bucket),
                type(pb.spec).__name__,
                pb.n_padded,
                f", packed x{g}" if g > 1 else "",
            )
            self._run_bucket_degraded(
                lambda b, _p=pb, _g=g: (
                    self._train_bucket_packed(_p.spec, _p.n_padded, b, config, _g)
                    if _g > 1
                    else self._train_bucket(
                        _p.spec, _p.n_padded, b, config,
                        m_padded=bucket_m_padded(_p, b),
                    )
                ),
                bucket,
                by_name,
                failures,
            )
        for member in members:
            if member.name in failures:
                by_name[member.name] = FleetResult(
                    name=member.name,
                    params=None,
                    history=History(history={"loss": []}, params={}, epoch=[]),
                    seed=member.seed,
                    error=failures[member.name],
                )
        return [by_name[m.name] for m in members]

    def _run_bucket_degraded(self, run, bucket, by_name, failures) -> None:
        """
        Run one bucket's device program with degradation: an
        ``XlaRuntimeError``/``RESOURCE_EXHAUSTED`` failure bisects the
        bucket and retries each half recursively — an over-packed bucket
        resolves by splitting, a poisonous member is isolated down to a
        single-member program whose failure lands in ``failures`` (the
        member's FleetResult carries it as ``error``) instead of taking
        the whole fleet down. Host-side exceptions propagate unchanged:
        they are deterministic and would fail every half identically.
        """
        try:
            for member in bucket:
                fault_point("device_program", member.name)
            results = run(bucket)
        except Exception as exc:
            if not is_device_error(exc):
                raise
            if len(bucket) == 1:
                logger.error(
                    "Device program failed for member %s in isolation: %r",
                    bucket[0].name,
                    exc,
                )
                telemetry.get_recorder().event(
                    "member_isolated", member=bucket[0].name, error=repr(exc)
                )
                failures[bucket[0].name] = exc
                return
            mid = len(bucket) // 2
            self.bucket_bisects += 1
            telemetry.get_recorder().event(
                "bucket_bisect", members=len(bucket), error=repr(exc)
            )
            for member in bucket:
                self.bisect_counts[member.name] = (
                    self.bisect_counts.get(member.name, 0) + 1
                )
            logger.warning(
                "Device program failed for bucket of %d members (%s); "
                "bisecting into %d + %d",
                len(bucket),
                exc,
                mid,
                len(bucket) - mid,
            )
            self._run_bucket_degraded(run, bucket[:mid], by_name, failures)
            self._run_bucket_degraded(run, bucket[mid:], by_name, failures)
            return
        for result in results:
            by_name[result.name] = result

    def _stack_bucket(
        self,
        spec: ModelSpec,
        n_padded: int,
        bucket: List[FleetMember],
        config: FitConfig,
        m_padded: Optional[int] = None,
    ):
        """Stack + mask a bucket; returns device-sharded arrays.

        The model axis is padded with zero-weight dummies up to a multiple
        of the mesh's model-axis size (sharding requires divisibility);
        dummy results are dropped by the caller. The sample axis is padded
        to a multiple of the data-axis size for the same reason.
        ``m_padded`` raises the member-axis floor further (the packed
        planner pads sibling HBM-split buckets to one shared rung so they
        reuse a single compiled program).
        """
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        m_floor = max(len(bucket), m_padded or 0)
        m_total = -(-m_floor // model_axis) * model_axis
        # The sample axis must stay a whole number of batches (the fit
        # program reshapes [steps, batch]) AND divide across the data axis.
        step = int(np.lcm(config.batch_size, data_axis))
        n_padded = -(-n_padded // step) * step

        def stacked(attr_arrays):
            # Fill a preallocated block instead of pad-then-np.stack: one
            # copy per member, zero rows double as sample padding and
            # zero-weight dummy models.
            out = np.zeros(
                (m_total, n_padded) + np.shape(attr_arrays[0])[1:], np.float32
            )
            for i, a in enumerate(attr_arrays):
                out[i, : len(a)] = a
            return out

        X = stacked([m.X for m in bucket])
        # The AE fleet overwhelmingly trains y == X; staging X once and
        # aliasing saves a second 100s-of-MB host copy + tunnel transfer.
        y = X if all(m.y is m.X for m in bucket) else stacked([m.y for m in bucket])

        wtr = np.zeros((m_total, n_padded), np.float32)
        wval = np.zeros((m_total, n_padded), np.float32)
        for i, member in enumerate(bucket):
            _fill_weight_row(wtr, wval, i, member.n, member, config)

        rngs = host_prng_keys([m.seed for m in bucket] + [0] * (m_total - len(bucket)))
        w_sharding = model_data_sharding(self.mesh)
        X_dev = jax.device_put(X, model_data_sharding(self.mesh, extra_dims=X.ndim - 2))
        y_dev = (
            X_dev
            if y is X
            else jax.device_put(y, model_data_sharding(self.mesh, extra_dims=y.ndim - 2))
        )
        wtr, wval, rngs = jax.device_put(
            (wtr, wval, rngs),
            (w_sharding, w_sharding, model_sharding(self.mesh, extra_dims=1)),
        )
        return X_dev, y_dev, wtr, wval, rngs

    def _train_bucket(
        self,
        spec: ModelSpec,
        n_padded: int,
        bucket: List[FleetMember],
        config: FitConfig,
        m_padded: Optional[int] = None,
    ) -> List[FleetResult]:
        X, y, wtr, wval, rngs = self._stack_bucket(
            spec, n_padded, bucket, config, m_padded=m_padded
        )
        params, opt_state, rngs = self._init_bucket_params(spec, rngs)
        fit = _fleet_fit_program(spec, config)
        with telemetry.program_span(
            "fleet_fit",
            (spec, config, X.shape),
            members=len(bucket),
            shape=str(tuple(X.shape)),
            spec=type(spec).__name__,
            bytes=_bucket_nbytes(bucket),
            **_calibration_attrs(spec, config, X.shape[0], X.shape[1]),
        ):
            params, _, losses, val_losses, epochs_ran = _traced_outputs(
                fit(params, opt_state, X, y, wtr, X, y, wval, rngs)
            )
        return self._collect_results(
            bucket, params, losses, val_losses, epochs_ran, config,
            steps=n_padded // config.batch_size,
        )

    # -- packed training ----------------------------------------------------

    def _train_bucket_packed(
        self,
        spec: ModelSpec,
        n_padded: int,
        bucket: List[FleetMember],
        config: FitConfig,
        g: int,
    ) -> List[FleetResult]:
        """
        Train the bucket as ceil(M/G) block-diagonal supermodels
        (models/packing.py): G members share each device matmul, filling
        the MXU tile that a single tiny model would leave ~99% idle.
        Downstream (scoring, serving, artifacts) sees ordinary per-member
        params — unpacking happens right here.
        """
        from ..models.packing import (
            PackedFeedForwardSpec,
            init_packed,
            unpack_params,
        )

        pspec = PackedFeedForwardSpec(base=spec, g=g)
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        packs = -(-len(bucket) // g)
        packs_total = -(-packs // model_axis) * model_axis
        m_total = packs_total * g
        step = int(np.lcm(config.batch_size, data_axis))
        n_padded = -(-n_padded // step) * step

        f_in, f_out = spec.n_features, spec.n_features_out
        # AE fleets overwhelmingly train y == X; aliasing skips the second
        # [P, n, G·F] host block and its device transfer (same optimization
        # as _stack_bucket's).
        aliased = f_in == f_out and all(m.y is m.X for m in bucket)
        X = np.zeros((packs_total, n_padded, g * f_in), np.float32)
        y = X if aliased else np.zeros((packs_total, n_padded, g * f_out), np.float32)
        wtr = np.zeros((packs_total, n_padded, g), np.float32)
        wval = np.zeros((packs_total, n_padded, g), np.float32)
        for i, member in enumerate(bucket):
            p, gi = divmod(i, g)
            X[p, : member.n, gi * f_in : (gi + 1) * f_in] = member.X
            if not aliased:
                y[p, : member.n, gi * f_out : (gi + 1) * f_out] = member.y
            row_tr = np.zeros((1, n_padded), np.float32)
            row_val = np.zeros((1, n_padded), np.float32)
            _fill_weight_row(row_tr, row_val, 0, member.n, member, config)
            wtr[p, :, gi] = row_tr[0]
            wval[p, :, gi] = row_val[0]

        # Per-member RNG parity with the unpacked path: each member's key
        # splits into (fit, init) halves; the pack trains with its first
        # member's fit key (one shared shuffle stream per pack).
        seeds = [m.seed for m in bucket] + [0] * (m_total - len(bucket))
        member_keys = host_prng_keys(seeds)
        split_keys = jax.vmap(jax.random.split)(member_keys)
        fit_keys = np.asarray(split_keys[:, 0]).reshape(packs_total, g, 2)[:, 0]
        init_keys = np.asarray(split_keys[:, 1]).reshape(packs_total, g, 2)

        md1 = model_data_sharding(self.mesh, extra_dims=1)
        X_dev, wtr_dev, wval_dev = jax.device_put((X, wtr, wval), (md1, md1, md1))
        y_dev = X_dev if aliased else jax.device_put(y, md1)
        fit_rngs, init_rngs = jax.device_put(
            (fit_keys, init_keys),
            (
                model_sharding(self.mesh, extra_dims=1),
                model_sharding(self.mesh, extra_dims=2),
            ),
        )

        params = _packed_init_program(pspec)(init_rngs)
        params = jax.device_put(params, model_sharding(self.mesh, extra_dims=0))
        opt_state = jax.jit(jax.vmap(spec.optimizer.to_optax().init))(params)
        fit = _packed_fit_program(pspec, config)
        with telemetry.program_span(
            "fleet_packed_fit",
            (pspec, config, X.shape),
            members=len(bucket),
            packed=g,
            shape=str(tuple(X.shape)),
            spec=type(spec).__name__,
            bytes=_bucket_nbytes(bucket),
            **_calibration_attrs(spec, config, m_total, n_padded),
        ):
            params, _, losses, val_losses = _traced_outputs(
                fit(
                    params, opt_state, X_dev, y_dev, wtr_dev,
                    X_dev, y_dev, wval_dev, fit_rngs,
                )
            )

        host_params, losses, val_losses = fetch_to_host((params, losses, val_losses))
        losses = np.asarray(losses)
        val_losses = np.asarray(val_losses)

        results = []
        steps = n_padded // config.batch_size
        for i, member in enumerate(bucket):
            p, gi = divmod(i, g)
            pack_params = jax.tree_util.tree_map(lambda a: a[p], host_params)
            member_params = jax.tree_util.tree_map(
                np.asarray, unpack_params(pack_params, pspec, gi)
            )
            history = {"loss": [float(l) for l in losses[p][:, gi]]}
            member_val = val_losses[p][:, gi]
            if not np.all(np.isnan(member_val)):
                history["val_loss"] = [float(l) for l in member_val]
            results.append(
                FleetResult(
                    name=member.name,
                    seed=member.seed,
                    params=member_params,
                    history=History(
                        history=history,
                        params={
                            "epochs": config.epochs,
                            "steps": steps,
                            "verbose": 0,
                            "metrics": list(history),
                            "packed": g,
                        },
                        epoch=list(range(config.epochs)),
                    ),
                )
            )
        return results

    def _init_bucket_params(self, spec: ModelSpec, rngs):
        """Per-member init mirroring fit_single's derivation exactly so a
        fleet member trains bit-for-bit like the single-model path: fit rng
        and init rng are the two halves of split(PRNGKey(seed))."""
        split_keys = jax.vmap(jax.random.split)(rngs)
        rngs, init_rngs = split_keys[:, 0], split_keys[:, 1]
        params = _fleet_init_program(spec)(init_rngs)
        params = jax.device_put(params, model_sharding(self.mesh, extra_dims=0))
        opt_state = jax.jit(jax.vmap(spec.optimizer.to_optax().init))(params)
        return params, opt_state, rngs

    # -- windowed training --------------------------------------------------

    def _stack_windowed_bucket(
        self,
        spec: ModelSpec,
        n_padded: int,
        offset: int,
        bucket: List[WindowedFleetMember],
        config: FitConfig,
        m_padded: Optional[int] = None,
    ):
        """Stack a windowed bucket; series replicated over the data axis.

        The per-batch window gather indexes arbitrary series rows, so the
        series (and aligned targets) shard over ``models`` only; the
        virtual window axis (order + weights) shards over ``data``.
        """
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        m_floor = max(len(bucket), m_padded or 0)
        m_total = -(-m_floor // model_axis) * model_axis
        nw_padded = n_padded - offset
        step = int(np.lcm(config.batch_size, data_axis))
        nv_padded = -(-nw_padded // step) * step

        f_in = bucket[0].series.shape[1]
        f_out = bucket[0].targets.shape[1]
        series = np.zeros((m_total, n_padded, f_in), np.float32)
        ytgt = np.zeros((m_total, nw_padded, f_out), np.float32)
        order = np.zeros((m_total, nv_padded), np.int32)
        wtr = np.zeros((m_total, nv_padded), np.float32)
        wval = np.zeros((m_total, nv_padded), np.float32)
        for i, member in enumerate(bucket):
            series[i, : len(member.series)] = member.series
            ytgt[i, : member.n_windows] = member.targets
            nv = member.n_windows
            order[i, :nv] = (
                member.order if member.order is not None else np.arange(nv)
            )
            _fill_weight_row(wtr, wval, i, nv, member, config)

        rngs = host_prng_keys(
            [m.seed for m in bucket] + [0] * (m_total - len(bucket))
        )
        md = model_data_sharding(self.mesh)
        series, ytgt, order, wtr, wval, rngs = jax.device_put(
            (series, ytgt, order, wtr, wval, rngs),
            (
                model_sharding(self.mesh, extra_dims=2),
                model_sharding(self.mesh, extra_dims=2),
                md,
                md,
                md,
                model_sharding(self.mesh, extra_dims=1),
            ),
        )
        return series, ytgt, order, wtr, wval, rngs

    def _segmented_eligible(
        self, bucket: List[WindowedFleetMember], config: FitConfig
    ) -> Optional[int]:
        """Segments-per-update when the opt-in segmented path applies to
        this bucket, else None. Segments need consecutive windows, so any
        shuffle or explicit member ordering/weighting keeps the
        window-restart path."""
        segments = _segmented_config()
        if not segments or config.shuffle:
            return None
        if config.batch_size % segments:
            return None
        if any(
            m.order is not None
            or m.train_weights is not None
            or m.val_weights is not None
            for m in bucket
        ):
            return None
        return segments

    def _train_windowed_bucket(
        self,
        spec: ModelSpec,
        n_padded: int,
        offset: int,
        bucket: List[WindowedFleetMember],
        config: FitConfig,
        m_padded: Optional[int] = None,
    ) -> List[FleetResult]:
        series, ytgt, order, wtr, wval, rngs = self._stack_windowed_bucket(
            spec, n_padded, offset, bucket, config, m_padded=m_padded
        )
        params, opt_state, rngs = self._init_bucket_params(spec, rngs)
        segments = self._segmented_eligible(bucket, config)
        span_attrs = dict(
            members=len(bucket),
            shape=str(tuple(series.shape)),
            spec=type(spec).__name__,
            bytes=_bucket_nbytes(bucket),
            **_calibration_attrs(
                spec, config, series.shape[0], order.shape[1]
            ),
        )
        if segments is not None:
            logger.info(
                "Segmented LSTM training: %d segments/update (L=%d)",
                segments,
                config.batch_size // segments,
            )
            fit = _fleet_segmented_fit_program(spec, config, segments)
            with telemetry.program_span(
                "fleet_segmented_fit",
                (spec, config, segments, series.shape),
                **span_attrs,
            ):
                params, _, losses, val_losses, epochs_ran = _traced_outputs(
                    fit(params, opt_state, series, ytgt, wtr, wval, rngs)
                )
        else:
            fit = _fleet_windowed_fit_program(spec, config)
            with telemetry.program_span(
                "fleet_windowed_fit",
                (spec, config, series.shape, order.shape),
                **span_attrs,
            ):
                params, _, losses, val_losses, epochs_ran = _traced_outputs(
                    fit(params, opt_state, series, ytgt, order, wtr, wval, rngs)
                )
        return self._collect_results(
            bucket, params, losses, val_losses, epochs_ran, config,
            steps=order.shape[1] // config.batch_size,
        )

    def _collect_results(
        self, bucket, params, losses, val_losses, epochs_ran, config, steps
    ) -> List[FleetResult]:
        host_params, losses, val_losses, epochs_ran = fetch_to_host(
            (params, losses, val_losses, epochs_ran)
        )
        losses = np.asarray(losses)
        val_losses = np.asarray(val_losses)
        epochs_ran = np.asarray(epochs_ran)

        results = []
        for i, member in enumerate(bucket):
            ran = int(epochs_ran[i])
            history = {"loss": [float(l) for l in losses[i][:ran]]}
            member_val = val_losses[i][:ran]
            # NaN marks "no validation rows for this member" (see
            # weighted_mean_loss); only members with real validation data
            # get a val_loss history.
            if ran and not np.all(np.isnan(member_val)):
                history["val_loss"] = [float(l) for l in member_val]
            member_params = jax.tree_util.tree_map(
                lambda a: np.asarray(a[i]), host_params
            )
            results.append(
                FleetResult(
                    name=member.name,
                    seed=member.seed,
                    params=member_params,
                    history=History(
                        history=history,
                        params={
                            "epochs": config.epochs,
                            "steps": steps,
                            "verbose": 0,
                            "metrics": list(history),
                        },
                        epoch=list(range(ran)),
                    ),
                )
            )
        return results

    # -- prediction ---------------------------------------------------------

    def predict_bucket(
        self, spec: ModelSpec, stacked_params, X: np.ndarray
    ) -> np.ndarray:
        """Forward the whole bucket: X[M, N, ...] -> [M, N, out]."""
        X = np.asarray(X, np.float32)
        m = X.shape[0]
        model_axis = self.mesh.devices.shape[0]
        data_axis = self.mesh.devices.shape[1] if self.mesh.devices.ndim > 1 else 1
        m_total = -(-m // model_axis) * model_axis
        n = X.shape[1]
        n_total = -(-n // data_axis) * data_axis
        if m_total != m or n_total != n:
            padded = np.zeros((m_total, n_total) + X.shape[2:], X.dtype)
            padded[:m, :n] = X
            X = padded
            stacked_params = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [a, np.repeat(np.asarray(a)[:1], m_total - m, axis=0)]
                )
                if m_total != m
                else np.asarray(a),
                stacked_params,
            )
        X = jax.device_put(X, model_data_sharding(self.mesh, extra_dims=X.ndim - 2))
        with telemetry.program_span(
            "fleet_predict",
            (spec, X.shape),
            members=m,
            shape=str(tuple(X.shape)),
            spec=type(spec).__name__,
        ):
            out = np.asarray(
                fetch_to_host(fleet_predict_program(spec)(stacked_params, X))
            )
        return out[:m, :n]

    def predict_windowed_bucket(
        self,
        spec: ModelSpec,
        stacked_params,
        series: np.ndarray,
        order: np.ndarray,
        batch_size: int = 256,
    ) -> np.ndarray:
        """
        Forward a windowed bucket with on-device window gathering, sharded
        over the mesh's model axis like :meth:`predict_bucket`:
        ``series[M, n, F]`` + ``order[M, nv]`` → ``[M, nv, F_out]``
        (``nv`` is padded to a whole number of ``batch_size`` batches here).
        """
        series = np.asarray(series, np.float32)
        order = np.asarray(order, np.int32)
        m = series.shape[0]
        model_axis = self.mesh.devices.shape[0]
        m_total = -(-m // model_axis) * model_axis
        nv = order.shape[1]
        nv_pad = -(-nv // batch_size) * batch_size
        if m_total != m or nv_pad != nv:
            series = np.concatenate(
                [series, np.repeat(series[:1], m_total - m, axis=0)]
            ) if m_total != m else series
            padded_order = np.zeros((m_total, nv_pad), np.int32)
            padded_order[:m, :nv] = order
            order = padded_order
            stacked_params = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [a, np.repeat(np.asarray(a)[:1], m_total - m, axis=0)]
                )
                if m_total != m
                else np.asarray(a),
                stacked_params,
            )
        ms2 = model_sharding(self.mesh, extra_dims=2)
        series = jax.device_put(series, ms2)
        order = jax.device_put(order, model_sharding(self.mesh, extra_dims=1))
        with telemetry.program_span(
            "fleet_windowed_predict",
            (spec, batch_size, series.shape, order.shape),
            members=m,
            shape=str(tuple(series.shape)),
            spec=type(spec).__name__,
        ):
            out = np.asarray(
                fetch_to_host(
                    fleet_windowed_predict_program(spec, batch_size)(
                        stacked_params, series, order
                    )
                )
            )
        return out[:m, :nv]


def stack_member_params(results: Sequence[FleetResult]):
    """Re-stack per-member host params into a fleet pytree (serving path)."""
    return jax.tree_util.tree_map(
        lambda *leaves: np.stack(leaves), *[r.params for r in results]
    )
