"""
Sequence (time-axis) parallelism for long-series scoring.

The reference handles sequence length purely by *windowing* on one CPU
(``create_keras_timeseriesgenerator``, gordo/machine/model/models.py:713-793);
a decade-long 10-minute-resolution series (~500k rows) would be scored row
by row through a single process. Here the time axis itself becomes a mesh
axis: each device holds a contiguous chunk of the series, pulls the
``lookback + lookahead - 1`` halo rows it needs from its right-hand
neighbor over ICI with one ``jax.lax.ppermute``, builds its windows
locally, and runs the forward pass — so scoring an N-row series on D chips
touches N/D rows per chip and one tiny collective, instead of an N-row
gather on one device.

This is the ring/halo-exchange pattern of context parallelism specialised
to finite windows: because gordo models have no attention (SURVEY.md §5
"Long-context"), the dependency footprint of output row k is exactly rows
``[k, k + lookback + lookahead)`` — a fixed halo, not the whole sequence —
so a single neighbor exchange replaces the full ring rotation.

Works on any 1-D slice of a mesh; the fleet's ``data`` axis is the natural
choice. All shapes are static: the series is padded to a multiple of the
axis size, every device computes the same number of windows, and the
(globally meaningless) tail windows computed from padding are trimmed on
the host.
"""

import logging
from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
try:  # moved out of experimental in newer JAX
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map

import inspect as _inspect

# newer JAX: check_vma; older: check_rep — either must be off for the
# replicated-carry + sharded-sequence LSTM scan (see local_score).
_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(shard_map).parameters
    else "check_rep"
)

from ..ops.windows import model_offset, sliding_windows
from .mesh import DATA_AXIS

logger = logging.getLogger(__name__)

#: Row threshold above which windowed estimators route prediction through
#: the ring (time-sharded) path instead of host-materializing windows.
#: Overridable via the env var; <= 0 disables the ring path entirely.
RING_PREDICT_ROWS_ENV = "GORDO_TPU_RING_PREDICT_ROWS"
DEFAULT_RING_PREDICT_ROWS = 65_536


def ring_predict_enabled(n_rows: int) -> bool:
    """
    Whether a windowed predict over ``n_rows`` should take the ring path:
    the series is long enough that the host-side ``lookback×`` window
    materialization hurts (threshold rows), and there is more than one
    device to shard the time axis over.
    """
    from ..utils.env import env_int

    threshold = env_int(RING_PREDICT_ROWS_ENV, DEFAULT_RING_PREDICT_ROWS)
    if threshold <= 0:
        return False
    return n_rows >= threshold and len(jax.devices()) > 1


def _right_halo(local: jnp.ndarray, halo: int, axis_name: str, axis_size: int):
    """
    The first ``halo`` rows of the right-hand neighbor's chunk (device i
    receives from device i+1; the last device receives device 0's head,
    which only ever feeds trimmed tail windows).
    """
    head = local[:halo]
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    return jax.lax.ppermute(head, axis_name, perm)


def ring_windowed_predict(
    predict_fn: Callable,
    params,
    X: np.ndarray,
    lookback: int,
    lookahead: int = 0,
    mesh: Optional[Mesh] = None,
    axis_name: str = DATA_AXIS,
) -> np.ndarray:
    """
    Score a long series with a windowed model, sharded over the time axis.

    Equivalent to ``predict_fn(params, sliding_windows(X, lookback,
    lookahead))`` but with ``X`` split across the ``axis_name`` devices of
    ``mesh`` and halos exchanged via ``ppermute``.

    Parameters
    ----------
    predict_fn
        ``(params, windows[k, lookback, F]) -> out[k, F_out]`` — a jittable
        forward (e.g. ``models.training.predict_fn(spec)`` for LSTM specs).
    X
        The full series ``[n, F]`` (host array).
    lookback, lookahead
        Window geometry; output has ``n - (lookback + lookahead - 1)`` rows.
    mesh
        Mesh whose ``axis_name`` axis shards time. Every other mesh axis
        must have size 1 for this entry point (fleet scoring composes the
        model axis separately).
    """
    if mesh is None:
        dev = jax.devices()
        mesh = Mesh(np.array(dev).reshape(len(dev)), (axis_name,))
    axis_size = mesh.shape[axis_name]
    offset = model_offset(lookback, lookahead)
    halo = offset

    X = np.asarray(X, np.float32)
    n = X.shape[0]
    n_windows = n - offset
    if n_windows <= 0:
        raise ValueError(
            f"Series of length {n} too short for lookback={lookback}, "
            f"lookahead={lookahead}"
        )
    # Pad the time axis to a multiple of the mesh axis; every chunk must
    # also be at least one halo long so the neighbor exchange suffices.
    chunk = -(-n // axis_size)
    if chunk < halo:
        chunk = halo
    total = chunk * axis_size
    if total != n:
        Xp = np.zeros((total,) + X.shape[1:], X.dtype)
        Xp[:n] = X
    else:
        Xp = X

    other_axes = [a for a in mesh.axis_names if a != axis_name]
    for a in other_axes:
        if mesh.shape[a] != 1:
            raise ValueError(
                f"ring_windowed_predict shards only {axis_name!r}; mesh axis "
                f"{a!r} has size {mesh.shape[a]} != 1"
            )

    fn = _ring_program(predict_fn, lookback, lookahead, mesh, axis_name)
    with mesh:
        out = fn(
            params, jax.device_put(Xp, NamedSharding(mesh, PartitionSpec(axis_name)))
        )
    return np.asarray(out)[:n_windows]


@lru_cache(maxsize=None)
def _ring_program(
    predict_fn: Callable, lookback: int, lookahead: int, mesh: Mesh, axis_name: str
):
    """The jitted halo-exchange scoring program for a (geometry, mesh) key —
    cached so repeated scoring (a serving loop) traces/compiles once, like
    the sibling ``training.predict_fn`` / ``fleet._fleet_fit_program``."""
    axis_size = mesh.shape[axis_name]
    halo = model_offset(lookback, lookahead)
    in_spec = PartitionSpec(axis_name)
    rep = PartitionSpec()

    def local_score(params, xs):
        # xs: [chunk, F] — this device's contiguous slice of the series.
        halo_rows = _right_halo(xs, halo, axis_name, axis_size)
        ext = jnp.concatenate([xs, halo_rows], axis=0)  # [chunk + halo, F]
        if halo:
            windows = sliding_windows(ext, lookback, lookahead)  # [chunk, L, F]
        else:
            # lookback=1, lookahead=0: windows are the rows themselves.
            windows = ext[:, None, :]
        return predict_fn(params, windows)

    return jax.jit(
        shard_map(
            local_score,
            mesh=mesh,
            in_specs=(rep, in_spec),
            out_specs=in_spec,
            # The LSTM scan carry starts replicated (zeros) and becomes
            # device-varying after consuming the sharded sequence; vma/rep
            # checking rejects that mixed carry, so it is disabled here.
            **{_CHECK_KW: False},
        )
    )


def ring_windowed_anomaly_scores(
    predict_fn: Callable,
    params,
    X: np.ndarray,
    y: Optional[np.ndarray],
    lookback: int,
    lookahead: int = 0,
    mesh: Optional[Mesh] = None,
    axis_name: str = DATA_AXIS,
) -> np.ndarray:
    """
    Per-row squared reconstruction/forecast error over a time-sharded
    series: ``((predict(windows) - y_aligned) ** 2)`` with the same halo
    exchange as :func:`ring_windowed_predict`. ``y`` defaults to ``X``.
    Returns ``[n - offset, F_out]`` squared errors (host array).
    """
    y = np.asarray(X if y is None else y, np.float32)
    out = ring_windowed_predict(
        predict_fn, params, X, lookback, lookahead, mesh, axis_name
    )
    offset = model_offset(lookback, lookahead)
    aligned = y[offset:]
    return (out - aligned[: len(out)]) ** 2
