"""
Crash-safe fleet build journal: ``<output_dir>/build_state.json``.

The reference's resumability is Argo's: each machine is a pod, and a
re-submitted workflow skips Succeeded nodes. The chip-fan-out build is
one process, so resumability has to be data: the journal records every
machine's build status (``planned → data_loaded → cv_done → built``,
or ``failed``) plus the machine's config hash, each update written with
an atomic tempfile-then-``os.replace`` so a crash at ANY instant leaves
a parseable journal. ``fleet_build --resume`` replays it: machines
whose journal entry says ``built``, whose config hash still matches,
and whose on-disk artifact is complete are skipped; everything else —
including machines that crashed mid-status — is rebuilt.

The journal lives beside the artifacts on purpose: whatever volume
survives the crash carries both, and the server's fleet store ignores
the file (it only loads artifact *directories*).
"""

import contextlib
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional

from .. import serializer
from ..serializer.serializer import (
    BUILD_JOURNAL_EVENTS_FILE,
    BUILD_JOURNAL_FILE,
    is_staging_dir,
)

logger = logging.getLogger(__name__)

#: canonical names live in serializer (the artifact-layout module) so
#: every discovery path shares them; re-exported here for journal users
JOURNAL_FILE = BUILD_JOURNAL_FILE
EVENTS_FILE = BUILD_JOURNAL_EVENTS_FILE

#: machine statuses in build order (``failed`` is terminal at any phase)
STATUSES = ("planned", "data_loaded", "cv_done", "built", "failed")


class BuildJournal:
    """Per-machine build state with incremental atomic persistence.

    Thread-safe: the dump pool records ``built`` entries concurrently.

    Durability comes in two tiers so a 5000-machine dump phase is not
    O(N²) in journal bytes: phase-boundary batches rewrite the base file
    atomically (:meth:`flush`, which also compacts), while per-machine
    events from the dump pool append ONE JSON line to an event overlay
    (``.build_state.json.events``) — O(1) per machine, still durable the
    instant the line lands. :meth:`load` applies the overlay on top of
    the base and tolerates a torn final line (a kill mid-append).
    """

    def __init__(self, output_dir: str):
        self.output_dir = output_dir
        self.path = os.path.join(output_dir, JOURNAL_FILE)
        self.events_path = os.path.join(output_dir, EVENTS_FILE)
        self._lock = threading.Lock()
        self._machines: Dict[str, Dict[str, Any]] = {}
        # Build-level FleetPlan identity (gordo_tpu.planner): which plan
        # hash / strategy produced this build's buckets. A resume reads
        # it to tell a replay (same plan) from a replan (hash changed —
        # only non-resumed members get new bucket compositions).
        self._plan: Dict[str, Any] = {}

    @classmethod
    def load(cls, output_dir: str) -> "BuildJournal":
        """Read an existing journal (base + event overlay); missing or
        corrupt files yield an empty journal (resume then just rebuilds
        everything)."""
        journal = cls(output_dir)
        try:
            with open(journal.path) as f:
                state = json.load(f)
            machines = state.get("machines", {})
            if isinstance(machines, dict):
                journal._machines = {
                    name: dict(entry)
                    for name, entry in machines.items()
                    if isinstance(entry, dict)
                }
            plan = state.get("plan")
            if isinstance(plan, dict):
                journal._plan = dict(plan)
        except FileNotFoundError:
            pass
        except (OSError, ValueError) as exc:
            logger.warning(
                "Unreadable build journal %s (%r); starting fresh",
                journal.path,
                exc,
            )
        try:
            with open(journal.events_path) as f:
                for line in f:
                    try:
                        event = json.loads(line)
                        name = event.pop("name")
                    except (ValueError, KeyError):
                        # torn tail from a kill mid-append; later lines
                        # of a healthy file are never affected
                        continue
                    journal._machines.setdefault(name, {}).update(event)
        except FileNotFoundError:
            pass
        except OSError as exc:
            logger.warning(
                "Unreadable journal events %s (%r); ignored",
                journal.events_path,
                exc,
            )
        return journal

    def get(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._machines.get(name)
            return dict(entry) if entry else None

    def machines(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {name: dict(e) for name, e in self._machines.items()}

    def record(
        self,
        name: str,
        status: str,
        config_hash: Optional[str] = None,
        error: Optional[str] = None,
        flush: bool = True,
    ) -> None:
        """Record one machine's status. ``flush=True`` makes it durable
        immediately via an O(1) event-line append; ``flush=False`` defers
        to the caller's next :meth:`flush` (phase-boundary batching)."""
        if status not in STATUSES:
            raise ValueError(f"unknown journal status {status!r}")
        with self._lock:
            entry = self._machines.setdefault(name, {})
            entry["status"] = status
            if config_hash is not None:
                entry["config_hash"] = config_hash
            if error is not None:
                entry["error"] = error
            elif status != "failed":
                entry.pop("error", None)
            if flush:
                os.makedirs(self.output_dir, exist_ok=True)
                with open(self.events_path, "a") as f:
                    f.write(json.dumps({"name": name, **entry}, default=str) + "\n")

    def plan(self) -> Dict[str, Any]:
        """The recorded FleetPlan identity (``{}`` when the build ran
        without a planner plan — e.g. the pure naive path pre-plan)."""
        with self._lock:
            return dict(self._plan)

    def set_plan(
        self, plan_hash: str, strategy: str, flush: bool = True
    ) -> None:
        """Record the build's FleetPlan identity (hash + strategy); a
        later ``--resume`` compares hashes to tell replay from replan."""
        with self._lock:
            self._plan = {"plan_hash": str(plan_hash), "strategy": str(strategy)}
        if flush:
            self.flush()

    def flush(self) -> None:
        """Atomically persist the full state and compact the event
        overlay into it: a crash mid-flush leaves the previous complete
        journal (plus its overlay), never a torn file."""
        with self._lock:
            state = {"version": 1, "machines": self._machines}
            if self._plan:
                state["plan"] = self._plan
            payload = json.dumps(state, indent=1, sort_keys=True, default=str)
            os.makedirs(self.output_dir, exist_ok=True)
            # Dotted staging-convention name (`.build_state.json.tmp-*`):
            # a flush interrupted mid-write leaves a file every discovery
            # path already classifies as a staging leftover, and the next
            # build's clean_staging_dirs sweep removes it.
            tmp = os.path.join(
                self.output_dir, f".{JOURNAL_FILE}.tmp-{os.getpid()}"
            )
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
            # the overlay's events are now in the base; remove AFTER the
            # replace so no window exists where neither holds them
            with contextlib.suppress(FileNotFoundError, OSError):
                os.remove(self.events_path)

    # -- resume helpers ------------------------------------------------------

    def resumable(self, name: str, config_hash: str) -> bool:
        """True when ``name`` can be skipped on resume: journaled
        ``built`` under the same config hash AND the artifact on disk is
        complete (checksum-verified) — the journal alone is never
        trusted over the artifact."""
        entry = self.get(name)
        return bool(
            entry
            and entry.get("status") == "built"
            and entry.get("config_hash") == config_hash
            and artifact_complete(os.path.join(self.output_dir, name))
        )


def resumable_names(output_dir: str, machines) -> List[str]:
    """Machine names a ``--resume`` will skip, computed purely from the
    (shared) output volume. Multi-host fleet builds run one SPMD program
    across processes, so EVERY process must derive the same surviving
    machine list — non-coordinators (which never write artifacts) call
    this read-only helper to mirror the coordinator's resume filter; a
    divergent list would desynchronize the collective device programs."""
    from ..builder.build_model import ModelBuilder

    journal = BuildJournal.load(output_dir)
    return [
        machine.name
        for machine in machines
        if journal.resumable(
            machine.name, ModelBuilder.calculate_cache_key(machine)
        )
    ]


def artifact_complete(model_dir: str) -> bool:
    """A complete, uncorrupted artifact dir: all three files present and
    ``info.json``'s recorded checksum matching ``model.pkl``'s bytes.
    (Atomic dumps make partial dirs impossible, but a resume must also
    survive artifacts written by older non-atomic builders or tampering
    between runs.)"""
    from ..serializer.serializer import _file_checksum

    model_path = os.path.join(model_dir, serializer.MODEL_FILE)
    if not all(
        os.path.isfile(os.path.join(model_dir, f))
        for f in (serializer.MODEL_FILE, serializer.METADATA_FILE, serializer.INFO_FILE)
    ):
        return False
    try:
        info = serializer.load_info(model_dir)
        return info.get("checksum") == _file_checksum(model_path)
    except (OSError, ValueError):
        return False


#: a staging entry younger than this is assumed to belong to a LIVE
#: builder (shared register/output volumes host several pods by design);
#: an in-flight dump takes seconds, so an hour marks a true orphan
STAGING_ORPHAN_AGE_SECONDS = 3600.0


def clean_staging_dirs(
    output_dir: str, min_age_seconds: float = STAGING_ORPHAN_AGE_SECONDS
) -> List[str]:
    """Remove orphaned atomic-write staging leftovers — ``.<name>.tmp-*``
    artifact dirs and ``.build_state.json.tmp-*`` journal flush files —
    that a killed process can leave behind; returns the removed names.
    Entries younger than ``min_age_seconds`` are spared: on a shared
    volume they may be another live builder's in-flight dump, and
    sweeping one out from under it would fail a healthy machine. Never
    touches completed artifacts or the journal itself."""
    import shutil
    import time

    removed = []
    try:
        entries = os.listdir(output_dir)
    except FileNotFoundError:
        return removed
    now = time.time()
    for entry in entries:
        if not is_staging_dir(entry):
            continue
        full = os.path.join(output_dir, entry)
        try:
            age = now - os.stat(full).st_mtime
        except OSError:
            continue  # vanished: its owner just renamed/cleaned it
        if age < min_age_seconds:
            logger.info(
                "Sparing staging entry %s (%.0fs old — possibly a live "
                "builder's in-flight dump)",
                full,
                age,
            )
            continue
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        else:
            with contextlib.suppress(OSError):
                os.remove(full)
        removed.append(entry)
    if removed:
        logger.info(
            "Removed %d orphaned staging entr(ies) from %s",
            len(removed),
            output_dir,
        )
    return removed
