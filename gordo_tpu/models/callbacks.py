"""
Training callbacks.

Reference configs attach Keras callbacks (built via
gordo/serializer/from_definition.py:352-373); gordo-tpu supports the one that
matters for these models — EarlyStopping — and compiles it *into* the fused
training program as a static config (no per-epoch host round trip) whenever
possible. Unknown/custom callbacks fall back to the per-epoch host loop in
models/training.py.
"""

from typing import Optional


class Callback:
    """Base class; host-loop callbacks receive per-epoch logs."""

    def on_train_begin(self, logs: Optional[dict] = None):
        ...

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        """Return True to request early stop."""
        return False

    def get_params(self, deep: bool = False) -> dict:
        return {}


class EarlyStopping(Callback):
    """
    Stop training when ``monitor`` stops improving by ``min_delta`` for
    ``patience`` epochs; optionally restore the best params seen.

    Keras-compatible surface (the subset gordo configs use):
    monitor/min_delta/patience/restore_best_weights.
    """

    def __init__(
        self,
        monitor: str = "val_loss",
        min_delta: float = 0.0,
        patience: int = 0,
        verbose: int = 0,
        mode: str = "auto",
        restore_best_weights: bool = False,
        **kwargs,
    ):
        self.monitor = monitor
        self.min_delta = float(min_delta)
        self.patience = int(patience)
        self.verbose = verbose
        self.mode = mode
        self.restore_best_weights = restore_best_weights
        self._best = None
        self._wait = 0

    def get_params(self, deep: bool = False) -> dict:
        return {
            "monitor": self.monitor,
            "min_delta": self.min_delta,
            "patience": self.patience,
            "restore_best_weights": self.restore_best_weights,
        }

    def on_train_begin(self, logs: Optional[dict] = None):
        self._best, self._wait = None, 0

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        value = (logs or {}).get(self.monitor)
        if value is None:
            return False
        if self._best is None or value < self._best - self.min_delta:
            self._best, self._wait = value, 0
            return False
        self._wait += 1
        # Keras stops when wait >= patience (patience=0 behaves like 1)
        return self._wait >= max(self.patience, 1)
