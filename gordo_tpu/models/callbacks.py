"""
Training callbacks.

Reference configs attach Keras callbacks (built via
gordo/serializer/from_definition.py:352-373); gordo-tpu compiles the one
that matters for these models — EarlyStopping — *into* the fused training
program as a static config (no per-epoch host round trip) whenever
possible. Everything else — the built-ins below and any custom
dotted-path callback from YAML (serializer build_callbacks) — rides the
per-epoch host loop in models/training.py, which re-dispatches one
compiled epoch at a time and honors stop requests and learning-rate
changes between epochs.
"""

import math
from typing import Optional


class Callback:
    """Base class; host-loop callbacks receive per-epoch logs."""

    def on_train_begin(self, logs: Optional[dict] = None):
        ...

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        """Return True to request early stop."""
        return False

    def get_params(self, deep: bool = False) -> dict:
        return {}


class EarlyStopping(Callback):
    """
    Stop training when ``monitor`` stops improving by ``min_delta`` for
    ``patience`` epochs; optionally restore the best params seen.

    Keras-compatible surface (the subset gordo configs use):
    monitor/min_delta/patience/restore_best_weights.
    """

    def __init__(
        self,
        monitor: str = "val_loss",
        min_delta: float = 0.0,
        patience: int = 0,
        verbose: int = 0,
        mode: str = "auto",
        restore_best_weights: bool = False,
        **kwargs,
    ):
        self.monitor = monitor
        self.min_delta = float(min_delta)
        self.patience = int(patience)
        self.verbose = verbose
        self.mode = mode
        self.restore_best_weights = restore_best_weights
        self._best = None
        self._wait = 0

    def get_params(self, deep: bool = False) -> dict:
        return {
            "monitor": self.monitor,
            "min_delta": self.min_delta,
            "patience": self.patience,
            "restore_best_weights": self.restore_best_weights,
        }

    def on_train_begin(self, logs: Optional[dict] = None):
        self._best, self._wait = None, 0

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        value = (logs or {}).get(self.monitor)
        if value is None:
            return False
        if self._best is None or value < self._best - self.min_delta:
            self._best, self._wait = value, 0
            return False
        self._wait += 1
        # Keras stops when wait >= patience (patience=0 behaves like 1)
        return self._wait >= max(self.patience, 1)


class TerminateOnNaN(Callback):
    """Stop training the moment the epoch loss goes non-finite (Keras
    ``TerminateOnNaN``; the fleet path's analog is the diverged-member
    reseed retry in parallel/fleet.py)."""

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        loss = (logs or {}).get("loss")
        return loss is not None and not math.isfinite(loss)


class ReduceLROnPlateau(Callback):
    """
    Multiply the learning rate by ``factor`` when ``monitor`` stops
    improving for ``patience`` epochs (Keras-compatible surface:
    monitor/factor/patience/min_delta/cooldown/min_lr).

    The host loop applies the request between epochs by recompiling the
    one-epoch program with the new rate (models/training.py
    ``_fit_host_loop``; Adam's moment state carries over unchanged — the
    learning rate only scales the update).
    """

    def __init__(
        self,
        monitor: str = "val_loss",
        factor: float = 0.1,
        patience: int = 10,
        min_delta: float = 1e-4,
        cooldown: int = 0,
        min_lr: float = 0.0,
        verbose: int = 0,
        mode: str = "auto",
        **kwargs,
    ):
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau factor must be < 1.0")
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.min_delta = float(min_delta)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        self.verbose = verbose
        self.mode = mode
        self._best: Optional[float] = None
        self._wait = 0
        self._cooldown_left = 0
        self._requested_lr: Optional[float] = None

    def get_params(self, deep: bool = False) -> dict:
        return {
            "monitor": self.monitor,
            "factor": self.factor,
            "patience": self.patience,
            "min_delta": self.min_delta,
            "cooldown": self.cooldown,
            "min_lr": self.min_lr,
        }

    def on_train_begin(self, logs: Optional[dict] = None):
        self._best, self._wait, self._cooldown_left = None, 0, 0
        self._requested_lr = None

    def consume_lr_request(self) -> Optional[float]:
        """The new learning rate this callback wants (one-shot), or None.
        Called by the host loop after each epoch's callbacks ran."""
        requested, self._requested_lr = self._requested_lr, None
        return requested

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None) -> bool:
        logs = logs or {}
        # monitor falls back to train loss when val_loss is absent, like
        # the compiled EarlyStopping's per-member fallback
        value = logs.get(self.monitor, logs.get("loss"))
        current_lr = logs.get("lr")
        if value is None or not math.isfinite(value):
            return False
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
        if self._best is None or value < self._best - self.min_delta:
            self._best, self._wait = value, 0
        elif self._cooldown_left <= 0:
            self._wait += 1
            if self._wait >= max(self.patience, 1) and current_lr is not None:
                new_lr = max(current_lr * self.factor, self.min_lr)
                if new_lr < current_lr:
                    self._requested_lr = new_lr
                self._wait = 0
                self._cooldown_left = self.cooldown
        return False
