"""
Packed fleet training: G tiny models as ONE block-diagonal supermodel.

The fleet's models are hourglass MLPs a few tens of units wide, but the
TPU MXU multiplies 128×128 tiles — a vmapped ``[B, 17] @ [17, 13]`` fleet
spends one systolic pass per model with ~1% of each tile doing work.
Packing G models into block-diagonal weights turns G passes into one:
``[B, G·17] @ (G·17, G·13 block-diag)`` fills the tile laterally.

What that buys in practice: the MXU-pass count drops ~G×, but the fleet
regime is NOT matmul-bound — per training step the chip moves the f32
params + Adam moments + gradients and the batch through HBM, and that
elementwise/optimizer traffic is identical packed or unpacked (compact
``[G, d_in, d_out]`` parameters, by design). Measured on a v5e, packing
is worth ~1.1× end to end, consistent with the roofline arithmetic in
docs/architecture.md — it is the matmul share of the step, not the whole
step, that scales with G. The block-diagonal trick would approach its
ideal ~G× only for compute-bound workloads (wider layers, bigger
batches), which these fleet models deliberately are not.

Parameters stay COMPACT: each layer's weights live as ``[G, d_in, d_out]``
stacks (exactly a vmapped ``init_feedforward``), and the block-diagonal
``[G·d_in, G·d_out]`` matrix is materialized *inside* the step, only for
the matmul. This keeps the matmul win without a G× optimizer tax — Adam's
moments, the gradients it consumes, and every elementwise update touch
``G·d_in·d_out`` elements, not the ``G²·d_in·d_out`` of a dense packed
weight. (An earlier dense-parameter formulation lost on real TPUs for
exactly that reason: these models are so small that training is
elementwise/HBM-bound, not matmul-bound.)

Per-model math is EXACTLY preserved:

- off-diagonal blocks are structural zeros (built by construction, not
  masked), so cross-model terms are exact float zeros and each model's
  output matches its unpacked forward to within dot-product summation
  order;
- autodiff through the block-diagonal construction returns gradients in
  the compact ``[G, d_in, d_out]`` layout — each member's block, nothing
  else — so per-member gradients equal separate-training gradients;
- the training loss is the SUM of per-model weighted means (not a mean
  over the concatenated feature axis), so each model's parameter gradients
  equal its separate-training gradients;
- per-model "empty batch" guards become per-member update masks over the
  leading G axis, keeping the no-op contract of the unpacked engine
  (models/training.py).

The one intentional departure: members of a pack share the per-epoch
shuffle permutation (one ``jax.random.permutation`` per pack instead of
per member). With ``shuffle=False`` packed training reproduces unpacked
training to float summation order; with shuffling it is statistically
equivalent.

Early stopping is not supported in packed mode — callers fall back to the
unpacked program when ``config.early_stopping`` is set.

One more ragged-bucket caveat: Adam's step count is shared across a
pack. A batch that is padding for only SOME members masks their updates
and moments, but the shared count still advances, so their later
bias-correction factors differ slightly from separate training (order
1e-3 over a few epochs). Members of equal length are unaffected.
"""

from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.activations import resolve_activation
from ..ops.losses import resolve_loss
from .nn import init_feedforward
from .spec import FeedForwardSpec, ModelSpec

Params = Dict[str, Dict[str, jnp.ndarray]]

#: MXU lane width — packing beyond this stops helping and starts hurting.
MXU_LANES = 128


@dataclass(frozen=True)
class PackedFeedForwardSpec(ModelSpec):
    """G copies of ``base`` fused into block-diagonal layers."""

    base: FeedForwardSpec
    g: int

    @property
    def layer_dims(self) -> Tuple[Tuple[int, int], ...]:
        """Per-layer (d_in, d_out) of the BASE model, output layer last."""
        dims = []
        d_in = self.base.n_features
        for units in self.base.dims:
            dims.append((d_in, units))
            d_in = units
        dims.append((d_in, self.base.n_features_out))
        return tuple(dims)

    @property
    def layer_keys(self) -> Tuple[str, ...]:
        return tuple(f"dense_{i}" for i in range(len(self.base.dims))) + ("out",)


def auto_packing(spec: FeedForwardSpec, n_members: int) -> int:
    """
    A packing factor that fills (but does not overflow) the MXU lane
    width: ``G = 128 // widest layer``, capped by the member count.
    """
    widest = max((spec.n_features, spec.n_features_out) + tuple(spec.dims))
    g = max(1, MXU_LANES // max(widest, 1))
    return max(1, min(g, n_members, 16))


def _block_diag(W: jnp.ndarray) -> jnp.ndarray:
    """
    ``W[G, d_in, d_out] -> [G·d_in, G·d_out]`` with member ``gi``'s matrix
    on diagonal block ``gi`` and structural zeros elsewhere. Differentiable:
    the backward pass is the block-extraction, so gradients arrive compact.
    """
    g, d_in, d_out = W.shape
    eye = jnp.eye(g, dtype=W.dtype)
    # [G(row-block), d_in, G(col-block), d_out] -> flatten pairwise
    blocks = W[:, :, None, :] * eye[:, None, :, None]
    return blocks.reshape(g * d_in, g * d_out)


def init_packed(member_keys: jnp.ndarray, spec: PackedFeedForwardSpec) -> Params:
    """
    Compact packed params from G per-member PRNG keys: each member
    initializes through the exact ``init_feedforward`` chain (same glorot
    draws as unpacked training); leaves carry a leading member axis
    (``W[G, d_in, d_out]``, ``b[G, d_out]``).
    """
    return jax.vmap(lambda k: init_feedforward(k, spec.base))(member_keys)


def unpack_params(packed: Params, spec: PackedFeedForwardSpec, gi: int) -> Params:
    """Member ``gi``'s standalone param pytree (leading-axis slice)."""
    return jax.tree_util.tree_map(lambda leaf: leaf[gi], packed)


def forward_packed(
    spec: PackedFeedForwardSpec, params: Params, x: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """
    ``x[B, G*F] -> (out[B, G*F_out], penalties[G])`` — the packed
    equivalent of ``forward_feedforward`` with per-model activity
    penalties (L1 over each member's block).
    """
    base = spec.base
    dtype = jnp.dtype(base.compute_dtype)

    def cast(leaf):
        return leaf.astype(dtype) if leaf.dtype != dtype else leaf

    penalties = jnp.zeros((spec.g,), jnp.float32)
    h = cast(x)
    for i in range(len(base.dims)):
        layer = params[f"dense_{i}"]
        pre = h @ _block_diag(cast(layer["W"])) + cast(layer["b"]).reshape(-1)
        h = resolve_activation(base.activations[i])(pre)
        if base.l1_activity and base.l1_activity[i]:
            per_member = jnp.sum(
                jnp.abs(h).reshape(h.shape[0], spec.g, base.dims[i]),
                axis=(0, 2),
                dtype=jnp.float32,
            )
            penalties = penalties + base.l1_activity[i] * per_member
    out = h @ _block_diag(cast(params["out"]["W"])) + cast(
        params["out"]["b"]
    ).reshape(-1)
    # float32 out regardless of compute dtype (models/nn.py dtype contract)
    return resolve_activation(base.out_activation)(out).astype(jnp.float32), penalties


def _per_model_losses(
    spec: PackedFeedForwardSpec, out: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """
    ``(weighted per-model means [G], per-model weight totals [G])`` from
    packed outputs. ``w[B, G]`` carries each member's sample weights.
    """
    base = spec.base
    # resolve_loss gives the per-sample loss (mean over the trailing
    # feature axis); reshaping to [B, G, F_out] yields the [B, G]
    # per-member matrix with the same registry as the unpacked engine.
    per_sample_fn = resolve_loss(base.loss)
    shape = (out.shape[0], spec.g, base.n_features_out)
    per_sample = per_sample_fn(out.reshape(shape), y.reshape(shape))
    totals = jnp.sum(w, axis=0)
    means = jnp.sum(per_sample * w, axis=0) / jnp.maximum(totals, 1.0)
    return means, totals


def _per_member_select(g: int, new, old, keep: jnp.ndarray):
    """
    ``where(keep[member], new, old)`` over every leaf whose leading axis is
    the member axis (compact params and optimizer moments all carry it);
    scalar leaves (Adam's shared step count) advance unconditionally.
    """

    def select(new_leaf, old_leaf):
        shape = tuple(np.shape(new_leaf))
        if len(shape) >= 2 and shape[0] == g:
            cond = keep.reshape((g,) + (1,) * (len(shape) - 1))
            return jnp.where(cond, new_leaf, old_leaf)
        return new_leaf

    return jax.tree_util.tree_map(select, new, old)


@lru_cache(maxsize=None)
def build_packed_fit_fn(spec: PackedFeedForwardSpec, config):
    """
    The unjitted packed fused fit:

    ``(params, opt_state, Xtr[n, G·F], ytr[n, G·Fo], wtr[n, G],
    Xval, yval, wval[nv, G], rng) ->
    (params, opt_state, losses[epochs, G], val_losses[epochs, G])``

    Mirrors ``models.training.build_raw_fit_fn`` with per-model loss
    vectors and per-model empty-batch update masks. No early stopping.
    """
    if config.early_stopping is not None:
        raise ValueError("Packed training does not support early stopping")
    tx = spec.base.optimizer.to_optax()

    def batch_loss(params, xb, yb, wb):
        out, penalties = forward_packed(spec, params, xb)
        means, totals = _per_model_losses(spec, out, yb, wb)
        has_data = totals > 0
        # Penalties for empty members are pure padding artifacts and would
        # leak gradients into their biases.
        losses_g = means + jnp.where(has_data, penalties, 0.0)
        return jnp.sum(losses_g), (losses_g, totals)

    grad_fn = jax.value_and_grad(batch_loss, has_aux=True)

    def train_epoch(params, opt_state, Xtr, ytr, wtr, erng):
        n_total = Xtr.shape[0]
        steps = n_total // config.batch_size
        if config.shuffle:
            perm = jax.random.permutation(erng, n_total)
            Xtr = jnp.take(Xtr, perm, axis=0)
            ytr = jnp.take(ytr, perm, axis=0)
            wtr = jnp.take(wtr, perm, axis=0)
        batches = (
            Xtr.reshape((steps, config.batch_size) + Xtr.shape[1:]),
            ytr.reshape((steps, config.batch_size) + ytr.shape[1:]),
            wtr.reshape((steps, config.batch_size) + wtr.shape[1:]),
        )

        def step(carry, batch):
            params, opt_state = carry
            xb, yb, wb = batch
            (_, (losses_g, totals)), grads = grad_fn(params, xb, yb, wb)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            has_data = totals > 0
            # A batch that is padding for EVERY member is a true no-op —
            # Adam's shared step count must not advance (matches the
            # unpacked engine's has_data skip exactly). A batch that is
            # padding for only SOME members masks their updates/moments,
            # but the shared count still advances for them — the one
            # bias-correction divergence of packed ragged buckets.
            any_data = jnp.any(has_data)
            new_params = optax.apply_updates(params, updates)
            new_params = _per_member_select(spec.g, new_params, params, has_data)
            new_opt_state = _per_member_select(
                spec.g, new_opt_state, opt_state, has_data
            )
            params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(any_data, n, o), new_params, params
            )
            opt_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(any_data, n, o), new_opt_state, opt_state
            )
            contribution = jnp.where(has_data, losses_g * totals, 0.0)
            return (params, opt_state), (contribution, totals)

        (params, opt_state), (weighted, batch_totals) = jax.lax.scan(
            step, (params, opt_state), batches
        )
        member_totals = jnp.sum(batch_totals, axis=0)
        epoch_losses = jnp.sum(weighted, axis=0) / jnp.maximum(member_totals, 1.0)
        epoch_losses = jnp.where(member_totals > 0, epoch_losses, jnp.nan)
        return params, opt_state, epoch_losses

    def evaluate(params, X, y, w):
        out, _ = forward_packed(spec, params, X)
        means, totals = _per_model_losses(spec, out, y, w)
        return jnp.where(totals > 0, means, jnp.nan)

    compute_dtype = jnp.dtype(spec.base.compute_dtype)

    def fit(params, opt_state, Xtr, ytr, wtr, Xval, yval, wval, rng):
        if compute_dtype != jnp.float32:
            Xtr, ytr = Xtr.astype(compute_dtype), ytr.astype(compute_dtype)
            Xval, yval = Xval.astype(compute_dtype), yval.astype(compute_dtype)
        has_val = Xval.shape[0] > 0

        def epoch_body(carry, erng):
            params, opt_state = carry
            params, opt_state, losses_g = train_epoch(
                params, opt_state, Xtr, ytr, wtr, erng
            )
            val_g = (
                evaluate(params, Xval, yval, wval)
                if has_val
                else jnp.full((spec.g,), jnp.nan, jnp.float32)
            )
            return (params, opt_state), (losses_g, val_g)

        rngs = jax.random.split(rng, config.epochs)
        (params, opt_state), (losses, val_losses) = jax.lax.scan(
            epoch_body, (params, opt_state), rngs
        )
        return params, opt_state, losses, val_losses

    return fit
