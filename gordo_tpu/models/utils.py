"""
Response-frame assembly and metric wrapping.

Reference parity: gordo/machine/model/utils.py — ``make_base_dataframe``
builds the MultiIndex-column response DataFrame (``model-input`` /
``model-output`` / ``start`` / ``end``) with model-offset alignment, and
``metric_wrapper`` clips y_true to the (possibly shorter) prediction length
before scoring.
"""

import functools
from datetime import timedelta
from typing import List, Optional, Union

import numpy as np
import pandas as pd

from ..dataset.sensor_tag import SensorTag


def metric_wrapper(metric, scaler=None):
    """
    Adapt a metric to (a) optionally scale y/y_pred first and (b) tolerate a
    model whose output is shorter than its input (LSTM offset).
    """

    @functools.wraps(metric)
    def _wrapped(y_true, y_pred, *args, **kwargs):
        if scaler is not None:
            y_true = scaler.transform(y_true)
            y_pred = scaler.transform(y_pred)
        y_true = np.asarray(y_true)
        y_pred = np.asarray(y_pred)
        return metric(y_true[-len(y_pred):], y_pred, *args, **kwargs)

    return _wrapped


def _tag_names(tags) -> List[str]:
    return [tag.name if isinstance(tag, SensorTag) else str(tag) for tag in tags]


def make_base_dataframe(
    tags: Union[List[SensorTag], List[str]],
    model_input: np.ndarray,
    model_output: np.ndarray,
    target_tag_list: Optional[Union[List[SensorTag], List[str]]] = None,
    index: Optional[Union[np.ndarray, pd.Index]] = None,
    frequency: Optional[timedelta] = None,
) -> pd.DataFrame:
    """
    MultiIndex-column DataFrame with top-level keys ``start``, ``end``,
    ``model-input``, ``model-output``; everything aligned to the (possibly
    shorter) model output and timestamps ISO-formatted for JSON.
    """
    target_tag_list = target_tag_list if target_tag_list is not None else tags
    model_output = getattr(model_output, "values", model_output)
    n_out = len(model_output)
    model_input = getattr(model_input, "values", model_input)[-n_out:, :]

    if index is not None:
        normalized_index = pd.Index(index[-n_out:])
    else:
        normalized_index = pd.RangeIndex(n_out)

    if isinstance(normalized_index, pd.DatetimeIndex):
        starts = [ts.isoformat() for ts in normalized_index]
        if frequency is not None:
            ends = [(ts + frequency).isoformat() for ts in normalized_index]
        else:
            ends = [None] * n_out
    else:
        starts = [None] * n_out
        ends = [None] * n_out

    data = pd.DataFrame(
        {("start", ""): starts, ("end", ""): ends},
        columns=pd.MultiIndex.from_product((("start", "end"), ("",))),
        index=normalized_index,
    )

    for name, values, name_tags in (
        ("model-input", model_input, tags),
        ("model-output", model_output, target_tag_list),
    ):
        if values is None:
            continue
        if values.shape[1] == len(name_tags):
            sub_names = _tag_names(name_tags)
        else:
            sub_names = [str(i) for i in range(values.shape[1])]
        columns = pd.MultiIndex.from_tuples((name, sub) for sub in sub_names)
        data = data.join(
            pd.DataFrame(values[-n_out:], columns=columns, index=normalized_index)
        )
    return data
