"""
Diff-based anomaly detectors — the production model family.

Math parity with the reference (gordo/machine/model/anomaly/diff.py):

``DiffBasedAnomalyDetector``
    Wraps any estimator + scaler. ``cross_validate`` runs
    TimeSeriesSplit(3); per fold it computes per-tag MAE and the per-
    timestep MSE of *scaled* residuals; thresholds are
    ``metric.rolling(6).min().max()`` of the **last** fold (plus optional
    ``window``-smoothed variants). ``anomaly`` emits tag-level scaled /
    unscaled errors, total (mean-square) errors, optional smoothed columns,
    and confidence = error / threshold.

``DiffBasedKFCVAnomalyDetector``
    Shuffled KFold(5); thresholds are the ``threshold_percentile`` quantile
    of window-smoothed validation errors stitched over all folds.

Engine note: the base estimator's predict is the jitted JAX forward; the
pandas threshold/rolling arithmetic is host-side by design (tiny data,
rich semantics).
"""

import logging
from datetime import timedelta
from typing import Optional, Union

import numpy as np
import pandas as pd
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.exceptions import NotFittedError
from sklearn.metrics import explained_variance_score
from sklearn.model_selection import KFold, TimeSeriesSplit
from sklearn.model_selection import cross_validate as sklearn_cross_validate
from sklearn.preprocessing import MinMaxScaler
from sklearn.utils import shuffle as sklearn_shuffle

from .. import utils as model_utils
from ..base import GordoBase
from .base import AnomalyDetectorBase

logger = logging.getLogger(__name__)


def _default_base_estimator():
    from ..estimators import JaxAutoEncoder

    return JaxAutoEncoder(kind="feedforward_hourglass")


class DiffBasedAnomalyDetector(AnomalyDetectorBase):
    def __init__(
        self,
        base_estimator: Optional[BaseEstimator] = None,
        scaler: Optional[TransformerMixin] = None,
        require_thresholds: bool = True,
        shuffle: bool = False,
        window: Optional[int] = None,
        smoothing_method: Optional[str] = None,
    ):
        """
        Diff-error anomaly detection around ``base_estimator``; the scaler is
        fit on ``y`` *after* training purely for error scaling.
        """
        self.base_estimator = (
            base_estimator if base_estimator is not None else _default_base_estimator()
        )
        self.scaler = scaler if scaler is not None else MinMaxScaler()
        self.require_thresholds = require_thresholds
        self.shuffle = shuffle
        self.window = window
        self.smoothing_method = smoothing_method
        if self.window is not None and self.smoothing_method is None:
            self.smoothing_method = "smm"

    def __getattr__(self, item):
        # Transparent delegation into the base estimator (reference
        # diff.py:78-86); __getattr__ only fires on missing attributes.
        # Dunders, privates, and the serializer hooks must NOT delegate:
        # leaking the base estimator's into_definition would serialize the
        # detector as if it were its base estimator.
        if item.startswith("_") or item in ("into_definition", "from_definition"):
            raise AttributeError(item)
        try:
            return getattr(self.__dict__["base_estimator"], item)
        except KeyError:
            raise AttributeError(item)

    def get_params(self, deep: bool = True) -> dict:
        params = {
            "base_estimator": self.base_estimator,
            "scaler": self.scaler,
            "shuffle": self.shuffle,
        }
        if self.window is not None:
            params["window"] = self.window
            params["smoothing_method"] = self.smoothing_method
        return params

    def get_metadata(self) -> dict:
        metadata = {}
        if hasattr(self, "feature_thresholds_"):
            metadata["feature-thresholds"] = self.feature_thresholds_.tolist()
        if hasattr(self, "aggregate_threshold_"):
            metadata["aggregate-threshold"] = self.aggregate_threshold_
        if hasattr(self, "feature_thresholds_per_fold_"):
            metadata["feature-thresholds-per-fold"] = (
                self.feature_thresholds_per_fold_.to_dict()
            )
        if hasattr(self, "aggregate_thresholds_per_fold_"):
            metadata["aggregate-thresholds-per-fold"] = (
                self.aggregate_thresholds_per_fold_
            )
        metadata["window"] = self.window
        metadata["smoothing-method"] = self.smoothing_method
        if getattr(self, "smooth_feature_thresholds_", None) is not None:
            metadata["smooth-feature-thresholds"] = (
                self.smooth_feature_thresholds_.tolist()
            )
        if getattr(self, "smooth_aggregate_threshold_", None) is not None:
            metadata["smooth-aggregate-threshold"] = self.smooth_aggregate_threshold_
        if hasattr(self, "smooth_feature_thresholds_per_fold_"):
            metadata["smooth-feature-thresholds-per-fold"] = (
                self.smooth_feature_thresholds_per_fold_.to_dict()
            )
        if hasattr(self, "smooth_aggregate_thresholds_per_fold_"):
            metadata["smooth-aggregate-thresholds-per-fold"] = (
                self.smooth_aggregate_thresholds_per_fold_
            )
        if isinstance(self.base_estimator, GordoBase):
            metadata.update(self.base_estimator.get_metadata())
        else:
            metadata.update(
                {
                    "scaler": str(self.scaler),
                    "base_estimator": str(self.base_estimator),
                    "shuffle": self.shuffle,
                }
            )
        return metadata

    def score(self, X, y, sample_weight=None) -> float:
        if hasattr(self.base_estimator, "score"):
            return self.base_estimator.score(X, y)
        out = self.base_estimator.predict(X)
        y = np.asarray(getattr(y, "values", y))
        return explained_variance_score(y[-len(out):], out)

    def fit(self, X, y) -> "DiffBasedAnomalyDetector":
        if self.shuffle:
            X_s, y_s = sklearn_shuffle(X, y, random_state=0)
            self.base_estimator.fit(X_s, y_s)
        else:
            self.base_estimator.fit(X, y)
        self.scaler.fit(y)  # used only for error scaling in .anomaly()
        return self

    def cross_validate(
        self,
        *,
        X: Union[pd.DataFrame, np.ndarray],
        y: Union[pd.DataFrame, np.ndarray],
        cv=None,
        **kwargs,
    ):
        """
        TimeSeriesSplit(3) CV; updates threshold attributes from the folds
        (final thresholds = last fold's).
        """
        if cv is None:
            cv = TimeSeriesSplit(n_splits=3)
        kwargs.update(dict(return_estimator=True, cv=cv))
        cv_output = sklearn_cross_validate(self, X=X, y=y, **kwargs)

        feature_folds = {}
        smooth_feature_folds = {}
        self.aggregate_thresholds_per_fold_ = {}
        self.smooth_aggregate_thresholds_per_fold_ = {}
        tag_thresholds_fold = None
        aggregate_threshold_fold = None
        smooth_tag_thresholds_fold = None
        smooth_aggregate_threshold_fold = None

        for i, ((_, test_idxs), fold_model) in enumerate(
            zip(kwargs["cv"].split(X, y), cv_output["estimator"])
        ):
            X_test = X.iloc[test_idxs] if isinstance(X, pd.DataFrame) else X[test_idxs]
            y_pred = fold_model.predict(X_test)
            # Align y for any model offset (LSTM outputs fewer rows)
            test_idxs = test_idxs[-len(y_pred):]
            y_true = y.iloc[test_idxs] if isinstance(y, pd.DataFrame) else y[test_idxs]

            scaled_mse = self._scaled_mse_per_timestep(fold_model, y_true, y_pred)
            mae = self._absolute_error(y_true, y_pred)

            aggregate_threshold_fold = float(scaled_mse.rolling(6).min().max())
            self.aggregate_thresholds_per_fold_[f"fold-{i}"] = aggregate_threshold_fold

            tag_thresholds_fold = mae.rolling(6).min().max()
            tag_thresholds_fold.name = f"fold-{i}"
            feature_folds[f"fold-{i}"] = tag_thresholds_fold

            if self.window is not None:
                smooth_aggregate_threshold_fold = float(
                    scaled_mse.rolling(self.window).min().max()
                )
                self.smooth_aggregate_thresholds_per_fold_[f"fold-{i}"] = (
                    smooth_aggregate_threshold_fold
                )
                smooth_tag_thresholds_fold = mae.rolling(self.window).min().max()
                smooth_tag_thresholds_fold.name = f"fold-{i}"
                smooth_feature_folds[f"fold-{i}"] = smooth_tag_thresholds_fold

        self.feature_thresholds_per_fold_ = (
            pd.DataFrame(feature_folds).T if feature_folds else pd.DataFrame()
        )
        self.smooth_feature_thresholds_per_fold_ = (
            pd.DataFrame(smooth_feature_folds).T
            if smooth_feature_folds
            else pd.DataFrame()
        )
        # Final thresholds come from the last fold
        self.feature_thresholds_ = tag_thresholds_fold
        self.aggregate_threshold_ = aggregate_threshold_fold
        self.smooth_feature_thresholds_ = smooth_tag_thresholds_fold
        self.smooth_aggregate_threshold_ = smooth_aggregate_threshold_fold
        return cv_output

    @staticmethod
    def _scaled_mse_per_timestep(model, y_true, y_pred) -> pd.Series:
        try:
            scaled_y_true = model.scaler.transform(y_true)
        except (NotFittedError, ValueError):
            scaled_y_true = model.scaler.fit_transform(y_true)
        scaled_y_pred = model.scaler.transform(y_pred)
        mse = np.mean(np.square(scaled_y_pred - scaled_y_true), axis=1)
        return pd.Series(np.asarray(mse))

    @staticmethod
    def _absolute_error(y_true, y_pred) -> pd.DataFrame:
        return pd.DataFrame(
            np.abs(np.asarray(getattr(y_true, "values", y_true)) - np.asarray(y_pred))
        )

    def _smoothing(self, metric):
        if self.smoothing_method == "smm":
            return metric.rolling(self.window).median()
        if self.smoothing_method == "sma":
            return metric.rolling(self.window).mean()
        if self.smoothing_method == "ewma":
            return metric.ewm(span=self.window).mean()
        raise ValueError(f"Unknown smoothing_method {self.smoothing_method!r}")

    def anomaly(
        self,
        X: pd.DataFrame,
        y: pd.DataFrame,
        frequency: Optional[timedelta] = None,
        model_output: Optional[np.ndarray] = None,
    ) -> pd.DataFrame:
        """
        Build the anomaly response DataFrame for ``X``/``y``.

        ``model_output`` short-circuits the base estimator's predict with
        an already-computed reconstruction — the fleet serving route
        scores whole spec buckets as one fused device program and then
        assembles each machine's full anomaly frame from its slice.
        """
        if not hasattr(X, "values"):
            raise ValueError("Unable to find X.values property")

        if model_output is None:
            model_output = (
                self.predict(X)
                if hasattr(self.base_estimator, "predict")
                else self.transform(X)
            )

        data = model_utils.make_base_dataframe(
            tags=X.columns,
            model_input=X.values,
            model_output=model_output,
            target_tag_list=y.columns,
            index=getattr(X, "index", None),
            frequency=frequency,
        )

        model_out_scaled = pd.DataFrame(
            self.scaler.transform(data["model-output"]),
            columns=data["model-output"].columns,
            index=data.index,
        )

        # Scaled per-tag anomaly; y offset-aligned to the model output
        scaled_y = self.scaler.transform(y)
        tag_anomaly_scaled = np.abs(model_out_scaled - scaled_y[-len(data):, :])
        tag_anomaly_scaled.columns = pd.MultiIndex.from_product(
            (("tag-anomaly-scaled",), tag_anomaly_scaled.columns)
        )
        data = data.join(tag_anomaly_scaled)
        data["total-anomaly-scaled"] = np.square(data["tag-anomaly-scaled"]).mean(axis=1)

        unscaled_abs_diff = pd.DataFrame(
            data=np.abs(
                data["model-output"].to_numpy() - np.asarray(y)[-len(data):, :]
            ),
            index=data.index,
            columns=pd.MultiIndex.from_product(
                (("tag-anomaly-unscaled",), list(y.columns))
            ),
        )
        data = data.join(unscaled_abs_diff)
        data["total-anomaly-unscaled"] = np.square(
            data["tag-anomaly-unscaled"]
        ).mean(axis=1)

        if self.window is not None and self.smoothing_method is not None:
            smooth_scaled = self._smoothing(tag_anomaly_scaled)
            smooth_scaled.columns = smooth_scaled.columns.set_levels(
                ["smooth-tag-anomaly-scaled"], level=0
            )
            data = data.join(smooth_scaled)
            data["smooth-total-anomaly-scaled"] = self._smoothing(
                data["total-anomaly-scaled"]
            )
            smooth_unscaled = self._smoothing(unscaled_abs_diff)
            smooth_unscaled.columns = smooth_unscaled.columns.set_levels(
                ["smooth-tag-anomaly-unscaled"], level=0
            )
            data = data.join(smooth_unscaled)
            data["smooth-total-anomaly-unscaled"] = self._smoothing(
                data["total-anomaly-unscaled"]
            )

        if hasattr(self, "feature_thresholds_") and self.feature_thresholds_ is not None:
            confidence = unscaled_abs_diff.values / np.asarray(
                self.feature_thresholds_.values, dtype=float
            )
            data = data.join(
                pd.DataFrame(
                    confidence,
                    index=unscaled_abs_diff.index,
                    columns=pd.MultiIndex.from_product(
                        (("anomaly-confidence",), data["model-output"].columns)
                    ),
                )
            )

        if hasattr(self, "aggregate_threshold_") and self.aggregate_threshold_ is not None:
            data["total-anomaly-confidence"] = (
                data["total-anomaly-scaled"] / self.aggregate_threshold_
            )

        if self.require_thresholds and not any(
            hasattr(self, attr)
            for attr in ("feature_thresholds_", "aggregate_threshold_")
        ):
            raise AttributeError(
                f"`require_thresholds={self.require_thresholds}` however "
                "`.cross_validate` was not called to calculate thresholds "
                "before `.anomaly`"
            )
        return data


class DiffBasedKFCVAnomalyDetector(DiffBasedAnomalyDetector):
    def __init__(
        self,
        base_estimator: Optional[BaseEstimator] = None,
        scaler: Optional[TransformerMixin] = None,
        require_thresholds: bool = True,
        shuffle: bool = True,
        window: int = 144,
        smoothing_method: str = "smm",
        threshold_percentile: float = 0.99,
    ):
        """
        KFold(5, shuffled) variant: thresholds are the
        ``threshold_percentile`` quantile of smoothed validation errors.
        """
        super().__init__(
            base_estimator=base_estimator,
            scaler=scaler,
            require_thresholds=require_thresholds,
            shuffle=shuffle,
            window=window,
            smoothing_method=smoothing_method,
        )
        self.threshold_percentile = threshold_percentile

    def get_params(self, deep: bool = True) -> dict:
        return {
            "base_estimator": self.base_estimator,
            "scaler": self.scaler,
            "window": self.window,
            "smoothing_method": self.smoothing_method,
            "shuffle": self.shuffle,
            "threshold_percentile": self.threshold_percentile,
        }

    def get_metadata(self) -> dict:
        metadata = {}
        if hasattr(self, "feature_thresholds_"):
            metadata["feature-thresholds"] = self.feature_thresholds_.tolist()
        if hasattr(self, "aggregate_threshold_"):
            metadata["aggregate-threshold"] = self.aggregate_threshold_
        if isinstance(self.base_estimator, GordoBase):
            metadata.update(self.base_estimator.get_metadata())
        else:
            metadata.update(
                {
                    "scaler": str(self.scaler),
                    "base_estimator": str(self.base_estimator),
                    "shuffle": self.shuffle,
                    "window": self.window,
                    "smoothing-method": self.smoothing_method,
                    "threshold-percentile": self.threshold_percentile,
                }
            )
        return metadata

    def cross_validate(
        self,
        *,
        X: Union[pd.DataFrame, np.ndarray],
        y: Union[pd.DataFrame, np.ndarray],
        cv=None,
        **kwargs,
    ):
        if cv is None:
            cv = KFold(n_splits=5, shuffle=True, random_state=0)
        kwargs.update(dict(return_estimator=True, cv=cv))
        cv_output = sklearn_cross_validate(self, X=X, y=y, **kwargs)

        y = pd.DataFrame(y)
        y_pred = pd.DataFrame(
            np.zeros_like(y, dtype=float), index=y.index, columns=y.columns
        )
        y_val_mse = pd.Series(np.full(len(y), np.nan), index=y.index)

        for (_, test_idxs), fold_model in zip(
            kwargs["cv"].split(X, y), cv_output["estimator"]
        ):
            X_test = (
                X.iloc[test_idxs].to_numpy()
                if isinstance(X, pd.DataFrame)
                else X[test_idxs]
            )
            y_pred.iloc[test_idxs] = fold_model.predict(X_test)
            y_val_mse.iloc[test_idxs] = self._scaled_mse_per_timestep(
                fold_model, y.iloc[test_idxs], y_pred.iloc[test_idxs]
            ).to_numpy()

        self.aggregate_threshold_ = float(self._calculate_threshold(y_val_mse))
        self.feature_thresholds_ = self._calculate_feature_thresholds(y, y_pred)
        return cv_output

    def _calculate_feature_thresholds(self, y_true, y_pred):
        return self._calculate_threshold(self._absolute_error(y_true, y_pred))

    def _calculate_threshold(self, validation_metric):
        return self._smoothing(validation_metric).quantile(self.threshold_percentile)
