"""
Scikit-learn-compatible JAX estimators — the drop-in replacements for the
reference's Keras wrappers (gordo/machine/model/models.py:36-710).

API parity: ``kind`` factory resolution (registered name or dotted path),
``from_definition``/``into_definition`` hooks, ``supported_fit_args``
filtering, fit-history metadata, pickling of a *fitted* model, and the LSTM
output-offset contract. Engine: specs + the fused JAX training program in
models/training.py — there is no per-model Python training loop to port.
"""

import abc
import importlib
import logging
from copy import copy, deepcopy
from importlib.util import find_spec
from pprint import pformat
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import numpy as np
import pandas as pd
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.exceptions import NotFittedError
from sklearn.metrics import explained_variance_score

from .. import serializer
from ..ops.windows import sliding_windows, window_targets
from .base import GordoBase
from .register import register_model_builder
from .spec import ModelSpec, Sequential
from .training import (
    History,
    fit_config_from_kwargs,
    fit_single,
    fit_single_segmented,
    predict_fn,
    segmented_config,
    split_fit_kwargs,
)

logger = logging.getLogger(__name__)


class JaxBaseEstimator(GordoBase, BaseEstimator):
    """
    Base estimator: resolves ``kind`` to an architecture factory, trains via
    the fused JAX engine, and exposes the GordoBase + sklearn surface.
    """

    # Keras fit args honored by configs written for the reference
    # (gordo/machine/model/models.py:37-51). Args that have no JAX analog
    # (workers, multiprocessing, queue sizes) are accepted and ignored.
    supported_fit_args = [
        "batch_size",
        "epochs",
        "verbose",
        "callbacks",
        "validation_split",
        "shuffle",
        "class_weight",
        "initial_epoch",
        "steps_per_epoch",
        "validation_batch_size",
        "max_queue_size",
        "workers",
        "use_multiprocessing",
    ]

    def __init__(self, kind: Union[str, Callable, dict], **kwargs) -> None:
        self.kind = self.load_kind(kind)
        self.kwargs: Dict[str, Any] = kwargs
        self._history: Optional[History] = None
        self.params_ = None
        self.spec_: Optional[ModelSpec] = None

    # -- kind resolution ----------------------------------------------------

    @staticmethod
    def parse_module_path(module_path: str) -> Tuple[Optional[str], str]:
        parts = module_path.split(".")
        if len(parts) == 1:
            return None, parts[0]
        return ".".join(parts[:-1]), parts[-1]

    def _factory_registry_type(self) -> str:
        for klass in type(self).__mro__:
            if klass.__name__ in register_model_builder.factories:
                return klass.__name__
        return type(self).__name__

    def load_kind(self, kind):
        if callable(kind):
            register_model_builder(type=type(self).__name__)(kind)
            return kind.__name__
        module_name, attr_name = self.parse_module_path(kind)
        if module_name is None:
            registry = register_model_builder.factories.get(
                self._factory_registry_type(), {}
            )
            if attr_name not in registry:
                raise ValueError(
                    f"kind: {kind} is not an available model for type: "
                    f"{type(self).__name__}!"
                )
        else:
            try:
                found = find_spec(module_name)
            except ModuleNotFoundError:
                found = None
            if not found:
                raise ValueError(f"kind: {kind}, unable to find module: {module_name!r}")
        return kind

    def _resolve_factory(self) -> Callable:
        module_name, attr_name = self.parse_module_path(self.kind)
        if module_name is None:
            return register_model_builder.factories[self._factory_registry_type()][
                self.kind
            ]
        module = importlib.import_module(module_name)
        if not hasattr(module, attr_name):
            raise ValueError(
                f"kind: {self.kind}, unable to find {attr_name} in module "
                f"{module_name!r}"
            )
        return getattr(module, attr_name)

    # -- serializer hooks ---------------------------------------------------

    @classmethod
    def from_definition(cls, definition: dict):
        definition = copy(definition)
        kind = definition.pop("kind")
        return cls(kind, **definition)

    def into_definition(self) -> dict:
        definition = copy(self.kwargs)
        definition["kind"] = self.kind
        return definition

    @classmethod
    def extract_supported_fit_args(cls, kwargs: dict) -> dict:
        return {k: kwargs[k] for k in cls.supported_fit_args if k in kwargs}

    @property
    def sk_params(self) -> dict:
        """kwargs with any definition-form fit args (e.g. callbacks) built."""
        fit_args = self.extract_supported_fit_args(self.kwargs)
        if fit_args:
            kwargs = deepcopy(self.kwargs)
            kwargs.update(serializer.load_params_from_definition(fit_args))
            return kwargs
        return self.kwargs

    # -- fitting ------------------------------------------------------------

    @staticmethod
    def get_n_features(X) -> int:
        if X.ndim < 2:
            raise ValueError(f"Unsupported input dimensionality {X.ndim}")
        return X.shape[-1]

    def _build_spec(self, factory_kwargs: dict) -> ModelSpec:
        factory = self._resolve_factory()
        spec = factory(**factory_kwargs)
        if not isinstance(spec, ModelSpec):
            raise TypeError(
                f"Factory {self.kind!r} returned {type(spec).__name__}, "
                "expected a ModelSpec"
            )
        return spec

    def fit(self, X, y, **kwargs):
        if isinstance(y, np.ndarray) and y.ndim == 1:
            y = y.reshape(-1, 1)
        X = X.values if isinstance(X, (pd.DataFrame, pd.Series)) else np.asarray(X)
        y = y.values if isinstance(y, (pd.DataFrame, pd.Series)) else np.asarray(y)

        self.kwargs.update(
            {"n_features": self.get_n_features(X), "n_features_out": self.get_n_features(y)}
        )

        all_kwargs = {**self.sk_params, **kwargs}
        fit_kwargs, factory_kwargs = split_fit_kwargs(all_kwargs)
        self.spec_ = self._build_spec(factory_kwargs)
        config, host_callbacks = fit_config_from_kwargs(fit_kwargs)
        seed = int(fit_kwargs.get("seed", 42))
        self.params_, self._history = fit_single(
            self.spec_,
            np.asarray(X, np.float32),
            np.asarray(y, np.float32),
            config,
            seed=seed,
            host_callbacks=host_callbacks,
        )
        return self

    def predict(self, X, **kwargs) -> np.ndarray:
        if self.params_ is None:
            raise NotFittedError(f"This {type(self).__name__} has not been fitted yet.")
        X = X.values if isinstance(X, pd.DataFrame) else np.asarray(X)
        out = predict_fn(self.spec_)(self.params_, np.asarray(X, np.float32))
        return np.asarray(out)

    def score(self, X, y, sample_weight=None, **kwargs) -> float:
        out = self.predict(X)
        y = y.values if isinstance(y, pd.DataFrame) else np.asarray(y)
        return explained_variance_score(y[-len(out):], out)

    # -- params / metadata / pickling --------------------------------------

    def get_params(self, deep: bool = False) -> dict:
        params = {"kind": self.kind}
        params.update(self.kwargs)
        if params.get("callbacks") and any(
            isinstance(cb, dict) for cb in params["callbacks"]
        ):
            params["callbacks"] = serializer.build_callbacks(params["callbacks"])
        return params

    def get_metadata(self) -> dict:
        if self._history is not None:
            history: Dict[str, Any] = dict(self._history.history)
            history["params"] = self._history.params
            return {"history": history}
        return {}

    def __getstate__(self):
        state = self.__dict__.copy()
        if state.get("params_") is not None:
            state["params_"] = jax.tree_util.tree_map(
                # gt-lint: disable=jax-device-sync -- pickling fetch on the
                # serialization path, not timed device work; no span exists
                lambda a: np.asarray(a), jax.device_get(state["params_"])
            )
        return state

    def __setstate__(self, state):
        self.__dict__ = state
        return self

    def __repr__(self):
        return f"{type(self).__name__}(kind={self.kind!r})"


class JaxAutoEncoder(JaxBaseEstimator, TransformerMixin):
    """
    Feedforward autoencoder: fits X→y (usually y=X); scores with explained
    variance of the reconstruction (reference:
    gordo/machine/model/models.py:360-398).
    """

    def score(self, X, y, sample_weight=None, **kwargs) -> float:
        if self.params_ is None:
            raise NotFittedError(f"This {type(self).__name__} has not been fitted yet.")
        out = self.predict(X)
        y = y.values if isinstance(y, pd.DataFrame) else np.asarray(y)
        return explained_variance_score(y, out)

    def transform(self, X) -> np.ndarray:
        return self.predict(X)


class JaxLSTMBaseEstimator(JaxBaseEstimator, TransformerMixin, metaclass=abc.ABCMeta):
    """
    Many-to-one LSTM over sliding windows. Output is ``lookback_window +
    lookahead - 1`` rows shorter than the input — the model-offset contract
    that threads through builder metadata and server alignment (reference:
    gordo/machine/model/models.py:463-698).
    """

    def __init__(
        self,
        kind: Union[Callable, str],
        lookback_window: int = 1,
        batch_size: int = 32,
        **kwargs,
    ) -> None:
        kwargs["lookback_window"] = lookback_window
        kwargs["batch_size"] = batch_size
        self.lookback_window = lookback_window
        self.batch_size = batch_size
        super().__init__(kind, **kwargs)

    @property
    @abc.abstractmethod
    def lookahead(self) -> int:
        """Steps ahead in y the model targets."""

    def get_metadata(self) -> dict:
        metadata = super().get_metadata()
        metadata.update({"forecast_steps": self.lookahead})
        return metadata

    def _validate_and_fix_size_of_X(self, X: np.ndarray) -> np.ndarray:
        if X.ndim == 1:
            X = X.reshape(len(X), 1)
        if self.lookback_window >= X.shape[0]:
            raise ValueError(
                f"For {type(self).__name__} lookback_window must be < size of X"
            )
        return X

    def fit(self, X, y, **kwargs):
        X = X.values if isinstance(X, pd.DataFrame) else np.asarray(X)
        y = y.values if isinstance(y, pd.DataFrame) else np.asarray(y)
        if y.ndim == 1:
            y = y.reshape(-1, 1)
        X = self._validate_and_fix_size_of_X(X)

        targets = window_targets(y, self.lookback_window, self.lookahead)

        self.kwargs.update(
            {"n_features": X.shape[1], "n_features_out": y.shape[1]}
        )
        all_kwargs = {**self.sk_params, **kwargs}
        # Time-series training never shuffles between epochs (reference fits
        # its generator with shuffle=False — models.py:613-615).
        all_kwargs["shuffle"] = False
        fit_kwargs, factory_kwargs = split_fit_kwargs(all_kwargs)
        self.spec_ = self._build_spec(factory_kwargs)
        config, host_callbacks = fit_config_from_kwargs(fit_kwargs)
        seed = int(fit_kwargs.get("seed", 42))

        # Opt-in segmented (stateful-scan) training — same env knob as the
        # fleet path: the raw series goes to the device and the host never
        # materializes the lookback× window blowup. Host callbacks need
        # the per-epoch loop, which only the dense program provides;
        # ineligible fits fall through silently.
        segments = segmented_config()
        if (
            segments
            and not host_callbacks
            and config.batch_size % segments == 0
            and len(targets) >= config.batch_size
        ):
            self.params_, self._history = fit_single_segmented(
                self.spec_,
                X,
                targets,
                config,
                seed=seed,
                segments=segments,
            )
            return self

        windows = sliding_windows(X, self.lookback_window, self.lookahead)
        self.params_, self._history = fit_single(
            self.spec_,
            np.asarray(windows, np.float32),
            np.asarray(targets, np.float32),
            config,
            seed=seed,
            host_callbacks=host_callbacks,
        )
        return self

    def predict(self, X, **kwargs) -> np.ndarray:
        if self.params_ is None:
            raise NotFittedError(f"This {type(self).__name__} has not been fitted yet.")
        X = X.values if isinstance(X, pd.DataFrame) else np.asarray(X)
        X = self._validate_and_fix_size_of_X(X)

        from ..parallel.sequence import ring_predict_enabled, ring_windowed_predict

        if ring_predict_enabled(len(X)):
            # Long series: shard the time axis over the devices and exchange
            # window halos over ICI (parallel/sequence.py) — the host never
            # materializes the lookback× window blowup.
            return ring_windowed_predict(
                predict_fn(self.spec_),
                self.params_,
                np.asarray(X, np.float32),
                self.lookback_window,
                self.lookahead,
            )
        windows = sliding_windows(X, self.lookback_window, self.lookahead)
        out = predict_fn(self.spec_)(self.params_, np.asarray(windows, np.float32))
        return np.asarray(out)

    def score(self, X, y, sample_weight=None, **kwargs) -> float:
        if self.params_ is None:
            raise NotFittedError(f"This {type(self).__name__} has not been fitted yet.")
        out = self.predict(X)
        y = y.values if isinstance(y, pd.DataFrame) else np.asarray(y)
        return explained_variance_score(y[-len(out):], out)

    def transform(self, X) -> np.ndarray:
        return self.predict(X)


class JaxLSTMForecast(JaxLSTMBaseEstimator):
    @property
    def lookahead(self) -> int:
        return 1


class JaxLSTMAutoEncoder(JaxLSTMBaseEstimator):
    @property
    def lookahead(self) -> int:
        return 0


class JaxRawModelRegressor(JaxAutoEncoder):
    """
    Estimator from a raw ``{spec: ..., compile: ...}`` config — the analog of
    KerasRawModelRegressor (gordo/machine/model/models.py:401-460): ``spec``
    holds a Sequential layer-list definition, ``compile`` the loss/optimizer.
    """

    _expected_keys = ("spec", "compile")

    def load_kind(self, kind):
        return kind

    def __repr__(self):
        return f"{type(self).__name__}(kind: {pformat(self.kind)})"

    def _build_spec(self, factory_kwargs: dict) -> ModelSpec:
        if not all(k in self.kind for k in self._expected_keys):
            raise ValueError(
                f"Expected spec to have keys: {self._expected_keys}, "
                f"but found {list(self.kind)}"
            )
        sequential = serializer.from_definition(self.kind["spec"])
        if not isinstance(sequential, Sequential):
            raise ValueError(
                f"Raw spec must describe a Sequential stack, got {type(sequential)}"
            )
        compile_kwargs = dict(self.kind.get("compile") or {})
        sequential.loss = compile_kwargs.get("loss", sequential.loss)
        optimizer = compile_kwargs.get("optimizer", sequential.optimizer)
        sequential.optimizer = (
            optimizer.capitalize() if isinstance(optimizer, str) else optimizer
        )
        return sequential.compile_spec(n_features=factory_kwargs["n_features"])
