"""
Named functions referencable from configs in ``FunctionTransformer`` steps
(reference: gordo/machine/model/transformer_funcs/general.py).
"""


def multiply_by(X, factor):
    """
    Multiply the input by ``factor``.

    >>> import numpy as np
    >>> multiply_by(np.array([1.0, 2.0]), 2).tolist()
    [2.0, 4.0]
    """
    return X * factor
