from . import factories  # noqa: F401  (populates the factory registry)
from .base import GordoBase
from .callbacks import Callback, EarlyStopping
from .estimators import (
    JaxAutoEncoder,
    JaxBaseEstimator,
    JaxLSTMAutoEncoder,
    JaxLSTMBaseEstimator,
    JaxLSTMForecast,
    JaxRawModelRegressor,
)
from .register import register_model_builder
from .spec import (
    Dense,
    FeedForwardSpec,
    LSTMSpec,
    ModelSpec,
    OptimizerSpec,
    Sequential,
)

# Migration aliases: reference configs name the Keras classes; resolving them
# here lets `gordo.machine.model.models.Keras*` paths rewritten by the
# serializer's COMPAT_LOCATIONS (and direct `gordo_tpu.models.Keras*` paths)
# work unchanged.
KerasAutoEncoder = JaxAutoEncoder
KerasLSTMAutoEncoder = JaxLSTMAutoEncoder
KerasLSTMForecast = JaxLSTMForecast
KerasRawModelRegressor = JaxRawModelRegressor

__all__ = [
    "GordoBase",
    "register_model_builder",
    "JaxBaseEstimator",
    "JaxAutoEncoder",
    "JaxLSTMBaseEstimator",
    "JaxLSTMAutoEncoder",
    "JaxLSTMForecast",
    "JaxRawModelRegressor",
    "KerasAutoEncoder",
    "KerasLSTMAutoEncoder",
    "KerasLSTMForecast",
    "KerasRawModelRegressor",
    "ModelSpec",
    "FeedForwardSpec",
    "LSTMSpec",
    "OptimizerSpec",
    "Sequential",
    "Dense",
    "Callback",
    "EarlyStopping",
]
